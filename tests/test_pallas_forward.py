"""Fully-fused Pallas forward (ops/pallas_forward.py) vs the XLA paths.

All kernel launches run under ``interpret=True`` (Pallas CPU interpreter);
the real-chip compile + timing happens in bench.py config 3c.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mano_hand_tpu.models import core
from mano_hand_tpu.ops import pallas_forward

TOL = 1e-4


@pytest.fixture(scope="module")
def params32(params):
    return params.astype(np.float32)


def _rand(b, seed=0):
    rng = np.random.default_rng(seed)
    pose = rng.normal(scale=0.6, size=(b, 16, 3)).astype(np.float32)
    beta = rng.normal(size=(b, 10)).astype(np.float32)
    return jnp.asarray(pose), jnp.asarray(beta)


def test_matches_forward_batched(params32):
    pose, beta = _rand(6)
    want = core.forward_batched(params32, pose, beta).verts
    got = pallas_forward.forward_verts_fused(
        params32, pose, beta, block_b=4, interpret=True
    )
    assert got.shape == want.shape
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_ragged_batch_and_flat_pose(params32):
    # B=5 is not a multiple of block_b=4: the pad/slice path must be exact,
    # and [B, 48] flat poses must behave like [B, 16, 3].
    pose, beta = _rand(5, seed=1)
    want = core.forward_batched(params32, pose, beta).verts
    got = pallas_forward.forward_verts_fused(
        params32, pose.reshape(5, 48), beta, block_b=4, interpret=True
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_empty_batch(params32):
    pose, beta = _rand(0)
    got = pallas_forward.forward_verts_fused(
        params32, pose, beta, interpret=True
    )
    assert got.shape == (0, params32.v_template.shape[0], 3)


def test_zero_pose_is_rest_mesh(params32):
    # At theta=0 every rotation is I: the pose corrective vanishes and the
    # kernel must reproduce the shaped rest mesh (mano_np.py:87-91 quirk).
    beta = jnp.asarray(
        np.random.default_rng(2).normal(size=(3, 10)), jnp.float32
    )
    pose = jnp.zeros((3, 16, 3), jnp.float32)
    want = core.forward_batched(params32, pose, beta).verts
    got = pallas_forward.forward_verts_fused(
        params32, pose, beta, block_b=8, interpret=True
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_vjp_matches_xla_grad(params32):
    pose, beta = _rand(4, seed=3)
    targets = core.forward_batched(params32, pose, beta).verts

    def loss_ref(p, s):
        v = core.forward_batched(params32, p, s).verts
        return ((v - targets) ** 2).sum()

    def loss_fused(p, s):
        v = pallas_forward.forward_verts_fused_ad(
            params32, p, s, jax.lax.Precision.HIGHEST, 4, True
        )
        return ((v - targets) ** 2).sum()

    p2, b2 = _rand(4, seed=4)
    gp_ref, gs_ref = jax.grad(loss_ref, argnums=(0, 1))(p2, b2)
    gp, gs = jax.grad(loss_fused, argnums=(0, 1))(p2, b2)
    # Relative tolerance: gradients scale with vertex count.
    def close(a, b):
        a, b = np.asarray(a), np.asarray(b)
        denom = max(1.0, np.abs(b).max())
        return np.abs(a - b).max() / denom < 1e-4

    assert close(gp, gp_ref)
    assert close(gs, gs_ref)


def test_param_grads_match_xla(params32):
    # The hybrid VJP must produce REAL parameter cotangents (template,
    # bases, weights, regressor), not zeros — checked against autodiff of
    # the plain XLA path.
    pose, beta = _rand(3, seed=7)
    hi = jax.lax.Precision.HIGHEST

    def loss_ref(prm):
        return core.forward_batched(prm, pose, beta, precision=hi).verts.sum()

    def loss_fused(prm):
        return pallas_forward.forward_verts_fused_ad(
            prm, pose, beta, hi, 2, True
        ).sum()

    # allow_int: the faces leaf is integer-valued and gets float0 tangents.
    g_ref = jax.grad(loss_ref, allow_int=True)(params32)
    g_fused = jax.grad(loss_fused, allow_int=True)(params32)
    for name in ("v_template", "shape_basis", "pose_basis",
                 "lbs_weights", "j_regressor"):
        a = np.asarray(getattr(g_fused, name))
        b = np.asarray(getattr(g_ref, name))
        denom = max(1.0, np.abs(b).max())
        assert np.abs(a - b).max() / denom < 1e-4, name
        assert np.abs(b).max() > 0, f"{name}: reference grad trivially zero"


def test_grad_finite_at_zero_pose(params32):
    # theta=0 is the fitting init; the Taylor-guarded Rodrigues must keep
    # the fused path's gradients finite there too.
    pose = jnp.zeros((2, 16, 3), jnp.float32)
    beta = jnp.zeros((2, 10), jnp.float32)

    g = jax.grad(
        lambda p: pallas_forward.forward_verts_fused_ad(
            params32, p, beta, jax.lax.Precision.HIGHEST, 2, True
        ).sum()
    )(pose)
    assert np.isfinite(np.asarray(g)).all()


def test_chunked_fused_route(params32):
    # forward_chunked(use_pallas_fused=True) must agree with the XLA path,
    # including a ragged trailing chunk.
    pose, beta = _rand(10, seed=6)
    want = core.forward_batched(params32, pose, beta).verts
    got = core.forward_chunked(
        params32, pose, beta, chunk_size=4,
        use_pallas_fused=True, block_b=4, interpret=True,
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_split_hi_lo_xla_reconstruction_under_jit():
    # The XLA-level operand split must survive compilation: on TPU the
    # convert-based split compiles to lo == 0 (XLA folds the bf16->f32
    # convert pair), which silently degraded the HIGH path to single-pass
    # bf16. The bit-masked split is fold-proof; assert its reconstruction
    # captures the residual on whatever backend runs the suite.
    from mano_hand_tpu.ops.common import split_hi_lo_xla

    rng = np.random.default_rng(13)
    x = rng.normal(size=(64, 256)).astype(np.float32)
    hi, lo = jax.jit(split_hi_lo_xla)(jnp.asarray(x))
    assert np.abs(np.asarray(lo).astype(np.float32)).max() > 0
    rec = (np.asarray(hi).astype(np.float64)
           + np.asarray(lo).astype(np.float64))
    # bf16 rounding of lo bounds the residual: |x| <~ 4 here -> ~6e-5.
    assert np.abs(rec - x).max() < 1e-4


def test_jit_param_as_arg_parity(params32):
    # Params as TRACED jit arguments (the bench's timed context) — the
    # operand pre-split runs on-device through XLA, where the fold bug
    # lived; parity must hold there, not just with closed-over params.
    pose, beta = _rand(4, seed=14)
    fn = jax.jit(
        lambda prm, p, s: pallas_forward.forward_verts_fused(
            prm, p, s, block_b=4, interpret=True
        )
    )
    got = fn(params32, pose, beta)
    want = core.forward_batched(params32, pose, beta).verts
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_string_precision_canonicalized(params32):
    # JAX accepts 'high' anywhere Precision.HIGH is legal; the kernels must
    # canonicalize rather than silently fall through to single-pass bf16.
    pose, beta = _rand(2, seed=9)
    a = pallas_forward.forward_verts_fused(
        params32, pose, beta, precision="high", block_b=2, interpret=True
    )
    b = pallas_forward.forward_verts_fused(
        params32, pose, beta, precision=jax.lax.Precision.HIGH,
        block_b=2, interpret=True,
    )
    assert np.array_equal(np.asarray(a), np.asarray(b))


def test_jit_compiles(params32):
    pose, beta = _rand(4, seed=5)
    fn = jax.jit(
        lambda p, s: pallas_forward.forward_verts_fused(
            params32, p, s, block_b=4, interpret=True
        )
    )
    want = core.forward_batched(params32, pose, beta).verts
    got = jax.block_until_ready(fn(pose, beta))
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


# ------------------------------------------------- full-fusion kernel
def test_full_fusion_matches_forward_batched(params32):
    pose, beta = _rand(6, seed=3)
    want = core.forward_batched(params32, pose, beta).verts
    got = pallas_forward.forward_verts_fused_full(
        params32, pose, beta, block_b=4, interpret=True
    )
    assert got.shape == want.shape
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_full_fusion_ragged_flat_empty(params32):
    pose, beta = _rand(5, seed=4)
    want = core.forward_batched(params32, pose, beta).verts
    got = core.forward_batched_pallas_fused_full(
        params32, pose.reshape(5, 48), beta, block_b=4, interpret=True
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL
    empty = core.forward_batched_pallas_fused_full(
        params32, jnp.zeros((0, 16, 3)), jnp.zeros((0, 10)), interpret=True
    )
    assert empty.shape == (0, 778, 3)


def test_full_fusion_zero_pose_taylor_guard(params32):
    # theta = 0 exercises the in-kernel Taylor branch of Rodrigues.
    beta = jnp.asarray(
        np.random.default_rng(5).normal(size=(3, 10)).astype(np.float32)
    )
    want = core.forward_batched(params32, jnp.zeros((3, 16, 3)), beta).verts
    got = pallas_forward.forward_verts_fused_full(
        params32, jnp.zeros((3, 16, 3)), beta, block_b=4, interpret=True
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_full_fusion_vjp_matches_xla_grad(params32):
    pose, beta = _rand(4, seed=6)
    w = jnp.asarray(
        np.random.default_rng(7).normal(size=(4, 778, 3)).astype(np.float32)
    )

    def loss_full(po, sh):
        v = core.forward_batched_pallas_fused_full(
            params32, po, sh, block_b=4, interpret=True
        )
        return jnp.sum(v * w)

    def loss_ref(po, sh):
        return jnp.sum(core.forward_batched(params32, po, sh).verts * w)

    gp, gs = jax.grad(loss_full, argnums=(0, 1))(pose, beta)
    rp, rs = jax.grad(loss_ref, argnums=(0, 1))(pose, beta)
    assert np.abs(np.asarray(gp) - np.asarray(rp)).max() < 1e-3
    assert np.abs(np.asarray(gs) - np.asarray(rs)).max() < 1e-3


def test_full_fusion_chunked_route(params32):
    pose, beta = _rand(10, seed=8)
    want = core.forward_batched(params32, pose, beta).verts
    got = core.forward_chunked(
        params32, pose, beta, chunk_size=4, use_pallas_fused_full=True,
        block_b=4, interpret=True,
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_level_layout_mano_and_segment_split():
    from mano_hand_tpu.constants import MANO_PARENTS

    perm, levels = pallas_forward.level_layout(tuple(MANO_PARENTS))
    assert perm[0] == 0 and sorted(perm) == list(range(16))
    # MANO stays the whole-level layout: one segment per level — the
    # generalization must not change the compiled MANO program.
    assert [lv[1] for lv in levels] == [5, 5, 5]
    # L1 shares the root parent (broadcast); deeper levels pair 1:1.
    assert levels[0][3] == 1 and levels[1][3] == 5
    assert levels == ((1, 5, 0, 1), (6, 5, 1, 5), (11, 5, 6, 5))

    # Two level-2 parents with uneven child counts (1 has two children,
    # 2 has one): neither one-shared-parent nor one-to-one as a whole —
    # the level SPLITS into a broadcast segment and a singleton.
    perm2, segs2 = pallas_forward.level_layout((-1, 0, 0, 1, 2, 1))
    assert sorted(perm2) == list(range(6))
    # perm: [0, 1, 2, {3,5}(parent 1), 4(parent 2)]
    assert perm2 == (0, 1, 2, 3, 5, 4)
    assert segs2 == ((1, 2, 0, 1), (3, 2, 1, 1), (5, 1, 2, 1))


def test_full_fusion_shared_parent_inside_wide_level():
    """A level whose single shared parent sits INSIDE a multi-joint
    previous level (here: joints 3,4 both children of joint 1, while
    level 1 is {1, 2}) must compose against that parent's lane — not
    pair elementwise with the whole previous level."""
    import dataclasses

    from mano_hand_tpu.assets import synthetic_params

    base = synthetic_params(seed=11, n_verts=97, n_joints=5, n_shape=4,
                            n_faces=60)
    p32 = dataclasses.replace(base, parents=(-1, 0, 0, 1, 1)).astype(
        np.float32
    )
    rng = np.random.default_rng(12)
    pose = jnp.asarray(rng.normal(scale=0.5, size=(3, 5, 3)), jnp.float32)
    beta = jnp.asarray(rng.normal(size=(3, 4)), jnp.float32)
    want = core.forward_batched(p32, pose, beta).verts
    got = pallas_forward.forward_verts_fused_full(
        p32, pose, beta, block_b=2, interpret=True
    )
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL


def test_full_fusion_hands_single_launch(params32):
    """Two-hand single-launch kernel == per-hand full-fusion kernels ==
    the XLA forward_hands path, on distinct L/R assets."""
    import dataclasses

    left = params32

    right = dataclasses.replace(
        params32,
        v_template=np.asarray(params32.v_template) * 1.05,
        side="right" if params32.side == "left" else "left",
    )
    stacked = core.stack_params(left, right)
    pose, beta = _rand(6, seed=9)
    pose2 = jnp.stack([pose, pose * 0.5])
    beta2 = jnp.stack([beta, -beta])

    want = core.forward_hands(stacked, pose2, beta2).verts
    got = core.forward_hands_pallas_fused_full(
        stacked, pose2, beta2, block_b=4, interpret=True
    )
    assert got.shape == want.shape
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < TOL
    # Per-hand agreement with the one-hand kernel (same compute core).
    for h, prm in ((0, left), (1, right)):
        one = pallas_forward.forward_verts_fused_full(
            prm, pose2[h], beta2[h], block_b=4, interpret=True
        )
        assert np.abs(np.asarray(got[h]) - np.asarray(one)).max() < 1e-6

    # Flat [2, B, 48] poses normalize like the one-hand API's [B, 48].
    flat = core.forward_hands_pallas_fused_full(
        stacked, pose2.reshape(2, 6, 48), beta2, block_b=4,
        interpret=True)
    assert np.abs(np.asarray(flat) - np.asarray(got)).max() == 0.0

    with pytest.raises(ValueError, match="pose must be"):
        core.forward_hands_pallas_fused_full(
            stacked, pose, beta, interpret=True)


def test_full_fusion_stack_skin_parity(params32):
    """stack_skin batches each coordinate's four K=16 skin dots into one
    [4*TB, J] dot — identical per-row math, so interpret-mode results
    must match the unstacked path to float tolerance, one-hand and
    two-hand (LOCKSTEP pair), plus the VJP route."""
    pose, beta = _rand(6, seed=11)
    base = pallas_forward.forward_verts_fused_full(
        params32, pose, beta, block_b=4, interpret=True
    )
    stacked = pallas_forward.forward_verts_fused_full(
        params32, pose, beta, block_b=4, interpret=True, stack_skin=True
    )
    assert np.abs(np.asarray(stacked) - np.asarray(base)).max() < 1e-6

    # The non-split branch too (DEFAULT precision skips the hi/lo split;
    # its stack_skin slicing is a separate code path), and the 12-way
    # "full" stacking in both precision branches.
    base_d = pallas_forward.forward_verts_fused_full(
        params32, pose, beta, precision="default", block_b=4, interpret=True
    )
    for variant in (True, "full"):
        stacked_d = pallas_forward.forward_verts_fused_full(
            params32, pose, beta, precision="default", block_b=4,
            interpret=True, stack_skin=variant
        )
        assert np.abs(np.asarray(stacked_d) - np.asarray(base_d)).max() < 1e-6
    full12 = pallas_forward.forward_verts_fused_full(
        params32, pose, beta, block_b=4, interpret=True, stack_skin="full"
    )
    assert np.abs(np.asarray(full12) - np.asarray(base)).max() < 1e-6

    two = core.stack_params(params32, params32)
    pose_h = jnp.stack([pose, pose])
    beta_h = jnp.stack([beta, beta])
    base_h = core.forward_hands_pallas_fused_full(
        two, pose_h, beta_h, block_b=4, interpret=True
    )
    for variant in (True, "full"):
        stacked_h = core.forward_hands_pallas_fused_full(
            two, pose_h, beta_h, block_b=4, interpret=True,
            stack_skin=variant
        )
        assert np.abs(np.asarray(stacked_h) - np.asarray(base_h)).max() \
            < 1e-6

    # The hybrid VJP is unchanged by the forward's pass ordering.
    w = jnp.asarray(
        np.random.default_rng(12).normal(size=(6, 778, 3)).astype(np.float32)
    )

    def loss(p, b, ss):
        v = core.forward_batched_pallas_fused_full(
            params32, p, b, block_b=4, interpret=True, stack_skin=ss
        )
        return jnp.sum(v * w)

    g0 = jax.grad(loss, argnums=(0, 1))(pose, beta, False)
    for variant in (True, "full"):
        g1 = jax.grad(loss, argnums=(0, 1))(pose, beta, variant)
        for a, b_ in zip(g0, g1):
            assert np.abs(np.asarray(a) - np.asarray(b_)).max() < 1e-6
