"""A REAL two-process jax.distributed group over a local TCP coordinator.

`tests/test_parallel.py::test_multihost_helpers_single_process` covers the
degraded single-process path; until round 5 the n_proc>1 branches of
`parallel/multihost.py` (explicit-coordinator initialize, process slicing,
cross-process batch assembly) had never executed anywhere (VERDICT r4 weak
#5). This spawns two worker processes with 2 virtual CPU devices each —
gloo collectives carry the cross-process all-reduce — and checks the
branches with `jax.process_count() == 2` for real.

Reference parity: the reference has no distributed machinery (SURVEY §2.2);
this is the TPU-native NCCL/MPI-equivalent bootstrap, tested clusterless.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).parent.parent

# Backend-capability gate (PR 6 satellite): some jaxlib builds ship the
# gloo *bindings* but a CPU client whose collectives still raise
# "Multiprocess computations aren't implemented on the CPU backend" at
# execution time (this container since PR 5). That is a missing backend
# capability, not a regression in parallel/multihost.py — convert
# exactly that error into a skip so tier-1 is honest instead of
# known-red, while any OTHER worker failure still fails the test.
_CPU_MULTIPROC_UNSUPPORTED = (
    "Multiprocess computations aren't implemented on the CPU backend")

_WORKER = """
import json, sys
root, port, pid, out = sys.argv[1], sys.argv[2], int(sys.argv[3]), sys.argv[4]
sys.path.insert(0, root)
import jax
jax.config.update("jax_platforms", "cpu")  # wins over the axon site hook
import numpy as np
from mano_hand_tpu.parallel import multihost

is_multi = multihost.initialize(f"localhost:{port}", 2, pid)
mesh = multihost.global_mesh()  # all-data-parallel over both procs' devices
gb = 8
sl = multihost.process_local_slice(gb, mesh)
full = np.arange(gb * 3, dtype=np.float32).reshape(gb, 3)
arr = multihost.global_batch_array(full[sl], mesh)
import jax.numpy as jnp
# Global reduction over the data-sharded array: XLA inserts the
# cross-process all-reduce (gloo on CPU) — the value only comes out right
# if assembly AND the collective both work.
total = float(jax.jit(jnp.sum)(arr))
json.dump({"is_multi": is_multi, "process_count": jax.process_count(),
           "pid": jax.process_index(), "n_devices": jax.device_count(),
           "local_devices": len(jax.local_devices()),
           "mesh_data": int(mesh.shape["data"]),
           "slice": [sl.start, sl.stop], "total": total,
           "expect": float(full.sum()),
           "shard_rows": sorted(s.data.shape[0]
                                for s in arr.addressable_shards)},
          open(out, "w"))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_group_end_to_end(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    # Fresh env: the conftest's 8-device XLA flag and any JAX_PLATFORMS
    # must not leak in (2 devices/process keeps the topology pinned).
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    # stderr to files (not pipes): a worker wedged in a collective must
    # not also deadlock the test on an undrained pipe; and kill BOTH on
    # any failure — an orphaned jax.distributed worker would spin on the
    # 1-core box for the rest of the session.
    err_files = [open(tmp_path / f"err{pid}.log", "w") for pid in (0, 1)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(ROOT), str(port), str(pid),
             str(tmp_path / f"out{pid}.json")],
            env=env, cwd=tmp_path,
            stdout=subprocess.DEVNULL, stderr=err_files[pid])
        for pid in (0, 1)
    ]
    try:
        for p in procs:
            p.wait(timeout=240)
    finally:
        for p in procs:
            p.kill()
        for f in err_files:
            f.close()
    for pid, p in enumerate(procs):
        err = (tmp_path / f"err{pid}.log").read_text()
        if p.returncode != 0 and _CPU_MULTIPROC_UNSUPPORTED in err:
            pytest.skip(
                "this jaxlib's CPU backend does not implement "
                "multiprocess collectives (gloo bindings present, "
                "runtime capability absent); the 2-process group "
                "bootstrap itself succeeded up to the first collective")
        assert p.returncode == 0, err[-2000:]

    outs = [json.loads((tmp_path / f"out{i}.json").read_text())
            for i in (0, 1)]
    for pid, o in enumerate(outs):
        assert o["is_multi"] is True
        assert o["process_count"] == 2
        assert o["pid"] == pid
        assert o["n_devices"] == 4 and o["local_devices"] == 2
        assert o["mesh_data"] == 4
        # Row-major process slicing: proc 0 loads [0,4), proc 1 [4,8).
        assert o["slice"] == [pid * 4, pid * 4 + 4]
        # Each process holds 2 addressable shards of 2 rows each.
        assert o["shard_rows"] == [2, 2]
        # The cross-process all-reduce saw every row exactly once.
        assert o["total"] == o["expect"] == 276.0
