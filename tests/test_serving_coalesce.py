"""Cross-subject coalescing (the PR-4 tentpole), CPU-verified.

The subject becomes a per-ROW runtime index instead of a per-batch
executable constant: every ``specialize``d subject lives in a row of a
device-resident ``core.SubjectTable``, and the engine's pose-only
dispatch is the GATHERED program ``core.forward_posed_gather`` — so
requests for different subjects coalesce into one dispatch. Everything
that matters is deterministic on CPU and pinned here:

* bit-identity — the gathered program's rows equal the per-subject
  posed program (``forward_posed_batched``) EXACTLY (f32 ``==``) at a
  matched batch size, for any subject mixture, any table capacity, and
  through the LIVE engine at awkward batch compositions;
* table mechanics — functional row writes (snapshots stay valid),
  capacity growth by doubling (counted; zero recompiles once grown),
  LRU eviction above ``max_subjects`` (counted; never a recompile, and
  an evicted subject transparently re-bakes on its next dispatch);
* coalescing policy — mixed-subject pose-only requests merge into one
  dispatch; full-path and pose-only requests never share one; overflow
  parks on ``_pending`` (counted) and still dispatches next.
"""

import collections

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mano_hand_tpu.models import core
from mano_hand_tpu.serving import (
    ServingEngine,
    bucket_for,
    pad_rows,
    subject_index_rows,
)

# quick: the seconds-scale `make check-quick` pre-commit lane. slow
# (PR 8): the tier-1 `-m 'not slow'` lane sat 8 s under its 870 s
# budget at PR-8 HEAD; canonical runner `make coalesce-smoke` (own
# pytest process + cache dir, in `make check`) — the test_coldstart
# precedent, which is also why `make test` already --ignore's it.
pytestmark = [pytest.mark.quick, pytest.mark.slow]


@pytest.fixture(scope="module")
def params32(params):
    return params.astype(np.float32)


def _betas(n, seed=3, scale=0.5):
    rng = np.random.default_rng(seed)
    return [rng.normal(scale=scale, size=10).astype(np.float32)
            for _ in range(n)]


def _poses(n, seed=0, scale=0.4):
    return np.random.default_rng(seed).normal(
        scale=scale, size=(n, 16, 3)).astype(np.float32)


def _prestuffed(eng, submits):
    """Submit every (pose, kwargs) pair with the dispatcher HELD OFF,
    then start it: the queue is drained in one _coalesce scan, so batch
    composition is deterministic (no timing races)."""
    orig_start = eng.start
    eng.start = lambda: eng          # hold the dispatcher
    try:
        futs = [eng.submit(p, **kw) for p, kw in submits]
    finally:
        eng.start = orig_start
    eng.start()
    return futs


# ---------------------------------------------------------- the gather op
def test_forward_posed_gather_bit_identical(params32):
    """THE acceptance criterion: at a matched batch size, every row of
    the gathered mixed-subject program equals the corresponding row of
    the per-subject posed program EXACTLY (f32 ==)."""
    betas = _betas(3)
    shaped = [core.jit_specialize(params32, jnp.asarray(b)) for b in betas]
    table = core.stack_shaped(shaped)
    poses = jnp.asarray(_poses(8, seed=11, scale=0.6))
    idx = np.random.default_rng(1).integers(0, 3, size=8).astype(np.int32)
    got = np.asarray(core.jit_forward_posed_gather(
        table, jnp.asarray(idx), poses).verts)
    for si in range(3):
        want = np.asarray(core.jit_forward_posed_batched(
            shaped[si], poses).verts)
        rows = np.where(idx == si)[0]
        np.testing.assert_array_equal(got[rows], want[rows],
                                      err_msg=f"subject {si}")


def test_table_mechanics_functional_and_grow(params32):
    betas = _betas(2, seed=7)
    shaped = [core.jit_specialize(params32, jnp.asarray(b)) for b in betas]
    t0 = core.subject_table(params32, capacity=2)
    t1 = core.jit_table_set_row(t0, 0, shaped[0])
    t2 = core.jit_table_set_row(t1, 1, shaped[1])
    # Functional: earlier snapshots are untouched by later writes.
    np.testing.assert_array_equal(np.asarray(t0.v_shaped[1]),
                                  np.zeros((778, 3), np.float32))
    np.testing.assert_array_equal(np.asarray(t1.v_shaped[1]),
                                  np.zeros((778, 3), np.float32))
    np.testing.assert_array_equal(np.asarray(t2.v_shaped[0]),
                                  np.asarray(shaped[0].v_shaped))
    # Capacity growth pads rows and changes NO gathered result.
    poses = jnp.asarray(_poses(5, seed=2))
    idx = jnp.asarray([0, 1, 0, 1, 1], jnp.int32)
    got = np.asarray(core.jit_forward_posed_gather(t2, idx, poses).verts)
    tbig = core.table_grow(t2, 8)
    got2 = np.asarray(core.jit_forward_posed_gather(tbig, idx, poses).verts)
    np.testing.assert_array_equal(got, got2)
    with pytest.raises(ValueError, match="shrink"):
        core.table_grow(t2, 1)
    # Row read-back round-trips.
    row = core.table_row(t2, 1)
    np.testing.assert_array_equal(np.asarray(row.joints),
                                  np.asarray(shaped[1].joints))
    # ... and the pytree survives jit as a runtime argument.
    t3 = jax.jit(lambda t: t)(t2)
    assert isinstance(t3, core.SubjectTable) and t3.capacity == 2


def test_subject_index_rows():
    idx = subject_index_rows([5, 2, 5], [1, 2, 3], 8)
    np.testing.assert_array_equal(idx, np.array([5, 2, 2, 5, 5, 5, 5, 5],
                                                np.int32))
    assert idx.dtype == np.int32
    with pytest.raises(ValueError, match="pair up"):
        subject_index_rows([1, 2], [1], 4)
    with pytest.raises(ValueError, match=">= 1 row"):
        subject_index_rows([1], [0], 4)
    with pytest.raises(ValueError, match="cannot pad"):
        subject_index_rows([1, 2], [3, 3], 4)


# ------------------------------------------------------- engine parity
def test_engine_mixed_subject_parity_awkward_compositions(params32):
    """Mixed-subject batches through the LIVE engine, composition held
    deterministic by pre-stuffing the queue: 1 subject, many subjects,
    interleaved full/pose-only, oversize — every future bit-identical
    to its per-subject posed reference at the dispatch bucket size."""
    rng = np.random.default_rng(23)
    betas = _betas(4, seed=23)
    with ServingEngine(params32, max_bucket=16, max_delay_s=0.0) as eng:
        keys = [eng.specialize(b) for b in betas]
        shaped = [core.jit_specialize(params32, jnp.asarray(b))
                  for b in betas]
        eng.warmup_posed()
        eng.warmup()

        # One batch, many subjects, awkward sizes 1+2+3+5 = 11 -> b16.
        sizes = [1, 2, 3, 5]
        poses = [rng.normal(scale=0.4, size=(n, 16, 3)).astype(np.float32)
                 for n in sizes]
        futs = _prestuffed(eng, [
            (p, {"subject": keys[i]}) for i, p in enumerate(poses)])
        assert eng.counters.dispatches == 0 or True  # dispatch is async
        bucket = bucket_for(sum(sizes), eng.buckets)
        for i, (p, f) in enumerate(zip(poses, futs)):
            got = f.result(timeout=60.0)
            want = np.asarray(core.jit_forward_posed_batched(
                shaped[i], jnp.asarray(pad_rows(p, bucket))).verts)
            np.testing.assert_array_equal(got, want[:p.shape[0]],
                                          err_msg=f"request {i}")
        assert eng.counters.mixed_subject_batches >= 1

        # Single-subject single request (the degenerate composition).
        p1 = rng.normal(scale=0.4, size=(3, 16, 3)).astype(np.float32)
        got = eng.forward(p1, subject=keys[0])
        want = np.asarray(core.jit_forward_posed_batched(
            shaped[0], jnp.asarray(pad_rows(p1, 4))).verts)[:3]
        np.testing.assert_array_equal(got, want)

        # Interleaved full-path and pose-only: kinds never share a
        # batch, every future resolves correctly.
        d0 = eng.counters.dispatches
        pf = rng.normal(scale=0.4, size=(2, 16, 3)).astype(np.float32)
        sf = rng.normal(size=(2, 10)).astype(np.float32)
        futs = _prestuffed(eng, [
            (pf, {}), (p1, {"subject": keys[1]}),
            (pf, {"shape": sf}), (p1, {"subject": keys[2]})])
        full_want = np.asarray(core.jit_forward_batched(
            params32, jnp.asarray(pf),
            jnp.zeros((2, 10), jnp.float32)).verts)
        np.testing.assert_array_equal(futs[0].result(timeout=60.0),
                                      full_want)
        full_want2 = np.asarray(core.jit_forward_batched(
            params32, jnp.asarray(pf), jnp.asarray(sf)).verts)
        np.testing.assert_array_equal(futs[2].result(timeout=60.0),
                                      full_want2)
        for i, k in ((1, 1), (3, 2)):
            got = futs[i].result(timeout=60.0)
            want = np.asarray(core.jit_forward_posed_batched(
                shaped[k], jnp.asarray(pad_rows(p1, 8))).verts)[:3]
            np.testing.assert_array_equal(got, want)
        # 2 pose-only requests (6 rows -> one b8 batch) + 2 full
        # requests (4 rows -> one b4 batch): exactly two dispatches.
        assert eng.counters.dispatches - d0 == 2

        # Oversize still refuses by name at submit.
        big = rng.normal(scale=0.4, size=(17, 16, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="exceeds the largest"):
            eng.submit(big, subject=keys[0])


def test_engine_overflow_parks_counts_and_dispatches(params32):
    """Genuine overflow: the overhang parks on _pending (counted) and
    leads the NEXT batch — nothing is lost, nothing starves."""
    rng = np.random.default_rng(31)
    betas = _betas(2, seed=31)
    with ServingEngine(params32, max_bucket=8, max_delay_s=0.0) as eng:
        keys = [eng.specialize(b) for b in betas]
        eng.warmup_posed()
        poses = [rng.normal(scale=0.4, size=(n, 16, 3)).astype(np.float32)
                 for n in (5, 2, 6)]          # 5+2 fit b8; 6 overflows
        futs = _prestuffed(eng, [
            (p, {"subject": keys[i % 2]}) for i, p in enumerate(poses)])
        for p, f in zip(poses, futs):
            assert f.result(timeout=60.0).shape == (p.shape[0], 778, 3)
        assert eng.counters.coalesce_overflows >= 1
        assert eng.counters.dispatches == 2


def test_engine_lru_eviction_and_rebake(params32):
    """Above max_subjects the LRU subject's row is evicted (counted,
    no recompile — the table is a runtime arg) and a later submit for
    it transparently re-bakes, still bit-correct."""
    betas = _betas(3, seed=41)
    rng = np.random.default_rng(41)
    with ServingEngine(params32, max_bucket=8, max_subjects=2) as eng:
        k0, k1 = eng.specialize(betas[0]), eng.specialize(betas[1])
        eng.warmup_posed()
        warm = eng.counters.compiles
        p = rng.normal(scale=0.4, size=(2, 16, 3)).astype(np.float32)
        eng.forward(p, subject=k0)     # k0 most recently USED
        k2 = eng.specialize(betas[2])  # evicts k1 (LRU)
        assert eng.counters.specializations_evicted == 1
        assert eng.counters.compiles == warm   # eviction != recompile
        with eng._exe_lock:
            assert k1 not in eng._subject_slots
            assert k1 in eng._subject_betas    # betas survive eviction
        # The evicted subject still serves: its row re-bakes at
        # dispatch (one more specialization, k0 evicted in turn).
        got = eng.forward(p, subject=k1)
        shaped1 = core.jit_specialize(params32, jnp.asarray(betas[1]))
        want = np.asarray(core.jit_forward_posed_batched(
            shaped1, jnp.asarray(pad_rows(p, 2))).verts)
        np.testing.assert_array_equal(got, want)
        assert eng.counters.specializations_evicted == 2
        assert eng.counters.compiles == warm
        assert eng.counters.specializations == 4  # 3 subjects + 1 rebake
        eng.forward(p, subject=k2)     # k2 still resident
    snap = eng.counters.snapshot()
    assert snap["specializations_evicted"] == 2
    assert snap["table_growths"] == 0  # capacity pinned by max_subjects


def test_engine_table_growth_counted_zero_steady_recompiles(params32):
    """Capacity doubles past the initial 8 rows: growths are counted,
    the warm gathered executables are rebuilt ONCE per growth (counted
    compiles), and steady traffic afterwards compiles nothing."""
    betas = _betas(9, seed=51)
    rng = np.random.default_rng(51)
    with ServingEngine(params32, max_bucket=8, max_subjects=64) as eng:
        keys = [eng.specialize(b) for b in betas[:8]]
        eng.warmup_posed([4, 8])
        warm = eng.counters.compiles
        assert eng.counters.table_growths == 0     # 8 fit the initial 8
        keys.append(eng.specialize(betas[8]))      # 9th: capacity 8->16
        assert eng.counters.table_growths == 1
        # The growth retraced the two warm gather buckets eagerly.
        assert eng.counters.compiles == warm + 2
        warm = eng.counters.compiles
        for seed in range(3):     # steady mixed traffic, warm buckets only
            for n in (3, 7):
                p = rng.normal(scale=0.4,
                               size=(n, 16, 3)).astype(np.float32)
                got = eng.forward(p, subject=keys[(seed * 3 + n) % 9])
                assert got.shape == (n, 778, 3)
        assert eng.counters.compiles == warm       # ZERO steady
        assert eng.counters.specializations_evicted == 0


def test_counters_snapshot_has_coalesce_fields():
    from mano_hand_tpu.utils.profiling import ServingCounters

    c = ServingCounters()
    snap = c.snapshot()
    for k in ("requests_dispatched", "mixed_subject_batches",
              "coalesce_overflows", "specializations_evicted",
              "table_growths", "coalesce_width_mean"):
        assert k in snap and snap[k] == 0 or snap[k] == 0.0
    c.count_dispatch(8, 6, requests=3, subjects=2)
    c.count_dispatch(4, 4, requests=1, subjects=1)
    c.count_overflow()
    c.count_evict()
    c.count_table_growth()
    snap = c.snapshot()
    assert snap["requests_dispatched"] == 4
    assert snap["mixed_subject_batches"] == 1
    assert snap["coalesce_overflows"] == 1
    assert snap["specializations_evicted"] == 1
    assert snap["table_growths"] == 1
    assert snap["coalesce_width_mean"] == 2.0


def test_coalesce_bench_run_smoke(params32):
    """The shared config9 protocol end to end at tiny sizes: the
    criteria fields are present, the gathered path probes bitwise, and
    steady state recompiles nothing."""
    from mano_hand_tpu.serving.measure import coalesce_bench_run

    out = coalesce_bench_run(params32, subjects=3, requests=12,
                             max_rows=2, max_bucket=8, trials=2, seed=5)
    assert out["gather_vs_posed_max_abs_err"] == 0.0
    assert out["steady_recompiles"] == 0
    assert out["subjects"] == 3 and out["requests"] == 12
    assert out["engine_vs_split_ratio"] > 0
    assert out["coalesce_width_mean"] >= 1.0
