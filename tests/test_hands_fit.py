"""Joint two-hand fitting (fitting/hands.py) + inter-penetration repulsion.

The reference treats hands as two unrelated model instances
(/root/reference/dump_model.py:48-49); real two-hand observations are one
frame containing both. These tests pin the stacked-parameter solve, the
shared-camera 2D path, and the physical constraint the repulsion term
enforces: fitted hands may touch but not overlap.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mano_hand_tpu.fitting import fit_hands, inter_penetration
from mano_hand_tpu.models import core


@pytest.fixture(scope="module")
def stacked(params_pair):
    left, right = params_pair
    return core.stack_params(
        left.astype(np.float32), right.astype(np.float32)
    )


def _forward2(stacked, pose, shape):
    return jax.vmap(
        lambda prm, p, s: core.forward(prm, p, s)
    )(stacked, pose, shape)


def _two_hand_targets(stacked, seed, separation=0.12):
    rng = np.random.default_rng(seed)
    pose = jnp.asarray(rng.normal(scale=0.25, size=(2, 16, 3)), jnp.float32)
    shape = jnp.asarray(rng.normal(scale=0.5, size=(2, 10)), jnp.float32)
    out = _forward2(stacked, pose, shape)
    trans = jnp.asarray([[0.0, 0, 0], [separation, 0, 0]], jnp.float32)
    return pose, shape, trans, out.verts + trans[:, None, :]


# ---------------------------------------------------------- repulsion term
def test_inter_penetration_zero_when_separated():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(scale=0.01, size=(50, 3)), jnp.float32)
    b = a + jnp.asarray([1.0, 0.0, 0.0])  # a meter apart
    assert float(inter_penetration(a, b, radius=0.005)) == 0.0
    # Overlapping clouds: strictly positive, symmetric.
    c = a + jnp.asarray([0.001, 0.0, 0.0])
    e1 = float(inter_penetration(a, c, radius=0.005))
    e2 = float(inter_penetration(c, a, radius=0.005))
    assert e1 > 0.0
    np.testing.assert_allclose(e1, e2, rtol=1e-6)


def test_inter_penetration_gradient_pushes_apart():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(scale=0.002, size=(30, 3)), jnp.float32)
    b = jnp.asarray(rng.normal(scale=0.002, size=(30, 3)), jnp.float32)

    def energy(offset):
        return inter_penetration(a, b + offset, radius=0.01)

    g = jax.grad(energy)(jnp.zeros(3, jnp.float32))
    # Moving b along -grad must reduce the energy (descent direction).
    e0 = float(energy(jnp.zeros(3)))
    e1 = float(energy(-0.002 * g / jnp.linalg.norm(g)))
    assert np.isfinite(np.asarray(g)).all()
    assert e1 < e0


# ------------------------------------------------------------- basic solve
def test_fit_hands_recovers_both(stacked):
    pose, shape, trans, targets = _two_hand_targets(stacked, seed=0)
    res = fit_hands(stacked, targets, n_steps=300, lr=0.05, fit_trans=True)
    assert res.pose.shape == (2, 16, 3)
    assert res.trans is not None and res.trans.shape == (2, 3)
    out = _forward2(stacked, res.pose, res.shape)
    verts = out.verts + res.trans[:, None, :]
    err = float(jnp.abs(verts - targets).max())
    assert err < 5e-3
    assert float(res.loss_history[0]) > 100 * float(res.final_loss)


def test_fit_hands_21_keypoints2d_shared_camera(stacked):
    from mano_hand_tpu.viz.camera import default_hand_camera

    camera = default_hand_camera()
    pose, shape, trans, _ = _two_hand_targets(stacked, seed=1,
                                              separation=0.08)
    out = _forward2(stacked, pose, shape)
    kp3d = core.keypoints(out, "smplx") + trans[:, None, :]
    target_xy = camera.project(kp3d)[..., :2]

    res = fit_hands(stacked, target_xy, n_steps=400, lr=0.02,
                    data_term="keypoints2d", camera=camera, fit_trans=True,
                    tip_vertex_ids="smplx",
                    pose_prior_weight=1e-4, shape_prior_weight=1e-3)
    out2 = _forward2(stacked, res.pose, res.shape)
    kp2 = core.keypoints(out2, "smplx") + res.trans[:, None, :]
    xy = camera.project(kp2)[..., :2]
    reproj = float(np.max(np.linalg.norm(
        np.asarray(xy) - np.asarray(target_xy), axis=-1
    )))
    assert reproj < 1e-2


# -------------------------------------------------- penetration resolution
def test_repulsion_resolves_interpenetration(stacked):
    """Sparse (joint) observations of two overlapping hands: without
    repulsion the fitted surfaces interpenetrate freely; with it, the
    surfaces separate while the joints still fit."""
    rng = np.random.default_rng(2)
    pose = jnp.asarray(rng.normal(scale=0.15, size=(2, 16, 3)), jnp.float32)
    shape = jnp.zeros((2, 10), jnp.float32)
    out = _forward2(stacked, pose, shape)
    # Nearly coincident hands: heavy overlap by construction.
    trans = jnp.asarray([[0.0, 0, 0], [0.004, 0, 0]], jnp.float32)
    targets = core.keypoints(out, None) + trans[:, None, :]

    common = dict(n_steps=250, lr=0.03, data_term="joints", fit_trans=True,
                  shape_prior_weight=1e-3)
    res_off = fit_hands(stacked, targets, repulsion_weight=0.0, **common)
    res_on = fit_hands(stacked, targets, repulsion_weight=20.0,
                       repulsion_radius=0.004, **common)

    def penetration(res):
        out = _forward2(stacked, res.pose, res.shape)
        verts = out.verts + res.trans[:, None, :]
        return float(inter_penetration(verts[0], verts[1], radius=0.004))

    pen_off, pen_on = penetration(res_off), penetration(res_on)
    assert pen_on < 0.25 * pen_off  # repulsion separates the surfaces
    # ... without abandoning the data: joints still fit to a few mm.
    out_on = _forward2(stacked, res_on.pose, res_on.shape)
    kp = core.keypoints(out_on, None) + res_on.trans[:, None, :]
    assert float(jnp.abs(kp - targets).max()) < 1e-2


# --------------------------------------------------------------- sequence
def test_fit_hands_sequence_recovers_clip(stacked):
    """Offline joint two-hand clip solve: one shape per hand, per-frame
    pose/translation, smoothness; frame-major [T, 2, ...] targets."""
    from mano_hand_tpu.fitting import fit_hands_sequence

    rng = np.random.default_rng(6)
    t_frames = 4
    base = jnp.asarray(rng.normal(scale=0.2, size=(2, 16, 3)), jnp.float32)
    drift = jnp.asarray(
        np.cumsum(rng.normal(scale=0.02, size=(t_frames, 2, 16, 3)), axis=0),
        jnp.float32,
    )
    poses = base + drift                               # [T, 2, 16, 3]
    trans = jnp.asarray([[0.0, 0, 0], [0.09, 0, 0]], jnp.float32)
    outs = jax.vmap(
        lambda prm, pp, ss: core.forward_batched(prm, pp, ss)
    )(stacked, jnp.swapaxes(poses, 0, 1),
      jnp.zeros((2, t_frames, 10), jnp.float32))
    targets = (
        jnp.swapaxes(core.keypoints(outs, "smplx"), 0, 1)
        + trans[None, :, None, :]
    )                                                   # [T, 2, 21, 3]

    res = fit_hands_sequence(
        stacked, targets, n_steps=400, lr=0.04, data_term="joints",
        fit_trans=True, tip_vertex_ids="smplx", repulsion_weight=1.0,
    )
    assert res.pose.shape == (t_frames, 2, 16, 3)
    assert res.shape.shape == (2, 10)
    assert res.trans.shape == (t_frames, 2, 3)
    outs2 = jax.vmap(
        lambda prm, pp, ss: core.forward_batched(prm, pp, ss)
    )(stacked, jnp.swapaxes(res.pose, 0, 1),
      jnp.broadcast_to(res.shape[:, None], (2, t_frames, 10)))
    kp = (
        jnp.swapaxes(core.keypoints(outs2, "smplx"), 0, 1)
        + res.trans[..., None, :]
    )
    assert float(jnp.abs(kp - targets).max()) < 5e-3


def test_fit_hands_sequence_validations(stacked, params_pair):
    from mano_hand_tpu.fitting import fit_hands_sequence

    left, _ = params_pair
    t = jnp.zeros((3, 2, 16, 3), jnp.float32)
    with pytest.raises(ValueError, match="stack_params"):
        fit_hands_sequence(left.astype(np.float32), t, n_steps=2,
                           data_term="joints")
    with pytest.raises(ValueError, match="frame-major"):
        fit_hands_sequence(stacked, t[0], n_steps=2, data_term="joints")
    with pytest.raises(ValueError, match="verts/joints/keypoints2d"):
        fit_hands_sequence(stacked, t, n_steps=2, data_term="points")


# --------------------------------------------------------------- tracking
def test_hands_tracker_follows_smooth_motion(stacked):
    """Streaming two-hand tracking: warm-started joint solves follow a
    smooth clip with few steps per frame."""
    from mano_hand_tpu.fitting import make_hands_tracker

    rng = np.random.default_rng(5)
    base = jnp.asarray(rng.normal(scale=0.2, size=(2, 16, 3)), jnp.float32)
    drift = jnp.asarray(
        rng.normal(scale=0.02, size=(4, 2, 16, 3)), jnp.float32
    )
    trans = jnp.asarray([[0.0, 0, 0], [0.08, 0, 0]], jnp.float32)

    state, step = make_hands_tracker(
        stacked, n_steps=150, data_term="joints", lr=0.05,
        tip_vertex_ids="smplx",
    )
    for t in range(4):
        pose_t = base + drift[: t + 1].sum(0)
        out = _forward2(stacked, pose_t, jnp.zeros((2, 10), jnp.float32))
        target = core.keypoints(out, "smplx") + trans[:, None, :]
        state, res = step(state, target)
    assert state.frame == 4
    out = _forward2(stacked, res.pose, res.shape)
    kp = core.keypoints(out, "smplx") + res.trans[:, None, :]
    assert float(jnp.abs(kp - target).max()) < 5e-3


def test_track_hands_clip(stacked):
    from mano_hand_tpu.fitting import track_hands_clip

    rng = np.random.default_rng(7)
    t_frames = 3
    poses = jnp.asarray(
        rng.normal(scale=0.15, size=(2, 16, 3)), jnp.float32
    ) + jnp.asarray(
        np.cumsum(rng.normal(scale=0.02, size=(t_frames, 2, 16, 3)), 0),
        jnp.float32,
    )
    outs = jax.vmap(
        lambda prm, pp, ss: core.forward_batched(prm, pp, ss)
    )(stacked, jnp.swapaxes(poses, 0, 1),
      jnp.zeros((2, t_frames, 10), jnp.float32))
    targets = jnp.swapaxes(outs.posed_joints, 0, 1)      # [T, 2, 16, 3]

    p_track, s_track, state = track_hands_clip(
        stacked, targets, n_steps=120, data_term="joints", lr=0.05,
        fit_trans=False,
    )
    assert p_track.shape == (t_frames, 2, 16, 3)
    assert s_track.shape == (t_frames, 2, 10)
    assert state.frame == t_frames
    out_last = jax.vmap(
        lambda prm, pp, ss: core.forward(prm, pp, ss)
    )(stacked, p_track[-1], s_track[-1])
    err = float(jnp.abs(out_last.posed_joints - targets[-1]).max())
    assert err < 5e-3
    with pytest.raises(ValueError, match=r"\[T, 2, rows"):
        track_hands_clip(stacked, targets[0], n_steps=2)


def test_hands_tracker_rejects_unknown_options(stacked):
    from mano_hand_tpu.fitting import make_hands_tracker

    with pytest.raises(ValueError, match="cannot pass"):
        make_hands_tracker(stacked, self_penetration_weight=10.0)
    # Tracker-managed arguments are rejected at build time too — they
    # would collide with the per-frame warm start at frame 1 otherwise.
    with pytest.raises(ValueError, match="cannot pass"):
        make_hands_tracker(
            stacked,
            init={"pose": np.zeros((2, 16, 3), np.float32),
                  "shape": np.zeros((2, 10), np.float32)},
        )


# ---------------------------------------------------------------- errors
def test_fit_hands_validations(stacked, params_pair):
    pose, shape, trans, targets = _two_hand_targets(stacked, seed=3)
    left, _ = params_pair
    with pytest.raises(ValueError, match="stack_params"):
        fit_hands(left.astype(np.float32), targets, n_steps=2)
    with pytest.raises(ValueError, match="hand-major"):
        fit_hands(stacked, targets[0], n_steps=2)
    with pytest.raises(ValueError, match="verts/joints/keypoints2d"):
        fit_hands(stacked, targets, n_steps=2, data_term="points")
    with pytest.raises(ValueError, match="target_conf has 16"):
        out = _forward2(stacked, pose, shape)
        from mano_hand_tpu.viz.camera import default_hand_camera
        cam = default_hand_camera()
        xy = cam.project(core.keypoints(out, "smplx"))[..., :2]
        fit_hands(stacked, xy, n_steps=2, data_term="keypoints2d",
                  camera=cam, tip_vertex_ids="smplx",
                  target_conf=np.ones((16,), np.float32))
    with pytest.raises(ValueError, match="init"):
        fit_hands(stacked, targets, n_steps=2,
                  init={"pose": np.zeros((16, 3), np.float32)})


def test_mirror_pose_limits_roundtrip():
    from mano_hand_tpu.fitting import mirror_pose_limits, pose_limit_prior

    rng = np.random.default_rng(41)
    lo = rng.uniform(-0.5, 0.0, size=45).astype(np.float32)
    hi = rng.uniform(0.1, 1.0, size=45).astype(np.float32)
    rlo, rhi = mirror_pose_limits(lo, hi)
    # Valid box, involutive mirror.
    assert (np.asarray(rlo) <= np.asarray(rhi)).all()
    blo, bhi = mirror_pose_limits(rlo, rhi)
    np.testing.assert_allclose(np.asarray(blo), lo, atol=1e-7)
    np.testing.assert_allclose(np.asarray(bhi), hi, atol=1e-7)
    # A pose inside the left box lands inside the right box under the
    # [1, -1, -1] per-joint mirror — and exactly on the hinge's zero set.
    pose = rng.uniform(lo, hi).astype(np.float32).reshape(15, 3)
    mirrored = (pose * np.asarray([1.0, -1.0, -1.0],
                                  np.float32)).reshape(1, 45)
    assert float(pose_limit_prior(mirrored, rlo, rhi)) == 0.0
    assert float(pose_limit_prior(pose.reshape(1, 45), lo, hi)) == 0.0


def test_fit_hands_joint_limits_per_hand(stacked):
    from mano_hand_tpu.fitting import mirror_pose_limits

    pose, shape, trans, targets = _two_hand_targets(stacked, seed=5)
    flat_l = np.asarray(pose)[0, 1:].reshape(45)
    flat_r = np.asarray(pose)[1, 1:].reshape(45)
    lo = np.minimum(flat_l, flat_r) - 0.25
    hi = np.maximum(flat_l, flat_r) + 0.25
    limits = (jnp.asarray(np.stack([lo, lo])),
              jnp.asarray(np.stack([hi, hi])))
    res = fit_hands(stacked, targets, n_steps=300, lr=0.05,
                    fit_trans=True, joint_limits=limits,
                    joint_limit_weight=1.0)
    got = np.asarray(res.pose)[:, 1:].reshape(2, 45)
    assert (got > lo - 0.05).all() and (got < hi + 0.05).all()
    out = _forward2(stacked, res.pose, res.shape)
    verts = out.verts + res.trans[:, None, :]
    assert float(jnp.abs(verts - targets).max()) < 8e-3
    # mirror helper integrates: right bounds derived from left-only data
    # keep the same broadcast contract ([2, 45] box).
    rlo, rhi = mirror_pose_limits(lo, hi)
    limits2 = (jnp.stack([jnp.asarray(lo), rlo]),
               jnp.stack([jnp.asarray(hi), rhi]))
    res2 = fit_hands(stacked, targets, n_steps=5, lr=0.05,
                     fit_trans=True, joint_limits=limits2)
    assert np.isfinite(np.asarray(res2.final_loss)).all()

    # Sequence variant: same broadcast contract over [T, 2, 45].
    from mano_hand_tpu.fitting import fit_hands_sequence

    clip = jnp.stack([targets, targets], axis=0)      # [T=2, 2, V, 3]
    seq = fit_hands_sequence(stacked, clip, n_steps=5, fit_trans=True,
                             joint_limits=limits,
                             joint_limit_weight=1.0)
    assert np.isfinite(np.asarray(seq.final_loss)).all()


def test_hands_tracker_kabsch_first_frame(stacked):
    """A two-hand stream opening far from rest: both hands' frame-0
    Kabsch seeds (rotation AND translation) land the joint solve near
    the targets in the few per-frame steps."""
    from mano_hand_tpu.fitting import make_hands_tracker

    rng = np.random.default_rng(47)
    pose = np.zeros((2, 16, 3), np.float32)
    pose[0, 0] = [0.1, 3.0, 0.2]
    pose[1, 0] = [2.8, -0.4, 0.1]
    pose[:, 1:] = rng.normal(scale=0.15, size=(2, 15, 3))
    trans = np.asarray([[0.0, 0.02, 0.0], [0.15, -0.03, 0.05]],
                       np.float32)
    out = _forward2(stacked, jnp.asarray(pose),
                    jnp.zeros((2, 10), jnp.float32))
    targets = out.posed_joints + trans[:, None, :]

    state, step = make_hands_tracker(stacked, data_term="joints",
                                     n_steps=80, lr=0.05)
    state, res = step(state, targets)
    got = _forward2(stacked, res.pose, res.shape).posed_joints \
        + res.trans[:, None, :]
    err = float(jnp.abs(got - targets).max())
    assert err < 5e-3, err
