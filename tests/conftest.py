"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Must set env vars before the first ``import jax`` anywhere in the test
process so sharding/pjit paths are exercised without TPU hardware
(SURVEY.md §4.5: "multi-node without a cluster").
"""

import os
import sys

# Force CPU even when the shell env points at a TPU (JAX_PLATFORMS=axon):
# the suite exercises numerics + sharding on a deterministic virtual mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# The persistent compilation cache below re-loads AOT results compiled on
# this same machine; XLA's loader still error-logs a harmless mismatch on
# the "prefer-no-scatter/gather" PSEUDO-features (not real ISA bits) for
# every hit. Silence the C++ log noise — Python-level failures still raise.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

# Make the repo root importable regardless of pytest invocation directory.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import jax  # noqa: E402

# Persistent XLA compilation cache: the suite is compile-bound (hundreds of
# distinct jitted programs, one CPU core on this box), and the programs are
# deterministic run to run — so the gate pays full compilation only on a
# cold cache. Repo-local dir (gitignored) so `git clean`/fresh clones start
# cold; VERDICT r2 item 5 records cold vs warm wall times in the Makefile.
# MANO_TEST_CACHE_DIR override: two pytest processes must NEVER share one
# cache dir (executable-deserialize crashes, diagnosed round 3) — an
# ad-hoc run alongside the main suite points here at its own directory.
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("MANO_TEST_CACHE_DIR",
                   os.path.join(_ROOT, ".jax_compile_cache")),
)
# Cache EVERYTHING: the suite's long tail is hundreds of sub-second
# compiles (the default 1s threshold would skip them all and leave ~5 of
# the 10 cold minutes on the table).
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

# A site hook on this image (an accelerator-tunnel plugin) re-sets
# jax_platforms to "<plugin>,cpu" at interpreter startup, overriding the env
# var; when the tunnel is unavailable any backend init then hangs. Re-assert
# cpu through the config API, which wins over the startup hook.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from mano_hand_tpu.assets import synthetic_pair, synthetic_params  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "quick: core-correctness tests for the seconds-scale pre-commit "
        "lane (`make check-quick`); the full suite remains the snapshot "
        "gate",
    )
    config.addinivalue_line(
        "markers",
        "slow: wall-clock-heavy drill/e2e modules excluded from the "
        "tier-1 `-m 'not slow'` lane; each has its own make smoke "
        "target (separate pytest process + compile-cache dir) wired "
        "into `make check`",
    )


@pytest.fixture(autouse=True, scope="module")
def _bound_live_executables():
    """Clear jax's in-process executable caches after every module.

    Deserializing a LARGE cached executable late in a full-suite process
    segfaults inside XLA's ``backend.deserialize_executable`` once a few
    hundred executables are live (reproduced 5/5 at whichever big
    program happens to load last — silhouette fits, then pallas VJPs
    after reordering — while every subset and each module alone pass).
    Dropping compiled programs at module boundaries keeps the live count
    bounded; re-loads hit the warm persistent cache, so the wall-time
    cost is seconds, and the deserializations now happen in a
    low-executable-count process, which is exactly the state that never
    crashes.
    """
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def params():
    """Session-wide synthetic right-hand asset (float64)."""
    return synthetic_params(seed=0)


@pytest.fixture(scope="session")
def params_pair():
    """(left, right) synthetic asset pair."""
    return synthetic_pair(seed=0)
