"""Asset layer: schema validation, synthetic generator, loader round-trips."""

import dataclasses

import numpy as np
import pytest

from mano_hand_tpu import constants as C
from mano_hand_tpu.assets import (
    ManoParams,
    load_dumped_pickle,
    load_model,
    load_npz,
    save_dumped_pickle,
    save_npz,
    synthetic_params,
    validate,
)


def test_synthetic_shapes(params):
    assert params.v_template.shape == (C.N_VERTS, 3)
    assert params.shape_basis.shape == (C.N_VERTS, 3, C.N_SHAPE)
    assert params.pose_basis.shape == (C.N_VERTS, 3, C.N_POSE_BASIS)
    assert params.j_regressor.shape == (C.N_JOINTS, C.N_VERTS)
    assert params.lbs_weights.shape == (C.N_VERTS, C.N_JOINTS)
    assert params.pca_basis.shape == (45, 45)
    assert params.pca_mean.shape == (45,)
    assert params.faces.shape == (C.N_FACES, 3)
    assert params.parents == C.MANO_PARENTS


def test_synthetic_stochastic_structure(params):
    # Convex-combination structure of regressor and skinning weights.
    np.testing.assert_allclose(params.j_regressor.sum(axis=1), 1.0, atol=1e-12)
    np.testing.assert_allclose(params.lbs_weights.sum(axis=1), 1.0, atol=1e-12)
    assert (params.j_regressor >= 0).all()
    assert (params.lbs_weights >= 0).all()
    # PCA basis orthonormal.
    np.testing.assert_allclose(
        params.pca_basis @ params.pca_basis.T, np.eye(45), atol=1e-10
    )


def test_synthetic_deterministic():
    a = synthetic_params(seed=7)
    b = synthetic_params(seed=7)
    np.testing.assert_array_equal(a.v_template, b.v_template)
    np.testing.assert_array_equal(a.faces, b.faces)


def test_validate_rejects_bad_parents(params):
    bad = dataclasses.replace(params, parents=(0,) + params.parents[1:])
    with pytest.raises(ValueError, match="parents"):
        validate(bad)


def test_validate_rejects_bad_shape(params):
    bad = dataclasses.replace(params, pca_mean=params.pca_mean[:-1])
    with pytest.raises(ValueError, match="pca_mean"):
        validate(bad)


def test_npz_roundtrip(params, tmp_path):
    path = tmp_path / "hand.npz"
    save_npz(params, path)
    back = load_npz(path)
    np.testing.assert_array_equal(back.v_template, params.v_template)
    np.testing.assert_array_equal(back.faces, params.faces)
    assert back.parents == params.parents
    assert back.side == params.side


def test_dumped_pickle_roundtrip(params, tmp_path):
    """Interop with the reference's dumped format, incl. parents[0]=None."""
    path = tmp_path / "dump_mano_right.pkl"
    save_dumped_pickle(params, path)

    import pickle
    with open(path, "rb") as f:
        raw = pickle.load(f)
    assert raw["parents"][0] is None  # reference sentinel preserved
    assert set(raw) == {
        "pose_pca_basis", "pose_pca_mean", "J_regressor", "skinning_weights",
        "mesh_pose_basis", "mesh_shape_basis", "mesh_template", "faces",
        "parents",
    }

    back = load_dumped_pickle(path)
    np.testing.assert_array_equal(back.v_template, params.v_template)
    assert back.parents == params.parents
    assert back.side == C.RIGHT  # inferred from filename


def test_load_model_sniffs_format(params, tmp_path):
    npz = tmp_path / "hand.npz"
    pkl = tmp_path / "dump_mano_left.pkl"
    save_npz(params, npz)
    save_dumped_pickle(params, pkl)
    assert isinstance(load_model(npz), ManoParams)
    assert load_model(pkl).side == C.LEFT


def test_infer_side_neutral_vs_sided(params, tmp_path):
    """'neutral' marks an UNSIDED asset only when no side marker is in the
    name: a sided file mentioning neutral (neutral_pose_left.pkl) keeps
    its handedness, and a bare neutral name stays neutral (ADVICE.md r5)."""
    cases = {
        "neutral_pose_left.pkl": C.LEFT,
        "neutral_pose_right.pkl": C.RIGHT,
        "body_neutral.pkl": C.NEUTRAL,
        "dump_mano_left.pkl": C.LEFT,
        "hand.pkl": C.RIGHT,  # no marker at all: the historical default
    }
    for name, want in cases.items():
        path = tmp_path / name
        save_dumped_pickle(params, path)
        assert load_dumped_pickle(path).side == want, name
        # An explicit side always wins over any filename inference.
        assert load_dumped_pickle(path, side=C.NEUTRAL).side == C.NEUTRAL


def test_pytree_registration(params):
    """ManoParams must be a PyTree with static parents/side."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    assert len(leaves) == 8
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.parents == params.parents
    assert rebuilt.side == params.side


def test_astype(params):
    p32 = params.astype(np.float32)
    assert p32.v_template.dtype == np.float32
    assert p32.faces.dtype == np.int32  # ints untouched


def test_official_pickle_without_chumpy(params, tmp_path):
    """Official MANO pickles hold chumpy.Ch wrappers; chumpy is dead and not
    installed. The tolerant unpickler must stub those classes and still
    surface the wrapped arrays (/root/reference/dump_model.py:4-21 is the
    chumpy-era conversion this loader folds in)."""
    import pickle
    import sys
    import types

    import scipy.sparse as sp

    from mano_hand_tpu.assets import load_official_pickle

    # Forge a chumpy-like module so pickling records class "chumpy.Ch";
    # it is removed before loading, and the real chumpy is not installed,
    # so unpickling MUST go through the stub path.
    assert "chumpy" not in sys.modules or not getattr(
        sys.modules["chumpy"], "__file__", None
    )
    fake = types.ModuleType("chumpy")

    class Ch:
        def __init__(self, x):
            self.x = np.asarray(x)
            self.dterms = ("x",)  # extra non-array state, like real chumpy

    Ch.__module__ = "chumpy"
    Ch.__qualname__ = "Ch"
    fake.Ch = Ch
    sys.modules["chumpy"] = fake
    try:
        raw = {
            "v_template": Ch(params.v_template),
            "shapedirs": Ch(params.shape_basis),
            "posedirs": np.asarray(params.pose_basis),
            "J_regressor": sp.csc_matrix(np.asarray(params.j_regressor)),
            "weights": Ch(params.lbs_weights),
            "hands_components": np.asarray(params.pca_basis),
            "hands_mean": np.asarray(params.pca_mean),
            "f": np.asarray(params.faces, np.uint32),
            "kintree_table": np.stack([
                np.asarray([4294967295] + list(params.parents[1:]),
                           np.uint32),
                np.arange(16, dtype=np.uint32),
            ]),
        }
        path = tmp_path / "MANO_RIGHT.pkl"
        with open(path, "wb") as f:
            pickle.dump(raw, f, protocol=2)
    finally:
        del sys.modules["chumpy"]

    loaded = load_official_pickle(path)
    np.testing.assert_array_equal(loaded.v_template, params.v_template)
    np.testing.assert_array_equal(loaded.j_regressor, params.j_regressor)
    np.testing.assert_array_equal(loaded.lbs_weights, params.lbs_weights)
    assert loaded.parents == params.parents
    assert loaded.parents[0] == -1
    assert loaded.side == C.RIGHT

    # load_model sniffing must also land on the official branch.
    from mano_hand_tpu.assets import load_model as _lm
    assert _lm(path).side == C.RIGHT


def test_smpl_family_pickle_loads_and_runs(tmp_path):
    """An official-style SMPL body pickle (24 joints, no hand-PCA keys)
    loads into the same params PyTree and runs through the topology-
    generic forward. The synthesized pass-through PCA space (identity
    basis, zero mean) keeps every pose-PCA API live: decode(c) == c."""
    import pickle

    import scipy.sparse as sp

    from mano_hand_tpu.assets import load_model, load_smpl_pickle
    from mano_hand_tpu.assets.synthetic import synthetic_params

    body = synthetic_params(seed=11, n_verts=437, n_joints=24, n_shape=16,
                            n_faces=870)
    raw = {
        "v_template": np.asarray(body.v_template),
        "shapedirs": np.asarray(body.shape_basis),
        "posedirs": np.asarray(body.pose_basis),
        "J_regressor": sp.csc_matrix(np.asarray(body.j_regressor)),
        "weights": np.asarray(body.lbs_weights),
        "f": np.asarray(body.faces, np.uint32),
        # SMPL's uint32 root sentinel (2**32 - 1) must map to -1.
        "kintree_table": np.stack([
            np.asarray([4294967295] + list(body.parents[1:]), np.uint32),
            np.arange(24, dtype=np.uint32),
        ]),
    }
    path = tmp_path / "SMPL_NEUTRAL.pkl"
    with open(path, "wb") as f:
        pickle.dump(raw, f, protocol=2)

    loaded = load_smpl_pickle(path)
    np.testing.assert_array_equal(loaded.v_template, body.v_template)
    np.testing.assert_array_equal(loaded.lbs_weights, body.lbs_weights)
    assert loaded.parents == body.parents and loaded.parents[0] == -1
    assert loaded.side == "neutral"
    assert loaded.n_joints == 24 and loaded.n_shape == 16
    # Pass-through PCA space: identity basis, zero mean, (J-1)*3 dims.
    np.testing.assert_array_equal(loaded.pca_basis, np.eye(69))
    np.testing.assert_array_equal(loaded.pca_mean, np.zeros(69))

    # load_model sniffing: dumped -> official -> SMPL chain lands here.
    assert load_model(path).side == "neutral"

    # The body asset runs through the generic JAX core.
    from mano_hand_tpu.models import core

    b32 = loaded.astype(np.float32)
    rng = np.random.default_rng(0)
    pose = rng.normal(scale=0.2, size=(3, 24, 3)).astype(np.float32)
    beta = rng.normal(size=(3, 16)).astype(np.float32)
    out = core.forward_batched(b32, pose, beta)
    assert out.verts.shape == (3, 437, 3)
    assert np.isfinite(np.asarray(out.verts)).all()

    # Mirroring an unsided body keeps it neutral (geometry still flips).
    from mano_hand_tpu.assets import mirror_params

    assert mirror_params(loaded).side == "neutral"

    # Round-trip through the nine-key dumped format must keep the neutral
    # tag (filename inference knows 'neutral', not just left/right).
    from mano_hand_tpu.assets import save_dumped_pickle

    dumped = tmp_path / "body_neutral.pkl"
    save_dumped_pickle(loaded, dumped)
    assert load_model(dumped).side == "neutral"

    # A 16-joint pickle missing the hand-PCA keys is a corrupt MANO
    # asset: the sniffing chain must fail loudly, not fabricate a body.
    hand = synthetic_params(seed=3)
    raw16 = {
        "v_template": np.asarray(hand.v_template),
        "shapedirs": np.asarray(hand.shape_basis),
        "posedirs": np.asarray(hand.pose_basis),
        "J_regressor": sp.csc_matrix(np.asarray(hand.j_regressor)),
        "weights": np.asarray(hand.lbs_weights),
        "f": np.asarray(hand.faces, np.uint32),
        "kintree_table": np.stack([
            np.asarray([4294967295] + list(hand.parents[1:]), np.uint32),
            np.arange(16, dtype=np.uint32),
        ]),
    }
    broken = tmp_path / "MANO_RIGHT_broken.pkl"
    with open(broken, "wb") as f:
        pickle.dump(raw16, f, protocol=2)
    with pytest.raises(KeyError, match="corrupt MANO"):
        load_model(broken)


# Pre-commit quick lane: core correctness, seconds-scale (make check-quick).
pytestmark = __import__("pytest").mark.quick


def test_loader_failure_paths_are_named(params, tmp_path):
    """Malformed inputs fail with NAMED errors at load time, not XLA
    shape errors deep in a trace (the schema.validate contract) — the
    failure half of the `cli verify` trust story."""
    from mano_hand_tpu.assets import load_model, load_npz, save_npz

    # Truncated npz: numpy's own error surfaces, not a silent partial.
    good = tmp_path / "good.npz"
    save_npz(params, good)
    trunc = tmp_path / "trunc.npz"
    trunc.write_bytes(good.read_bytes()[:200])
    with pytest.raises(Exception):
        load_npz(trunc)

    # Missing keys: named KeyError/ValueError mentioning the field.
    arrs = dict(np.load(good, allow_pickle=False))
    arrs.pop("lbs_weights")
    partial = tmp_path / "partial.npz"
    np.savez(partial, **arrs)
    with pytest.raises((KeyError, ValueError)):
        load_npz(partial)

    # Wrong-shape field: schema.validate names the field and both shapes.
    arrs = dict(np.load(good, allow_pickle=False))
    arrs["lbs_weights"] = arrs["lbs_weights"][:, :8]
    bad = tmp_path / "bad.npz"
    np.savez(bad, **arrs)
    with pytest.raises(ValueError, match="lbs_weights"):
        load_npz(bad)

    # Not an asset at all: load_model's sniffing fails loudly.
    junk = tmp_path / "junk.pkl"
    junk.write_bytes(b"\x00\x01garbage")
    with pytest.raises(Exception):
        load_model(junk)
