"""Golden regression: the demo outputs are pinned across rounds.

The reference's only verification artifact is its deterministic demo
export (SURVEY.md §3.4: fixed inputs -> hand.obj). Here the same role is
played by a checked-in fixture of the demo vertices on the synthetic
asset: any unintended numerical change to the oracle, the JAX core, the
PCA decode, or the synthetic asset generator trips this test.

Regenerate (only for INTENTIONAL numerics changes, with a changelog note):
    python -c "see tests/test_golden.py docstring" — run the snippet in
    generate_fixture() below.
"""

from pathlib import Path

import numpy as np
import jax.numpy as jnp

from mano_hand_tpu import cli
from mano_hand_tpu.models import core
from mano_hand_tpu.models.layer import MANOModel

FIXTURE = Path(__file__).parent / "fixtures" / "golden_demo.npz"


def generate_fixture(params):  # pragma: no cover - regeneration helper
    model = MANOModel(params, backend="np")
    model.set_params(
        pose_pca=cli.DEMO_POSE_PCA, shape=cli.DEMO_SHAPE,
        global_rot=cli.DEMO_GLOBAL_ROT,
    )
    np.savez_compressed(
        FIXTURE, verts=model.verts, rest_verts=model.rest_verts,
        joints=model.J,
    )


def test_demo_matches_golden_np_backend(params):
    golden = np.load(FIXTURE)
    model = MANOModel(params, backend="np")
    model.set_params(
        pose_pca=cli.DEMO_POSE_PCA, shape=cli.DEMO_SHAPE,
        global_rot=cli.DEMO_GLOBAL_ROT,
    )
    # f64 end-to-end; tolerance covers BLAS summation-order differences.
    np.testing.assert_allclose(model.verts, golden["verts"], atol=1e-12)
    np.testing.assert_allclose(
        model.rest_verts, golden["rest_verts"], atol=1e-12
    )
    np.testing.assert_allclose(model.J, golden["joints"], atol=1e-12)


def test_demo_matches_golden_jax_backend(params):
    golden = np.load(FIXTURE)
    p32 = params.astype(np.float32)
    pose = core.decode_pca(
        p32,
        jnp.asarray(cli.DEMO_POSE_PCA, jnp.float32),
        jnp.asarray(cli.DEMO_GLOBAL_ROT, jnp.float32),
    )
    out = core.jit_forward(
        p32, pose, jnp.asarray(cli.DEMO_SHAPE, jnp.float32)
    )
    assert np.abs(np.asarray(out.verts) - golden["verts"]).max() < 1e-4


# Pre-commit quick lane: core correctness, seconds-scale (make check-quick).
pytestmark = __import__("pytest").mark.quick
