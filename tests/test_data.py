"""utils.data — epoch batching + device prefetch (the input-overlap
pattern; SURVEY §5's absent-in-reference data pipeline)."""

import jax
import numpy as np
import pytest

from mano_hand_tpu.utils.data import batches, prefetch_to_device

pytestmark = pytest.mark.quick


def _arrays(n=20):
    rng = np.random.default_rng(0)
    return {"pose": rng.normal(size=(n, 16, 3)).astype(np.float32),
            "beta": rng.normal(size=(n, 10)).astype(np.float32)}


def test_batches_cover_each_epoch_exactly_once():
    arrs = _arrays(20)
    seen = []
    for b in batches(arrs, batch_size=8, shuffle=True, seed=1, epochs=2):
        assert b["pose"].shape == (8, 16, 3)  # static shapes, tail dropped
        assert b["beta"].shape == (8, 10)
        seen.append(b["pose"][:, 0, 0])
    # 2 epochs x floor(20/8) = 4 batches; no sample repeats WITHIN an epoch.
    assert len(seen) == 4
    epoch1 = np.concatenate(seen[:2])
    assert len(np.unique(epoch1)) == 16


def test_batches_deterministic_and_validating():
    arrs = _arrays(20)
    a = [b["pose"] for b in batches(arrs, 8, shuffle=True, seed=3)]
    b = [b["pose"] for b in batches(arrs, 8, shuffle=True, seed=3)]
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    # Misuse errors fire AT THE CALL, not at first next() deep in a
    # consumer loop (batches is a validating wrapper over the generator).
    with pytest.raises(ValueError, match="leading dims disagree"):
        batches({"a": np.zeros(3), "b": np.zeros(4)}, 2)
    with pytest.raises(ValueError, match="exceeds dataset size"):
        batches(_arrays(4), 8)
    with pytest.raises(ValueError, match="batch_size must be"):
        batches(_arrays(4), 0)
    # Remainder kept on request (ragged tail allowed off-TPU).
    sizes = [len(b["pose"]) for b in
             batches(arrs, 8, drop_remainder=False)]
    assert sizes == [8, 8, 4]


def test_prefetch_lands_batches_on_device_in_order():
    arrs = _arrays(16)
    got = list(prefetch_to_device(batches(arrs, 4), size=2))
    assert len(got) == 4
    for i, b in enumerate(got):
        assert isinstance(b["pose"], jax.Array)  # already device-resident
    plain = list(batches(arrs, 4))
    for b, p in zip(got, plain):
        np.testing.assert_array_equal(np.asarray(b["pose"]), p["pose"])


def test_prefetch_with_mesh_sharding():
    from mano_hand_tpu import parallel

    n_dev = len(jax.devices())
    if n_dev < 2:
        pytest.skip("needs the virtual multi-device CPU mesh")
    mesh = parallel.make_mesh(data=n_dev)
    sh = parallel.batch_sharding(mesh)
    arrs = _arrays(16)
    for b in prefetch_to_device(batches(arrs, 8), size=2, sharding=sh):
        assert b["pose"].sharding.is_equivalent_to(sh, b["pose"].ndim)


def test_prefetch_drains_short_iterators():
    arrs = _arrays(8)
    got = list(prefetch_to_device(batches(arrs, 4), size=8))
    assert len(got) == 2
    # Misuse fires AT THE CALL (validating wrapper over the generator,
    # same contract as batches()) — no next() needed to trigger it.
    with pytest.raises(ValueError, match="size must be"):
        prefetch_to_device(iter([]), size=0)
