"""Multi-restart fitting (fitting/restarts.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mano_hand_tpu.fitting import fit, fit_lm, fit_restarts
from mano_hand_tpu.models import core


@pytest.fixture(scope="module")
def params32(params):
    return params.astype(np.float32)


def test_restarts_beat_zero_init_on_rotated_cloud(params32):
    """ICP to a strongly rotated scan: the zero-pose basin is wrong, and
    only a restart near the true orientation registers. Deterministic:
    the target pose IS one of the poses fit_restarts(key=0) will sample
    (same PRNG path), so that restart starts in the right basin."""
    n_restarts, pca_scale, rot_scale = 6, 0.8, 1.8
    sampled = core.sample_poses(
        params32, jax.random.PRNGKey(0), n_restarts - 1,
        pca_scale=pca_scale, global_rot_scale=rot_scale,
    )
    # The sampled pose with the LARGEST global rotation — the one a
    # zero init is least able to reach through ICP's frozen assignments.
    k = int(jnp.argmax(jnp.linalg.norm(sampled[:, 0], axis=-1)))
    true_pose = sampled[k]
    cloud = core.forward(
        params32, true_pose, jnp.zeros(10, jnp.float32)
    ).verts

    zero_only = fit_lm(params32, cloud, n_steps=12, data_term="points")
    best, losses = fit_restarts(
        params32, cloud, n_restarts=n_restarts, key=0, solver="lm",
        pca_scale=pca_scale, global_rot_scale=rot_scale,
        n_steps=12, data_term="points",
    )
    assert losses.shape == (n_restarts,)
    # Restart k+1 (after the zero restart) started at the true pose.
    assert float(losses[k + 1]) < 1e-8
    assert float(best.final_loss) <= float(losses[0]) + 1e-12
    assert float(best.final_loss) < 0.01 * float(zero_only.final_loss)


def test_restarts_never_worse_than_plain_fit(params32):
    target = core.forward(
        params32,
        0.1 * jax.random.normal(jax.random.PRNGKey(3), (16, 3)),
        jnp.zeros(10, jnp.float32),
    ).verts
    plain = fit(params32, target, n_steps=40, lr=0.05)
    best, losses = fit_restarts(
        params32, target, n_restarts=3, key=1, n_steps=40, lr=0.05,
    )
    assert best.pose.shape == (16, 3) and best.shape.shape == (10,)
    # include_zero: restart 0 IS the plain fit
    np.testing.assert_allclose(
        float(losses[0]), float(plain.final_loss), rtol=1e-5
    )
    assert float(best.final_loss) <= float(losses[0]) * (1 + 1e-6)


def test_restarts_validation(params32):
    target = np.zeros((778, 3), np.float32)
    with pytest.raises(ValueError, match="init"):
        fit_restarts(params32, target, init={"pose": None})
    with pytest.raises(ValueError, match="pose_space"):
        fit_restarts(params32, target, pose_space="pca")
    with pytest.raises(ValueError, match="ONE problem"):
        fit_restarts(params32, np.zeros((2, 778, 3), np.float32))
    with pytest.raises(ValueError, match="n_restarts"):
        fit_restarts(params32, target, n_restarts=0)
    with pytest.raises(ValueError, match="solver"):
        fit_restarts(params32, target, solver="newton")


def test_restarts_with_trans_and_adam(params32):
    """fit_trans plumbs a zero trans seed per restart (adam path)."""
    target = core.forward(
        params32, jnp.zeros((16, 3)), jnp.zeros(10, jnp.float32)
    ).verts + jnp.asarray([0.03, -0.01, 0.02])
    best, losses = fit_restarts(
        params32, target, n_restarts=2, key=2,
        n_steps=60, lr=0.05, fit_trans=True,
    )
    assert best.trans.shape == (3,)
    np.testing.assert_allclose(
        np.asarray(best.trans), [0.03, -0.01, 0.02], atol=5e-3
    )


def test_kabsch_seed_wins_on_far_rotation(params32):
    """The deterministic Kabsch restart beats sampling on a ~pi-rotated
    clean-mesh problem: with only 2 restarts (zero + Kabsch — no room
    for lucky samples) LM still lands at numerical floor."""
    from mano_hand_tpu.fitting import fit_restarts

    rng = np.random.default_rng(31)
    pose = np.zeros((16, 3), np.float32)
    pose[0] = [0.1, 3.0, 0.3]
    pose[1:] = rng.normal(scale=0.2, size=(15, 3))
    truth = core.forward(params32, jnp.asarray(pose),
                         jnp.zeros(10, jnp.float32))

    best, losses = fit_restarts(
        params32, truth.verts, n_restarts=2, solver="lm", n_steps=10,
    )
    got = core.forward(params32, best.pose, best.shape).verts
    assert float(jnp.abs(got - truth.verts).max()) < 1e-4
    # The Kabsch row (index 1, after the zero row) is the winner.
    assert int(np.argmin(np.asarray(losses))) == 1

    # Disabling it restores the old behavior (and a worse result here).
    best_no, losses_no = fit_restarts(
        params32, truth.verts, n_restarts=2, solver="lm", n_steps=10,
        include_kabsch=False,
    )
    assert float(np.min(np.asarray(losses_no))) \
        > float(np.min(np.asarray(losses)))

    # Inapplicable terms keep working (silently no Kabsch row).
    cloud = truth.verts[::3]
    best_icp, _ = fit_restarts(
        params32, cloud, n_restarts=2, solver="lm", n_steps=4,
        data_term="points",
    )
    assert np.isfinite(float(best_icp.final_loss))


def test_kabsch_seed_dropped_at_n1(params32):
    # Long-standing n_restarts=1 contract: plain zero-init fit, no error.
    from mano_hand_tpu.fitting import fit_restarts

    target = core.forward(params32).verts
    best, losses = fit_restarts(params32, target, n_restarts=1,
                                solver="lm", n_steps=4)
    assert losses.shape == (1,)
    assert np.isfinite(float(best.final_loss))


def test_restarts_lm_fit_trans_kabsch_seed(params32):
    """solver='lm' + fit_trans (round 5): the Kabsch restart row carries
    its pivot-compensating translation seed, so an uncentered rotated
    target lands in the right basin by construction."""
    rng = np.random.default_rng(21)
    pose = np.zeros((16, 3), np.float32)
    pose[0] = [0.2, 2.6, 0.1]          # far from rest orientation
    pose[1:] = rng.normal(scale=0.1, size=(15, 3))
    tr = np.array([0.12, -0.06, 0.2], np.float32)
    target = core.forward(
        params32, jnp.asarray(pose), jnp.zeros(10, jnp.float32)
    ).verts + jnp.asarray(tr)
    best, losses = fit_restarts(
        params32, target, n_restarts=3, solver="lm", n_steps=25,
        fit_trans=True,
    )
    assert float(best.final_loss) < 1e-10, np.asarray(losses)
    assert np.abs(np.asarray(best.trans) - tr).max() < 1e-3
