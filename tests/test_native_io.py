"""Native C++ OBJ serializer: byte-identical to the Python writer."""

import shutil

import numpy as np
import pytest

from mano_hand_tpu.io import native, obj

needs_cxx = pytest.mark.skipif(
    shutil.which("g++") is None and not native.available(),
    reason="no C++ toolchain",
)


@needs_cxx
def test_native_builds_and_loads():
    assert native.build()
    assert native.available()


@needs_cxx
def test_native_obj_byte_identical(params, tmp_path):
    rng = np.random.default_rng(0)
    verts = rng.normal(scale=0.1, size=(778, 3))
    faces = np.asarray(params.faces)

    py_path = tmp_path / "py.obj"
    nat_path = tmp_path / "nat.obj"
    obj.export_obj(verts, faces, py_path, use_native=False)
    native.write_obj(verts, faces, nat_path)
    assert nat_path.read_bytes() == py_path.read_bytes()


@needs_cxx
def test_native_sequence(params, tmp_path):
    rng = np.random.default_rng(1)
    seq = rng.normal(scale=0.1, size=(5, 778, 3))
    faces = np.asarray(params.faces)
    n = native.write_obj_sequence(seq, faces, tmp_path / "frames")
    assert n == 5
    # spot-check one frame against the python writer
    obj.export_obj(seq[3], faces, tmp_path / "ref3.obj", use_native=False)
    assert (
        (tmp_path / "frames" / "frame_00003.obj").read_bytes()
        == (tmp_path / "ref3.obj").read_bytes()
    )


@needs_cxx
def test_export_obj_routes_native(params, tmp_path, monkeypatch):
    """export_obj prefers the native path and both outputs agree."""
    rng = np.random.default_rng(2)
    verts = rng.normal(scale=0.1, size=(778, 3))
    faces = np.asarray(params.faces)
    a, b = tmp_path / "auto.obj", tmp_path / "forced.obj"
    obj.export_obj(verts, faces, a)               # auto (native if available)
    obj.export_obj(verts, faces, b, use_native=True)
    assert a.read_bytes() == b.read_bytes()


def test_native_error_on_bad_path(tmp_path):
    if not native.available():
        pytest.skip("native unavailable")
    with pytest.raises(RuntimeError, match="code -3"):
        native.write_obj(
            np.zeros((1, 3)), np.zeros((1, 3), np.int32),
            tmp_path / "no_such_dir" / "x.obj",
        )


# Pre-commit quick lane: core correctness, seconds-scale (make check-quick).
pytestmark = __import__("pytest").mark.quick
