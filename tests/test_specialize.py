"""The shape-specialization split (ISSUE 2 tentpole), CPU-verified.

The forward factors at the shape/pose boundary
(/root/reference/mano_np.py:81-83 vs 87-115); ``specialize`` bakes the
shape stage once and ``forward_posed`` replays ONLY the pose stage.
Everything that matters is deterministic on CPU and pinned here:

* bit-identity — the split output equals the full staged forward
  EXACTLY (f32 ==, not allclose) at matched batching structure, both
  unbatched and vmapped; the broadcast-shaped serving program is the
  one documented rounding-level exception (different batched
  contraction shapes by design);
* ``ShapedHand`` is a real pytree: flatten/unflatten, jit round-trip,
  tree_map all preserve it;
* the serving engine's composed caches: per-subject specialization
  cache (hit/miss counters) x per-bucket pose-only executables —
  steady multi-subject traffic compiles NOTHING after warm-up;
* frozen-betas fitting reaches the same optimum as the 58-col solve.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mano_hand_tpu.models import core
from mano_hand_tpu.serving import ServingEngine, bucket_for, pad_rows

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def params32(params):
    return params.astype(np.float32)


def _beta(seed=3, scale=0.5):
    return jnp.asarray(
        np.random.default_rng(seed).normal(scale=scale, size=10), jnp.float32)


def _poses(n, seed=0, scale=0.4):
    return jnp.asarray(
        np.random.default_rng(seed).normal(scale=scale, size=(n, 16, 3)),
        jnp.float32)


# ------------------------------------------------------------ the split
def test_specialize_bakes_the_shape_stage(params32):
    beta = _beta()
    sh = core.jit_specialize(params32, beta)
    assert sh.v_shaped.shape == (778, 3)
    assert sh.joints.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(sh.shape), np.asarray(beta))
    # Default betas = zeros: the rest template and its regressed joints.
    sh0 = core.specialize(params32)
    np.testing.assert_array_equal(
        np.asarray(sh0.v_shaped), np.asarray(params32.v_template))
    # The baked joints ARE the full forward's rest joints.
    out = core.jit_forward(params32, _poses(1)[0], beta)
    np.testing.assert_array_equal(np.asarray(sh.joints),
                                  np.asarray(out.joints))


def test_forward_posed_bit_identical_single(params32):
    """THE acceptance criterion: specialize + forward_posed == the full
    forward, f32 EXACT (same ops, same precision, same structure)."""
    beta = _beta()
    sh = core.jit_specialize(params32, beta)
    for i, pose in enumerate(_poses(4, seed=11, scale=0.6)):
        got = core.jit_forward_posed(sh, pose)
        want = core.jit_forward(params32, pose, beta)
        for field, a, b in zip(core.ManoOutput._fields, got, want):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"pose {i}, field {field}")


def test_forward_posed_bit_identical_batched_matched(params32):
    """Vmapped split == vmapped full staged forward, f32 exact — the
    batching structure matches (per-row specialize under the same vmap),
    so every contraction has identical shapes on both sides."""
    poses = _poses(6, seed=5, scale=0.5)
    betas = jnp.asarray(
        np.random.default_rng(7).normal(scale=0.5, size=(6, 10)), jnp.float32)

    split = jax.jit(lambda prm, pp, ss: jax.vmap(
        lambda q, s: core.forward_posed(core.specialize(prm, s), q).verts
    )(pp, ss))(params32, poses, betas)
    full = jax.jit(lambda prm, pp, ss: core.forward_batched(
        prm, pp, ss, fused=False).verts)(params32, poses, betas)
    np.testing.assert_array_equal(np.asarray(split), np.asarray(full))


def test_forward_posed_batched_broadcast_rounding(params32):
    """The serving fast path (ONE ShapedHand broadcast over a pose batch)
    matches the full batched forward to float rounding — the shared
    shape stage changes batched contraction shapes by design, so this
    is the documented rounding-level (not bitwise) pairing."""
    beta = _beta()
    sh = core.jit_specialize(params32, beta)
    poses = _poses(5, seed=9)
    got = core.jit_forward_posed_batched(sh, poses)
    want = core.jit_forward_batched(
        params32, poses, jnp.broadcast_to(beta, (5, 10)))
    np.testing.assert_allclose(np.asarray(got.verts),
                               np.asarray(want.verts), atol=1e-6)
    assert np.asarray(got.joints).shape == (5, 16, 3)


def test_shaped_hand_pytree_roundtrip(params32):
    beta = _beta()
    sh = core.jit_specialize(params32, beta)
    leaves, treedef = jax.tree_util.tree_flatten(sh)
    assert len(leaves) == 5  # v_shaped, joints, shape, pose_basis, weights
    sh2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(sh2, core.ShapedHand)
    assert sh2.parents == params32.parents  # static aux survives
    # Through jit as argument AND return value.
    sh3 = jax.jit(lambda s: s)(sh)
    assert isinstance(sh3, core.ShapedHand)
    for a, b in zip(jax.tree_util.tree_leaves(sh),
                    jax.tree_util.tree_leaves(sh3)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # tree_map keeps the structure (and the static parents).
    doubled = jax.tree_util.tree_map(lambda x: x * 2, sh)
    assert isinstance(doubled, core.ShapedHand)
    np.testing.assert_array_equal(np.asarray(doubled.joints),
                                  2 * np.asarray(sh.joints))
    # ... and the posed forward still runs on the jit-round-tripped tree.
    out = core.jit_forward_posed(sh3, _poses(1)[0])
    assert out.verts.shape == (778, 3)


# ------------------------------------------------- model-layer cache
def test_layer_specialization_cache(params):
    from mano_hand_tpu.models.layer import MANOModel

    model = MANOModel(params)
    beta = np.asarray(_beta())
    model.set_params(shape=beta)
    shaped1 = model._shaped_cache[1]
    # Pose-only updates reuse the bake (the reference's per-frame loop).
    model.set_params(pose_abs=np.asarray(_poses(1)[0]))
    assert model._shaped_cache[1] is shaped1
    # The wrapper's verts equal the one-jit full forward bit-for-bit
    # (the split is exact at unbatched structure).
    want = core.jit_forward(
        model._params_jax, jnp.asarray(model.pose, jnp.float32),
        jnp.asarray(beta, jnp.float32))
    np.testing.assert_array_equal(
        model.verts, np.asarray(want.verts, np.float64))
    # A betas change replaces the cache entry.
    model.set_params(shape=beta * 0.5)
    assert model._shaped_cache[1] is not shaped1


# ------------------------------------------------- serving: both caches
def test_engine_subject_cache_and_zero_recompiles(params32):
    """Steady-state pose-only traffic composes BOTH caches: the subject
    specialization cache (hit/miss counted) and the shared per-bucket
    pose-only executables — a second subject costs one bake and ZERO
    compiles, and warm traffic compiles nothing at all."""
    rng = np.random.default_rng(0)
    beta1, beta2 = (rng.normal(size=10).astype(np.float32) for _ in range(2))
    with ServingEngine(params32, max_bucket=8) as eng:
        s1 = eng.specialize(beta1)
        assert eng.specialize(beta1) == s1            # cache hit
        assert eng.counters.specializations == 1
        assert eng.counters.shaped_hits == 1
        assert eng.warmup_posed() == {1: "jit", 2: "jit", 4: "jit",
                                      8: "jit"}
        warm = eng.counters.compiles
        for seed in range(3):
            for n in (1, 3, 5, 8):
                pose = rng.normal(scale=0.4, size=(n, 16, 3)).astype(
                    np.float32)
                got = eng.forward(pose, subject=s1)
                assert got.shape == (n, 778, 3)
                # Bit-identical to the direct pose-only program at the
                # same padded size (same program family — the
                # engine-contract analogue of the full path's test; the
                # gathered dispatch preserves it, see
                # core.forward_posed_gather). The reference ShapedHand
                # is re-baked by the same jitted program the engine used.
                b = bucket_for(n, eng.buckets)
                want = np.asarray(core.jit_forward_posed_batched(
                    core.jit_specialize(params32, jnp.asarray(beta1)),
                    jnp.asarray(pad_rows(pose, b))).verts)[:n]
                np.testing.assert_array_equal(got, want)
                # ... and rounding-level vs the full path.
                full = np.asarray(core.jit_forward_batched(
                    params32, jnp.asarray(pose),
                    jnp.broadcast_to(jnp.asarray(beta1), (n, 10))).verts)
                assert np.abs(got - full).max() < 1e-6
        # Second subject: one more specialization, zero new compiles —
        # the pose-only executables take the ShapedHand as a runtime
        # argument, so they are shared across subjects.
        s2 = eng.specialize(beta2)
        pose = rng.normal(scale=0.4, size=(4, 16, 3)).astype(np.float32)
        eng.forward(pose, subject=s2)
        assert eng.counters.compiles == warm
        assert eng.counters.specializations == 2
        # Mixed full/pose-only submits coalesce safely (never into one
        # batch) and all resolve.
        futs = [eng.submit(pose, subject=s1), eng.submit(pose),
                eng.submit(pose, subject=s2)]
        for f in futs:
            assert f.result().shape == (4, 778, 3)
        with pytest.raises(ValueError, match="not both"):
            eng.submit(pose, shape=np.zeros((4, 10), np.float32),
                       subject=s1)
        with pytest.raises(ValueError, match="unknown subject"):
            eng.submit(pose, subject="deadbeef")
    snap = eng.counters.snapshot()
    assert snap["specializations"] == 2 and snap["shaped_hits"] == 1


# ------------------------------------------------- frozen-betas fitting
def test_frozen_lm_reaches_the_58col_optimum(params32):
    """Satellite criterion: with the true betas pinned, the 48-col GN
    solve lands at the same optimum as the full 58-col solve."""
    from mano_hand_tpu.fitting import fit_lm

    beta = _beta()
    pose_true = _poses(1, seed=21, scale=0.3)[0]
    target = core.jit_forward(params32, pose_true, beta).verts
    frozen = fit_lm(params32, target, n_steps=12, frozen_shape=beta)
    full = fit_lm(params32, target, n_steps=12)
    assert float(frozen.final_loss) < 1e-10
    assert float(frozen.final_loss) <= 2.0 * max(float(full.final_loss),
                                                 1e-12)
    np.testing.assert_allclose(np.asarray(frozen.pose),
                               np.asarray(pose_true), atol=1e-4)
    # The frozen betas come back verbatim as the result's shape.
    np.testing.assert_array_equal(np.asarray(frozen.shape),
                                  np.asarray(beta))
    # Per-problem frozen subjects on the batched path.
    poses = _poses(3, seed=22, scale=0.25)
    betas = jnp.asarray(np.random.default_rng(23).normal(
        scale=0.5, size=(3, 10)), jnp.float32)
    targets = core.jit_forward_batched(params32, poses, betas).verts
    res = fit_lm(params32, targets, n_steps=10, frozen_shape=betas)
    assert float(jnp.max(res.final_loss)) < 1e-8
    np.testing.assert_array_equal(np.asarray(res.shape), np.asarray(betas))
    # Seeding the non-existent beta parameter fails by name.
    with pytest.raises(ValueError, match="init keys"):
        fit_lm(params32, target, n_steps=2, frozen_shape=beta,
               init={"shape": beta})


def test_frozen_tracking_sequence(params32):
    """Pose-only tracking (frozen betas) follows a synthetic fixed-shape
    sequence to the same optimum as the free 58-col tracker."""
    from mano_hand_tpu.fitting import make_tracker

    beta = _beta()
    t_frames = 4
    base = _poses(1, seed=31, scale=0.25)[0]
    clip = jnp.stack([base * (1.0 + 0.1 * t) for t in range(t_frames)])
    targets = core.jit_forward_batched(
        params32, clip, jnp.broadcast_to(beta, (t_frames, 10))).verts

    state_f, step_f = make_tracker(params32, n_steps=8, solver="lm",
                                   data_term="verts", frozen_shape=beta)
    state_o, step_o = make_tracker(params32, n_steps=8, solver="lm",
                                   data_term="verts")
    for t in range(t_frames):
        state_f, res_f = step_f(state_f, targets[t])
        state_o, res_o = step_o(state_o, targets[t])
    np.testing.assert_array_equal(np.asarray(state_f.shape),
                                  np.asarray(beta))  # betas never moved
    np.testing.assert_allclose(np.asarray(state_f.pose),
                               np.asarray(clip[-1]), atol=1e-4)
    # Same optimum as the free-shape solve (fixed-shape sequence).
    np.testing.assert_allclose(np.asarray(state_f.pose),
                               np.asarray(state_o.pose), atol=1e-3)


def test_frozen_adam_fit(params32):
    """First-order counterpart: frozen-betas Adam fits pose only and
    returns the pinned betas."""
    from mano_hand_tpu.fitting import fit

    beta = _beta()
    pose_true = _poses(1, seed=41, scale=0.2)[0]
    target = core.jit_forward(params32, pose_true, beta).verts
    res = fit(params32, target, n_steps=80, lr=0.05, frozen_shape=beta)
    assert float(res.final_loss) < 1e-5
    np.testing.assert_array_equal(np.asarray(res.shape), np.asarray(beta))
    with pytest.raises(ValueError, match="init keys"):
        fit(params32, target, n_steps=2, frozen_shape=beta,
            init={"shape": np.zeros(10, np.float32)})
