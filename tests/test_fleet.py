"""The fleet front tier (PR 18): proxy routing, failover semantics,
live stream migration, and the chaos-drill protocol.

Every routing/failover assertion here crosses TWO loopback sockets
(client -> EdgeProxy -> backend). Deterministic backend failure modes
come from raw threaded socket stubs — a stub can die at EXACTLY the
byte the test needs (before the reply, mid-stream, with a canned 429)
— while real ``EdgeServer``s provide the healthy siblings, so the
failover target is always the genuine wire path. The semantic bars:

* dead at CONNECT -> silent idempotent re-route (counted, invisible);
* dead AFTER dispatch -> 502 ``upstream`` to the client, NEVER retried
  (a fully-received body WILL be dispatched — retrying double-submits);
* 429 + Retry-After relayed verbatim (PR-5 backpressure end to end);
* the migration race: a frame IN FLIGHT when the backend dies is
  re-sent on a sibling and the client sees one continuous stream;
* ``drain_backend`` (rolling deploy) hands live streams to siblings
  warm-started via ``resume_pose`` — bit-equal poses, continuous frame
  numbering, spans balanced on the drained worker;
* the proxied /healthz aggregate + ``mano status --server`` over it;
* ``SubjectStore.resize_warm`` (the serve-time warm-capacity knob);
* the config21 drill protocol itself at plumbing size (3 real worker
  processes — the one test here that pays for subprocess boots).

Canonical runner: `make fleet-smoke` (own pytest process +
compile-cache dir, wired into `make check`) — slow-marked, so the
tier-1 `-m 'not slow'` lane skips it by design; `make test`
--ignore's it for the same reason. Worker SUBPROCESSES never share
this process's compile cache: ``fleet_drill_run`` gives each worker
its own ``MANO_TEST_CACHE_DIR`` (the XLA executable-deserialization
crash class is two processes on one cache dir — CLAUDE.md).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mano_hand_tpu.edge import (
    Backend,
    EdgeClient,
    EdgeError,
    EdgeProxy,
    EdgeServer,
    protocol as proto,
)
from mano_hand_tpu.obs import Tracer
from mano_hand_tpu.serving.engine import ServingEngine
from mano_hand_tpu.serving.subject_store import (
    SubjectStore,
    SubjectStoreConfig,
    subject_digest,
)
from mano_hand_tpu.utils.profiling import ServingCounters

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def params32(params):
    return params.astype(np.float32)


def _betas(seed=1):
    return np.random.default_rng(seed).normal(size=(10,)).astype(
        np.float32)


def _target(params32, betas, seed=2):
    import jax.numpy as jnp

    from mano_hand_tpu.models import core

    pose = np.random.default_rng(seed).normal(
        scale=0.2, size=(16, 3)).astype(np.float32)
    out = core.jit_forward(params32.device_put(), jnp.asarray(pose),
                           jnp.asarray(betas))
    return np.asarray(out.posed_joints)


def _free_port() -> int:
    """A port that was just bound and released: connecting to it is
    (near-certainly) refused — the dead-at-connect backend."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------------------------------------------- stubs
class _StubBackend:
    """A raw threaded TCP server that fails exactly where told.

    ``mode``:
      * ``"die_after_request"`` — read the full HTTP request, then
        close without one reply byte (dead AFTER dispatch);
      * ``"shed_429"`` — read the request, answer a canned 429 with
        ``Retry-After: 7`` and a structured shed body;
      * ``"stream_die_first_frame"`` — speak the stream upgrade + open
        handshake, then close the socket the moment the first frame
        line arrives (the migration race: that frame is IN FLIGHT).
    """

    def __init__(self, mode: str):
        self.mode = mode
        self.connections = 0
        self.requests = 0
        self.frames_seen = 0
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            self.connections += 1
            threading.Thread(target=self._serve_one, args=(conn,),
                             daemon=True).start()

    def _read_http_request(self, rf) -> bool:
        """Consume one request head + Content-Length body; False on a
        closed socket."""
        length = 0
        line = rf.readline()
        if not line:
            return False
        while True:
            h = rf.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            name, _, value = h.decode("latin-1").partition(":")
            if name.strip().lower() == "content-length":
                length = int(value.strip())
        if length:
            rf.read(length)
        self.requests += 1
        return True

    def _serve_one(self, conn: socket.socket):
        conn.settimeout(30.0)
        rf = conn.makefile("rb")
        try:
            if self.mode == "die_after_request":
                if self._read_http_request(rf):
                    pass                # fall through: close, no reply
            elif self.mode == "shed_429":
                if self._read_http_request(rf):
                    body = proto.dumps(proto.error_body(
                        "shed", "stub shed", phase="admission"))
                    conn.sendall(
                        (f"HTTP/1.1 429 Too Many Requests\r\n"
                         f"Content-Type: application/json\r\n"
                         f"Retry-After: 7\r\n"
                         f"Content-Length: {len(body)}\r\n"
                         f"Connection: close\r\n\r\n").encode("latin-1")
                        + body)
            elif self.mode == "stream_die_first_frame":
                if not self._read_http_request(rf):
                    return
                conn.sendall(
                    (f"HTTP/1.1 101 Switching Protocols\r\n"
                     f"Upgrade: {proto.STREAM_UPGRADE}\r\n"
                     f"Connection: Upgrade\r\n\r\n").encode("latin-1"))
                open_line = rf.readline()       # the {"op": "open"}
                if not open_line:
                    return
                conn.sendall(proto.dumps(
                    {"event": "open", "stream_id": "stub-0"}) + b"\n")
                frame_line = rf.readline()      # first frame: die NOW,
                if frame_line:                  # reply never sent
                    self.frames_seen += 1
        except OSError:
            pass
        finally:
            for closer in (rf.close, conn.close):
                try:
                    closer()
                except OSError:
                    pass

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


# ------------------------------------------------------------ fixtures
def _engine(params32, tracer):
    eng = ServingEngine(params32, max_bucket=4, max_delay_s=0.001,
                        max_queued=32, tracer=tracer)
    eng.start()
    return eng


@pytest.fixture()
def live_backend(params32):
    """One real engine + edge server (the healthy failover target)."""
    tracer = Tracer()
    eng = _engine(params32, tracer)
    srv = EdgeServer(eng, port=0).start()
    yield eng, srv, tracer
    srv.drain(timeout_s=10.0)
    acc = tracer.accounting()
    assert acc["spans_started"] == acc["spans_closed"]
    assert acc["spans_open"] == 0


@pytest.fixture()
def live_pair(params32):
    """Two real backends — the drain test needs a genuine sibling on
    BOTH sides of the migration."""
    tracers = [Tracer(), Tracer()]
    engs = [_engine(params32, t) for t in tracers]
    srvs = [EdgeServer(e, port=0).start() for e in engs]
    yield engs, srvs, tracers
    for srv in srvs:
        srv.drain(timeout_s=10.0)
    for t in tracers:
        acc = t.accounting()
        assert acc["spans_started"] == acc["spans_closed"]
        assert acc["spans_open"] == 0


def _proxy_over(*backends) -> EdgeProxy:
    return EdgeProxy(list(backends), upstream_timeout_s=120.0).start()


# ----------------------------------------------- one-shot failover
def test_backend_dead_at_connect_reroutes_silently(live_backend,
                                                   params32):
    """A backend that refuses the CONNECT was never dispatched: the
    proxy re-routes the same request to a sibling and the client never
    learns — the idempotent retry is counted, not surfaced."""
    eng, srv, _tr = live_backend
    # Stub names sort before the live worker: _pick's deterministic
    # name tie-break routes the first attempt AT the dead backend.
    px = _proxy_over(Backend("a_dead", "127.0.0.1", _free_port()),
                     Backend("b_live", "127.0.0.1", srv.port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        betas = _betas(seed=3)
        pose = np.random.default_rng(4).normal(
            scale=0.3, size=(16, 3)).astype(np.float32)
        via_proxy = cli.forward(pose, shape=betas)
        direct = EdgeClient("127.0.0.1", srv.port, timeout_s=120.0)
        try:
            via_worker = direct.forward(pose, shape=betas)
        finally:
            direct.close()
        assert np.array_equal(via_proxy, via_worker)    # bitwise
        assert px.reroutes >= 1
        assert px.upstream_failures == 0
    finally:
        cli.close()
        px.drain(timeout_s=10.0)


def test_backend_dead_after_dispatch_maps_502_no_retry(live_backend,
                                                       params32):
    """Once the connect succeeded, the body may have been dispatched:
    the failure surfaces as 502 ``upstream`` and is NEVER re-routed —
    a silent retry here would double-submit."""
    _eng, srv, _tr = live_backend
    stub = _StubBackend("die_after_request")
    px = _proxy_over(Backend("a_stub", "127.0.0.1", stub.port),
                     Backend("b_live", "127.0.0.1", srv.port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        pose = np.random.default_rng(5).normal(
            scale=0.3, size=(16, 3)).astype(np.float32)
        with pytest.raises(EdgeError) as ei:
            cli.forward(pose, shape=_betas(seed=6))
        assert ei.value.status == 502
        assert ei.value.kind == "upstream"
        assert px.upstream_failures == 1
        assert px.reroutes == 0         # dispatched -> not idempotent
        assert stub.requests == 1       # exactly one delivery attempt
    finally:
        cli.close()
        px.drain(timeout_s=10.0)
        stub.stop()


def test_429_retry_after_passthrough(live_backend):
    """A worker's PR-5 shed crosses the proxy verbatim: status, kind,
    and the Retry-After header all reach the client untouched."""
    _eng, srv, _tr = live_backend
    stub = _StubBackend("shed_429")
    px = _proxy_over(Backend("a_stub", "127.0.0.1", stub.port),
                     Backend("b_live", "127.0.0.1", srv.port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        pose = np.random.default_rng(7).normal(
            scale=0.3, size=(16, 3)).astype(np.float32)
        with pytest.raises(EdgeError) as ei:
            cli.forward(pose, shape=_betas(seed=8))
        assert ei.value.status == 429
        assert ei.value.kind == "shed"
        assert ei.value.retry_after_s == 7
        # A structured backend ANSWER is not a failure: no counter
        # moved, the breaker stayed closed.
        assert px.upstream_failures == 0
        assert px.reroutes == 0
    finally:
        cli.close()
        px.drain(timeout_s=10.0)
        stub.stop()


# ------------------------------------------------------ stream failover
def test_stream_migration_race_frame_in_flight(live_backend, params32):
    """The backend dies with a frame IN FLIGHT (sent, reply pending).
    The reply never reached the client, so re-sending the frame on a
    sibling is NOT a double submit — the client must see one
    continuous, correct stream and never learn."""
    eng, srv, _tr = live_backend
    stub = _StubBackend("stream_die_first_frame")
    px = _proxy_over(Backend("a_stub", "127.0.0.1", stub.port),
                     Backend("b_live", "127.0.0.1", srv.port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        betas = _betas(seed=11)
        target = _target(params32, betas, seed=12)
        with cli.open_stream(betas=betas) as ws:
            wire = [ws.frame(target) for _ in range(3)]
        assert stub.frames_seen == 1        # the in-flight casualty
        assert px.migrations == 1
        assert px.migrated_frames == 1
        sess = eng.open_stream(betas)
        try:
            for i in range(3):
                ref = sess.step(target)
                assert wire[i].frame == i == ref.frame  # continuous
                assert np.array_equal(wire[i].pose, ref.pose)
                np.testing.assert_allclose(wire[i].verts, ref.verts,
                                           atol=1e-6, rtol=0)
        finally:
            sess.close()
    finally:
        cli.close()
        px.drain(timeout_s=10.0)
        stub.stop()


def test_drain_backend_migrates_live_stream_warm(live_pair, params32):
    """Rolling deploy: ``drain_backend`` proactively hands a parked
    live stream to a sibling, warm-started at the last confirmed pose
    (``resume_pose``). The client's next frames continue the SAME pose
    chain with continuous numbering; the drained worker's spans
    balance (the polite close closed its session exactly once)."""
    engs, srvs, tracers = live_pair
    px = _proxy_over(Backend("a_live", "127.0.0.1", srvs[0].port),
                     Backend("b_live", "127.0.0.1", srvs[1].port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    ws = None
    try:
        betas = _betas(seed=21)
        target = _target(params32, betas, seed=22)
        ws = cli.open_stream(betas=betas)       # lands on a_live
        first = ws.frame(target)
        assert first.frame == 0
        assert len(px.backends()["a_live"].streams) == 1
        report = px.drain_backend("a_live", timeout_s=30.0)
        assert report["clean"] is True
        assert report["streams_migrated"] == 1
        # The drain returns the moment the old worker holds no proxied
        # work (it is then safe to SIGTERM); the sibling re-open
        # completes moments later — bounded wait, not a sleep.
        deadline = time.monotonic() + 10.0
        while px.migrations < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert px.migrations == 1
        assert len(px.backends()["b_live"].streams) == 1
        rest = [ws.frame(target) for _ in range(2)]
        wire = [first] + rest
        # The uninterrupted in-process session is the reference: the
        # resume_pose warm start must reproduce its POSE chain exactly
        # (pose IS the migrated fit state; verts get the f32 anchor
        # tolerance — see fleet_drill_run's parity note).
        sess = engs[0].open_stream(betas)
        try:
            for i in range(3):
                ref = sess.step(target)
                assert wire[i].frame == i == ref.frame
                assert np.array_equal(wire[i].pose, ref.pose)
                np.testing.assert_allclose(wire[i].verts, ref.verts,
                                           atol=1e-6, rtol=0)
        finally:
            sess.close()
        ws.close()
        ws = None
        # The drained worker closed its half of the handoff span-once.
        acc = tracers[0].accounting()
        assert acc["spans_started"] == acc["spans_closed"]
        assert acc["spans_open"] == 0
        assert acc["spans_double_closed"] == 0
    finally:
        if ws is not None:
            ws.abort()
        cli.close()
        px.drain(timeout_s=10.0)


def test_stream_open_prefers_warm_scale_up_worker(live_pair, params32):
    """Cold-stream-start guard (PR 20 satellite): a scale-up worker
    that advertises ``warm_streams: true`` on its OWN /healthz wins
    new stream opens over a boot-fleet sibling that said it booted
    cold — the client's first frames never pay a cold worker's jit
    wall. The proxy learns the fact from the worker (add_backend
    probe + healthz aggregate), never from the test poking state."""
    engs, _srvs, _trs = live_pair
    srv_cold = EdgeServer(engs[0], port=0, warm_streams=False).start()
    srv_warm = EdgeServer(engs[1], port=0, warm_streams=True).start()
    px = _proxy_over(Backend("a_cold", "127.0.0.1", srv_cold.port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        # The scale-up join: add_backend's boot probe reads the
        # worker's warm fact and stamps the freshest boot_seq.
        px.add_backend(Backend("b_warm", "127.0.0.1", srv_warm.port))
        cli.healthz()                   # aggregate refresh of a_cold
        bes = px.backends()
        assert bes["a_cold"].stream_warm is False
        assert bes["b_warm"].stream_warm is True
        assert bes["b_warm"].boot_seq > bes["a_cold"].boot_seq
        betas = _betas(seed=41)
        target = _target(params32, betas, seed=42)
        ws = cli.open_stream(betas=betas)
        try:
            # The open landed on the WARM scale-up worker, not the
            # boot-fleet cold one.
            assert len(px.backends()["b_warm"].streams) == 1
            assert len(px.backends()["a_cold"].streams) == 0
            fr = ws.frame(target)
            assert fr.frame == 0
        finally:
            ws.close()
    finally:
        cli.close()
        px.drain(timeout_s=10.0)
        srv_cold.drain(timeout_s=10.0)
        srv_warm.drain(timeout_s=10.0)


def test_stream_open_all_cold_falls_back_to_plain_pick(live_pair,
                                                       params32):
    """Availability beats warmth: when EVERY routable worker booted
    cold, ``_pick_stream`` falls back to the plain pick — the open
    succeeds on a cold worker rather than refusing service."""
    engs, _srvs, _trs = live_pair
    fronts = [EdgeServer(engs[i], port=0, warm_streams=False).start()
              for i in range(2)]
    px = _proxy_over(Backend("c_cold", "127.0.0.1", fronts[0].port),
                     Backend("d_cold", "127.0.0.1", fronts[1].port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        cli.healthz()                   # both facts refreshed: False
        bes = px.backends()
        assert all(bes[n].stream_warm is False for n in bes)
        betas = _betas(seed=51)
        target = _target(params32, betas, seed=52)
        with cli.open_stream(betas=betas) as ws:
            fr = ws.frame(target)
        assert fr.frame == 0            # served, cold or not
    finally:
        cli.close()
        px.drain(timeout_s=10.0)
        for f in fronts:
            f.drain(timeout_s=10.0)


# ------------------------------------------------- healthz + status CLI
def test_proxy_healthz_aggregate_and_status_cli(live_pair, tmp_path):
    """The proxied /healthz carries the per-backend aggregate, and
    ``mano status --server`` pointed at a PROXY surfaces it (rc 0,
    bounded) — the operator's one look at fleet health."""
    _engs, srvs, _trs = live_pair
    px = _proxy_over(Backend("a_live", "127.0.0.1", srvs[0].port),
                     Backend("b_live", "127.0.0.1", srvs[1].port))
    cli = EdgeClient("127.0.0.1", px.port, timeout_s=120.0)
    try:
        h = cli.healthz()
        assert h["ok"] is True
        assert h["role"] == "proxy"
        assert set(h["backends"]) == {"a_live", "b_live"}
        for b in h["backends"].values():
            assert b["ok"] is True
            assert b["breaker"] == "healthy"
        env = dict(os.environ)
        env["TF_CPP_MIN_LOG_LEVEL"] = "3"
        # Its own cache dir: the subprocess must never share this
        # pytest process's compile cache (CLAUDE.md crash class).
        env["MANO_TEST_CACHE_DIR"] = str(tmp_path / "jax_cache_status")
        res = subprocess.run(
            [sys.executable, "-m", "mano_hand_tpu.cli", "status",
             "--platforms", "cpu", "--server", f"127.0.0.1:{px.port}",
             "--server-timeout", "30.0"],
            capture_output=True, text=True, timeout=300, env=env)
        assert res.returncode == 0, res.stderr[-2000:]
        report = json.loads(res.stdout)
        blk = report["server"]
        assert blk["ok"] is True
        assert blk["role"] == "proxy"
        assert set(blk["backends"]) == {"a_live", "b_live"}
        assert blk["counters"]["requests_proxied"] >= 1
    finally:
        cli.close()
        px.drain(timeout_s=10.0)


# -------------------------------------------------- warm-capacity knob
def _store_row(betas):
    return {"v_shaped": np.zeros((4, 3), np.float32),
            "joints": np.zeros((2, 3), np.float32),
            "shape": betas}


def test_resize_warm_shrink_evicts_lru_first_counted():
    store = SubjectStore(SubjectStoreConfig(warm_capacity=8))
    counters = ServingCounters()
    store.bind(counters)
    digests = []
    for i in range(5):
        betas = _betas(seed=100 + i)
        d = subject_digest(betas)
        digests.append(d)
        store.demote(d, _store_row(betas))
    # Touch 0 and 1: they become MRU; 2..4 are now the LRU victims.
    assert store.fetch_row(digests[0]) is not None
    assert store.fetch_row(digests[1]) is not None
    store.demote(digests[0], _store_row(_betas(seed=100)))
    store.demote(digests[1], _store_row(_betas(seed=101)))
    report = store.resize_warm(2)
    assert report == {"warm_capacity": 2, "previous": 8, "evicted": 3}
    assert counters.subject_store_resize_evictions == 3
    assert set(store.warm_digests()) == {digests[0], digests[1]}
    # No cold tier configured: the victims are gone, and re-entry is
    # the documented degradation (a counted miss -> re-bake upstream).
    assert store.fetch_row(digests[2]) is None


def test_resize_warm_grow_evicts_nothing():
    store = SubjectStore(SubjectStoreConfig(warm_capacity=2))
    store.bind(ServingCounters())
    for i in range(2):
        betas = _betas(seed=200 + i)
        store.demote(subject_digest(betas), _store_row(betas))
    report = store.resize_warm(64)
    assert report["evicted"] == 0
    assert len(store.warm_digests()) == 2


def test_resize_warm_rejects_nonpositive():
    store = SubjectStore(SubjectStoreConfig(warm_capacity=4))
    with pytest.raises(ValueError, match="warm_capacity"):
        store.resize_warm(0)


def test_engine_store_warm_capacity_kwarg(params32):
    """The engine kwarg rides the same runtime-resize path the serve
    flag does — a shrink against a pre-populated store evicts
    LRU-first, counted."""
    store = SubjectStore(SubjectStoreConfig(warm_capacity=16))
    for i in range(6):
        betas = _betas(seed=300 + i)
        store.demote(subject_digest(betas), _store_row(betas))
    eng = ServingEngine(params32, max_bucket=4, subject_store=store,
                        store_warm_capacity=4)
    assert store.config.warm_capacity == 4
    assert len(store.warm_digests()) == 4
    assert eng.counters.subject_store_resize_evictions == 2


def test_engine_store_warm_capacity_requires_store(params32):
    with pytest.raises(ValueError, match="store_warm_capacity"):
        ServingEngine(params32, max_bucket=4, store_warm_capacity=8)


# -------------------------------------------------- the drill protocol
def test_fleet_drill_protocol_plumbing(params):
    """config21's protocol end to end at plumbing size: 3 REAL worker
    processes cold-booting from the baked per-lane lattice, a SIGKILL
    mid-wave, a drain under live streams — every judged invariant must
    already hold here, far from the scarce chip."""
    from mano_hand_tpu.serving.measure import fleet_drill_run

    fd = fleet_drill_run(
        params, workers=3, lanes=2, streams=4, frames_per_stream=3,
        stream_workers=4, unique_tracks=2, max_bucket=4,
        max_subjects=16, store_warm_capacity=8, drain_budget_s=30.0,
        ready_timeout_s=420.0)
    assert fd["fleet_drill_schema"] == 1
    assert fd["cold_boot_zero_compiles"] is True
    assert fd["terminal_fraction"] == 1.0
    assert fd["outcomes"]["exception"] == 0
    assert fd["closes_ok"] == 4
    assert fd["frames_compared"] == fd["frame_numbering_ok"] > 0
    assert fd["intra_fleet_pose_max_abs_err"] == 0.0
    assert fd["wire_vs_inprocess_pose_max_abs_err"] == 0.0
    assert fd["intra_fleet_max_abs_err"] <= 1e-6
    assert fd["wire_vs_inprocess_max_abs_err"] <= 1e-6
    assert fd["steady_recompiles_total"] == 0
    assert fd["aot_load_failures_total"] == 0
    assert fd["spans_closed_exactly_once"] is True
    assert fd["drain"]["clean"] is True
    assert fd["drain"]["streams_migrated"] == fd["drain"][
        "streams_hosted"]


# ------------------------------------------------------- scale-up path
def test_add_worker_warm_streams_first_frame_zero_compiles(tmp_path):
    """Scale-up (PR 19, the PR-18 remainder): ``Fleet.add_worker``
    boots a NEW worker, and with ``warm_streams`` its first real
    stream frame pays ZERO compiles — the in-process stream-fit warm
    pass ran before the ready line, so the proxy is handed a worker
    that is warm, not merely alive. The baseline worker (booted
    WITHOUT the knob) proves the contrast: its first stream compiles
    the fit-stage programs, which are deliberately not in the AOT
    lattice (the PR-18 dead-end)."""
    from mano_hand_tpu.edge.fleet import Fleet, WorkerSpec
    from mano_hand_tpu.serving.measure import _prom_value

    def spec(i, **kw):
        # Per-worker compile-cache dirs: worker subprocesses inherit
        # the pytest lane's env (CLAUDE.md: never two processes on
        # one cache dir).
        return WorkerSpec(
            platform="cpu", max_bucket=4, max_delay_ms=1.0,
            max_subjects=16,
            extra_env={"MANO_TEST_CACHE_DIR":
                       str(tmp_path / f"jax_cache_w{i}")}, **kw)

    def scrape(port):
        cli = EdgeClient("127.0.0.1", port, timeout_s=30.0)
        try:
            text = cli.metrics_text()
        finally:
            cli.close()
        return int(_prom_value(text, "mano_serving_compiles") or 0)

    def first_stream_compiles(port):
        before = scrape(port)
        cli = EdgeClient("127.0.0.1", port, timeout_s=120.0)
        try:
            with cli.open_stream(betas=np.zeros(10, np.float32),
                                 frame_deadline_s=120.0) as ws:
                out = ws.frame(np.random.default_rng(3).normal(
                    scale=0.05, size=(16, 3)).astype(np.float32))
            assert out.frame == 0
        finally:
            cli.close()
        return scrape(port) - before

    fleet = Fleet([spec(0)], stderr_dir=str(tmp_path))
    fleet.start(ready_timeout_s=420.0)
    try:
        name = fleet.add_worker(spec(1, warm_streams=True),
                                ready_timeout_s=420.0)
        assert name == "w1"
        # Routed only after ready: the proxy holds both backends.
        assert set(fleet.proxy.backends()) == {"w0", "w1"}
        # The new worker's first stream frame: zero compiles.
        assert first_stream_compiles(fleet.workers["w1"].port) == 0
        # The cold-booted baseline pays the fit-stage compiles on ITS
        # first stream — the knob is what made the difference.
        assert first_stream_compiles(fleet.workers["w0"].port) > 0
    finally:
        reports = fleet.stop()
    # Both workers drained politely (exit line present).
    assert set(reports) == {"w0", "w1"}
    assert all(r is not None for r in reports.values())
