"""MANOModel wrapper: reference ergonomics, backend flag, OBJ export.

Includes a live cross-check against the reference implementation itself
(/root/reference/mano_np.py), run on an asset we write in its dumped-pickle
format — the strongest available parity evidence.
"""

import os
import sys

import numpy as np
import pytest

from mano_hand_tpu.assets import save_dumped_pickle
from mano_hand_tpu.io.obj import restpose_path
from mano_hand_tpu.models.layer import MANOModel

REFERENCE_DIR = "/root/reference"


@pytest.fixture(scope="module")
def model(params):
    return MANOModel(params, backend="jax")


def test_construction_holds_rest_pose(params):
    """A fresh model already holds the zero-pose mesh (reference cold-start
    behavior, mano_np.py:46)."""
    m = MANOModel(params, backend="np")
    assert m.verts is not None
    np.testing.assert_allclose(m.verts, np.asarray(params.v_template), atol=1e-12)


def test_set_params_pose_abs(model, params):
    rng = np.random.default_rng(0)
    pose = rng.normal(scale=0.5, size=(16, 3))
    verts = model.set_params(pose_abs=pose)
    assert verts.shape == (778, 3)
    # returned array is a copy: mutating it must not affect state
    verts[0] = 999.0
    assert model.verts[0, 0] != 999.0


def test_global_rot_only_in_pca_branch(model):
    """Reference quirk: global_rot is honored only via the PCA branch and
    persists across calls (mano_np.py:70-72)."""
    rng = np.random.default_rng(1)
    pca = rng.normal(size=9)
    v1 = model.set_params(pose_pca=pca, global_rot=[1.0, 0.0, 0.0])
    np.testing.assert_allclose(model.rot, [[1.0, 0.0, 0.0]])
    # next PCA call without global_rot keeps the old rot
    v2 = model.set_params(pose_pca=pca)
    np.testing.assert_allclose(model.rot, [[1.0, 0.0, 0.0]])
    np.testing.assert_allclose(v1, v2, atol=1e-5)


def test_backends_agree(params):
    m = MANOModel(params)
    rng = np.random.default_rng(2)
    pose = rng.normal(scale=0.5, size=(16, 3))
    shape = rng.normal(size=10)
    v_np = m(pose=pose, shape=shape, backend="np")
    v_jax = m(pose=pose, shape=shape, backend="jax")
    assert np.abs(v_np - v_jax).max() < 1e-4


def test_call_batched_jax(params):
    m = MANOModel(params)
    rng = np.random.default_rng(3)
    pose = rng.normal(scale=0.5, size=(4, 16, 3))
    shape = rng.normal(size=(4, 10))
    v = m(pose=pose, shape=shape, backend="jax")
    assert v.shape == (4, 778, 3)
    for i in range(4):
        vi = m(pose=pose[i], shape=shape[i], backend="np")
        assert np.abs(v[i] - vi).max() < 1e-4
    with pytest.raises(ValueError, match="unbatched"):
        m(pose=pose, shape=shape, backend="np")


def test_call_pca(params):
    m = MANOModel(params)
    rng = np.random.default_rng(4)
    pca = rng.normal(size=9)
    v_np = m(pose_pca=pca, global_rot=[1, 0, 0], backend="np")
    v_jax = m(pose_pca=pca, global_rot=[1, 0, 0], backend="jax")
    assert np.abs(v_np - v_jax).max() < 1e-4


def test_call_rejects_both_pose_kinds(params):
    m = MANOModel(params, backend="np")
    with pytest.raises(ValueError, match="exactly one"):
        m(pose=np.zeros((16, 3)), pose_pca=np.zeros(9))
    with pytest.raises(ValueError, match="backend"):
        m(backend="torch")


def test_call_rejects_global_rot_with_absolute_pose(params):
    """global_rot must not be silently dropped when an absolute pose
    already carries the root rotation."""
    m = MANOModel(params, backend="np")
    with pytest.raises(ValueError, match="global_rot"):
        m(pose=np.zeros((16, 3)), global_rot=[1.0, 0.0, 0.0])


def test_call_batched_pca(params):
    """Batched PCA coefficients with a shared [3] global rot broadcast on
    the jax backend; the np backend refuses batches with a clear error."""
    m = MANOModel(params)
    rng = np.random.default_rng(6)
    pca = rng.normal(size=(4, 9))
    v = m(pose_pca=pca, global_rot=[1.0, 0.0, 0.0], backend="jax")
    assert v.shape == (4, 778, 3)
    for i in range(4):
        vi = m(pose_pca=pca[i], global_rot=[1.0, 0.0, 0.0], backend="np")
        assert np.abs(v[i] - vi).max() < 1e-4
    with pytest.raises(ValueError, match="unbatched"):
        m(pose_pca=pca, backend="np")


def test_model_fit_adopts_solution(params):
    """MANOModel.fit recovers from a target and updates the wrapper's
    state in place — the stateful 'inverse set_params'."""
    import jax.numpy as jnp

    from mano_hand_tpu.models.layer import MANOModel

    rng = np.random.default_rng(11)
    true_pose = rng.normal(scale=0.25, size=(16, 3))
    source = MANOModel(params, backend="jax")
    target = source.set_params(pose_abs=true_pose)

    model = MANOModel(params, backend="jax")
    res = model.fit(jnp.asarray(target, jnp.float32), solver="lm",
                    n_steps=15)
    # The wrapper's state now IS the solution: verts match the target.
    np.testing.assert_allclose(model.verts, target, atol=1e-3)
    np.testing.assert_allclose(model.pose, true_pose, atol=1e-3)
    assert np.asarray(res.final_loss).shape == ()

    with pytest.raises(ValueError, match="no translation state"):
        model.fit(jnp.asarray(target, jnp.float32), fit_trans=True)
    # An explicit fit_trans=False is simply "off" — including for LM,
    # whose signature has no such kwarg.
    model.fit(jnp.asarray(target, jnp.float32), solver="lm", n_steps=2,
              fit_trans=False)
    with pytest.raises(ValueError, match="use fitting.fit for batches"):
        model.fit(jnp.asarray(np.stack([target] * 2), jnp.float32),
                  solver="lm", n_steps=2)
    with pytest.raises(ValueError, match="solver must be"):
        model.fit(jnp.asarray(target, jnp.float32), solver="bfgs")


def test_export_obj(model, tmp_path):
    rng = np.random.default_rng(5)
    model.set_params(pose_abs=rng.normal(scale=0.3, size=(16, 3)))
    out = tmp_path / "hand.obj"
    model.export_obj(out)
    twin = restpose_path(out)
    assert out.exists() and twin.exists()
    lines = out.read_text().splitlines()
    v_lines = [l for l in lines if l.startswith("v ")]
    f_lines = [l for l in lines if l.startswith("f ")]
    assert len(v_lines) == 778 and len(f_lines) == 1538
    # faces are 1-indexed
    ids = np.array([l.split()[1:] for l in f_lines], dtype=int)
    assert ids.min() >= 1 and ids.max() <= 778
    with pytest.raises(ValueError, match="obj"):
        model.export_obj(tmp_path / "hand.ply")


@pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_DIR, "mano_np.py")),
    reason="reference checkout not available",
)
def test_parity_with_reference_implementation(params, tmp_path):
    """Run the ACTUAL reference code on our asset and diff every exposed
    attribute and the exported OBJ bytes."""
    sys.path.insert(0, REFERENCE_DIR)
    try:
        from mano_np import MANOModel as RefModel
    finally:
        sys.path.remove(REFERENCE_DIR)

    pkl = tmp_path / "dump_mano_right.pkl"
    save_dumped_pickle(params, pkl)
    ref = RefModel(str(pkl))
    ours = MANOModel(params, backend="np")

    rng = np.random.default_rng(9608)
    pose_pca = rng.normal(size=9)
    shape = rng.normal(size=10)
    v_ref = ref.set_params(pose_pca=pose_pca, shape=shape, global_rot=[1, 0, 0])
    v_ours = ours.set_params(pose_pca=pose_pca, shape=shape, global_rot=[1, 0, 0])
    np.testing.assert_allclose(v_ours, v_ref, atol=1e-12)
    np.testing.assert_allclose(ours.J, ref.J, atol=1e-12)
    np.testing.assert_allclose(ours.R, ref.R, atol=1e-12)
    np.testing.assert_allclose(ours.rest_verts, ref.rest_verts, atol=1e-12)

    ref.export_obj(str(tmp_path / "ref.obj"))
    ours.export_obj(tmp_path / "ours.obj")
    assert (tmp_path / "ours.obj").read_text() == (tmp_path / "ref.obj").read_text()
    assert (tmp_path / "ours_restpose.obj").read_text() == (
        tmp_path / "ref_restpose.obj"
    ).read_text()


# Pre-commit quick lane: core correctness, seconds-scale (make check-quick).
pytestmark = __import__("pytest").mark.quick


def test_stateful_wrapper_on_body_model():
    """MANOModel is model-family generic: a 24-joint body drives the
    same stateful surface — set_params (abs + pass-through PCA), verts,
    keypoint-free joint read, and .fit recovery."""
    import dataclasses

    from mano_hand_tpu.assets.synthetic import synthetic_params

    body = synthetic_params(seed=6, n_verts=437, n_joints=24, n_shape=16,
                            n_faces=870)
    # Body assets carry the loader's pass-through PCA space (identity
    # basis, zero mean — assets.load_smpl_pickle): coefficients ARE the
    # leading articulated axis-angle dims.
    body = dataclasses.replace(
        body, pca_basis=np.eye(69), pca_mean=np.zeros(69))
    m = MANOModel(body, backend="jax")
    assert m.verts.shape == (437, 3)
    rng = np.random.default_rng(0)
    pose = rng.normal(scale=0.25, size=(24, 3))
    verts = m.set_params(pose_abs=pose, shape=rng.normal(size=16))
    assert verts.shape == (437, 3) and np.isfinite(verts).all()
    assert m.J.shape == (24, 3)
    # Pass-through PCA branch: coefficients ARE the articulated pose.
    v2 = m.set_params(pose_pca=np.zeros(9), global_rot=np.zeros(3),
                      shape=np.zeros(16))
    np.testing.assert_allclose(
        v2, MANOModel(body, backend="jax").verts, atol=1e-6)

    target = np.asarray(verts)
    m.fit(target, n_steps=12, solver="lm")  # adopts the solution in-state
    assert np.abs(m.verts - target).max() < 1e-4
