"""scripts/trace_report.py — the trace-to-numbers tool the headroom work
reads. Input format pinned by a synthetic Chrome-trace capture; ranking,
track split, and JSON mode asserted."""

import gzip
import json
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).parent.parent


def _write_trace(path: Path, events) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    with gzip.open(path, "wt") as f:
        json.dump({"displayTimeUnit": "ns", "traceEvents": events}, f)


def _fixture(tmp_path: Path) -> Path:
    # Layout mirrors jax.profiler: DIR/plugins/profile/<run>/*.trace.json.gz
    tdir = tmp_path / "trace"
    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        # Device ops: fused_kernel dominates, two launches.
        {"ph": "X", "pid": 1, "tid": 7, "name": "fused_full.3",
         "ts": 0, "dur": 900.0},
        {"ph": "X", "pid": 1, "tid": 7, "name": "fused_full.3",
         "ts": 1000, "dur": 850.0},
        {"ph": "X", "pid": 1, "tid": 7, "name": "dot.2",
         "ts": 2000, "dur": 300.0},
        # Host-side dispatch noise must not pollute the device ranking.
        {"ph": "X", "pid": 2, "name": "ExecuteSharded", "ts": 0,
         "dur": 5000.0},
        # Non-complete events are ignored.
        {"ph": "B", "pid": 1, "tid": 7, "name": "ignored", "ts": 0},
    ]
    _write_trace(tdir / "plugins" / "profile" / "run1" / "t.trace.json.gz",
                 events)
    return tdir


def _run(*argv):
    return subprocess.run(
        [sys.executable, str(ROOT / "scripts" / "trace_report.py"),
         *map(str, argv)],
        capture_output=True, text=True, timeout=60,
    )


def test_ranks_device_ops_by_total_time(tmp_path):
    proc = _run(_fixture(tmp_path))
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "/device:TPU:0:XLA Ops" in out
    # fused_full (1750 us total, 2 launches) outranks dot (300 us).
    assert out.index("fused_full.3") < out.index("dot.2")
    assert "x2" in out and "1.750 ms" in out
    # Host track hidden by default when a device track exists.
    assert "ExecuteSharded" not in out
    assert "ExecuteSharded" in _run(_fixture(tmp_path),
                                    "--all-tracks").stdout


def test_json_mode_is_machine_readable(tmp_path):
    proc = _run(_fixture(tmp_path), "--json")
    assert proc.returncode == 0, proc.stderr
    data = json.loads(proc.stdout)
    rows = data["tracks"]["/device:TPU:0:XLA Ops"]
    assert rows[0]["name"] == "fused_full.3"
    assert rows[0]["total_us"] == 1750.0 and rows[0]["count"] == 2


def test_missing_dir_fails_cleanly(tmp_path):
    proc = _run(tmp_path / "nope")
    assert proc.returncode == 1
    assert "no *.trace.json[.gz]" in proc.stderr


def test_multiple_captures_keep_their_own_tracks(tmp_path):
    """Two runs in one profile dir both use pid 1 for their device track;
    the totals must NOT merge (they would double-count same-named ops)."""
    tdir = _fixture(tmp_path)
    second = (tdir / "plugins" / "profile" / "run2" / "t.trace.json.gz")
    _write_trace(second, [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 7, "name": "thread_name",
         "args": {"name": "XLA Ops"}},
        {"ph": "X", "pid": 1, "tid": 7, "name": "fused_full.3",
         "ts": 0, "dur": 111.0},
    ])
    proc = _run(tdir, "--json")
    assert proc.returncode == 0, proc.stderr
    tracks = json.loads(proc.stdout)["tracks"]
    assert "run1:/device:TPU:0:XLA Ops" in tracks
    assert "run2:/device:TPU:0:XLA Ops" in tracks
    assert tracks["run1:/device:TPU:0:XLA Ops"][0]["total_us"] == 1750.0
    assert tracks["run2:/device:TPU:0:XLA Ops"][0]["total_us"] == 111.0


def test_truncated_capture_warns_and_continues(tmp_path):
    tdir = _fixture(tmp_path)
    bad = tdir / "plugins" / "profile" / "run0" / "t.trace.json.gz"
    bad.parent.mkdir(parents=True)
    good_bytes = (tdir / "plugins" / "profile" / "run1" /
                  "t.trace.json.gz").read_bytes()
    bad.write_bytes(good_bytes[: len(good_bytes) // 2])
    proc = _run(tdir, "--json")
    assert proc.returncode == 0, proc.stderr
    assert "skipping unreadable trace" in proc.stderr
    assert "run1:/device:TPU:0:XLA Ops" in json.loads(proc.stdout)["tracks"]


def test_closed_pipe_exits_clean(tmp_path):
    """`trace_report DIR | head -1` must exit 0 with no 'Exception
    ignored' shutdown noise (interpreter flush re-raising BrokenPipe)."""
    script = (f"{sys.executable} {ROOT / 'scripts' / 'trace_report.py'} "
              f"{_fixture(tmp_path)} --all-tracks | head -1")
    proc = subprocess.run(["bash", "-c",
                           f"set -o pipefail; {script}"],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, (proc.returncode, proc.stderr)
    assert "BrokenPipeError" not in proc.stderr


def _engine_trace(path: Path, n_spans=3) -> None:
    """A minimal engine span export (the obs.Tracer chrome_trace
    shape, schema 1) written as plain *.trace.json."""
    pid = 9001
    events = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "mano-serving-engine"}},
        {"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
         "args": {"name": "tier 0"}},
    ]
    for i in range(n_spans):
        t0 = i * 10_000.0
        events.append({"ph": "X", "pid": pid, "tid": 0,
                       "name": "request/full/b8", "ts": t0, "dur": 900.0,
                       "args": {"terminal": "ok"}})
        for stage, off, dur in (("queue", 0, 500.0),
                                ("dispatch", 500, 50.0),
                                ("device", 550, 300.0),
                                ("readback", 850, 50.0)):
            events.append({"ph": "X", "pid": pid, "tid": 0,
                           "name": f"stage/{stage}", "ts": t0 + off,
                           "dur": dur})
    block = {
        "schema": 1,
        "accounting": {"spans_started": n_spans, "spans_closed": n_spans,
                       "spans_open": 0, "spans_double_closed": 0,
                       "closed_by_kind": {"ok": n_spans},
                       "events_total": 6 * n_spans, "events_dropped": 0,
                       "ring_len": 6 * n_spans, "ring_capacity": 8192,
                       "incidents": 0},
        "stages": {"complete_spans": n_spans, "by_bucket_tier": {
            "b8/tier0": {"n": n_spans,
                         "queue_p50_ms": 0.5, "queue_p99_ms": 0.5,
                         "queue_mean_ms": 0.5,
                         "dispatch_p50_ms": 0.05, "dispatch_p99_ms": 0.05,
                         "dispatch_mean_ms": 0.05,
                         "device_p50_ms": 0.3, "device_p99_ms": 0.3,
                         "device_mean_ms": 0.3,
                         "readback_p50_ms": 0.05, "readback_p99_ms": 0.05,
                         "readback_mean_ms": 0.05,
                         "total_p50_ms": 0.9, "total_p99_ms": 0.9,
                         "total_mean_ms": 0.9}}},
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps({"displayTimeUnit": "ms",
                                "traceEvents": events,
                                "manoEngineTrace": block}))


def test_engine_export_host_only_stage_breakdown(tmp_path):
    """The tunnel-down acceptance path: an engine span export ALONE
    yields the queue/dispatch/device/readback stage table."""
    tdir = tmp_path / "trace"
    _engine_trace(tdir / "engine.trace.json")
    proc = _run(tdir)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "engine stage breakdown" in out
    assert "b8/tier0" in out
    assert "queue" in out and "readback" in out
    # Host-only capture: the engine host track is shown too.
    assert "mano-serving-engine" in out


def test_engine_export_merges_with_xla_capture(tmp_path):
    """One dir holding an XLA device capture AND the engine span
    export reads as ONE report: device top-ops first, then the
    per-request stage breakdown."""
    tdir = _fixture(tmp_path)
    _engine_trace(tdir / "engine.trace.json")
    proc = _run(tdir)
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "/device:TPU:0:XLA Ops" in out       # device half
    assert "engine stage breakdown" in out      # host half
    assert out.index("XLA Ops") < out.index("engine stage breakdown")
    data = json.loads(_run(tdir, "--json").stdout)
    assert any(k.endswith("XLA Ops") for k in data["tracks"])
    eng = data["engine"]
    block = next(iter(eng.values()))
    assert block["accounting"]["spans_closed"] == 3
    assert "b8/tier0" in block["stages"]["by_bucket_tier"]


def test_engine_export_unknown_schema_degrades(tmp_path):
    tdir = tmp_path / "trace"
    _engine_trace(tdir / "engine.trace.json")
    p = tdir / "engine.trace.json"
    data = json.loads(p.read_text())
    data["manoEngineTrace"]["schema"] = 99
    p.write_text(json.dumps(data))
    proc = _run(tdir)
    assert proc.returncode == 0, proc.stderr
    assert "schema 99 is not supported" in proc.stderr
    assert "engine stage breakdown" not in proc.stdout
    # The raw events still summarize as an ordinary host track.
    assert "mano-serving-engine" in proc.stdout
