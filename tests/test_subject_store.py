"""Tiered subject store (PR 16): device/host/disk paging + shard map.

The memory-hierarchy story, CPU-verified: warm demote→promote
roundtrips are bit-identical; warm overflow pages to cold and promotes
back THROUGH warm (inclusive tiers); a damaged cold page degrades to a
counted re-bake, never an error; a sharded lane fleet serving
cross-shard batches stays bit-identical to the single-device engine;
an evicted subject under a live stream re-bakes transparently;
``load()["subject_store"]`` is a one-lock-hold block; and the config19
drill protocol passes end-to-end at tiny sizes.

Canonical runner: `make subject-store-smoke` (own pytest process +
compile-cache dir, wired into `make check`) — slow-marked, so the
tier-1 `-m 'not slow'` lane skips it by design (the PR-8 budget
precedent); `make test` --ignore's it for the same reason.  The
pure-logic tests carry the `quick` mark too and ride the pre-commit
`make check-quick` lane.
"""

from __future__ import annotations

import numpy as np
import pytest

from mano_hand_tpu.serving.engine import ServingEngine
from mano_hand_tpu.serving.subject_store import (ROW_KEYS, SubjectStore,
                                                 SubjectStoreConfig,
                                                 shard_of, subject_digest)
from mano_hand_tpu.utils.profiling import ServingCounters

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def params32(params):
    return params.astype(np.float32)


def _betas(seed, n=10):
    return np.random.default_rng(seed).normal(size=(n,)).astype(np.float32)


def _row(seed, n_verts=8, n_joints=4, n_shape=10):
    rng = np.random.default_rng(seed)
    shape = rng.normal(size=(n_shape,)).astype(np.float32)
    return subject_digest(shape), {
        "v_shaped": rng.normal(size=(n_verts, 3)).astype(np.float32),
        "joints": rng.normal(size=(n_joints, 3)).astype(np.float32),
        "shape": shape,
    }


# ---------------------------------------------------------------- pure logic
@pytest.mark.quick
def test_shard_of_stable_in_range():
    d = subject_digest(_betas(0))
    assert shard_of(d, 4) == shard_of(d, 4)     # deterministic
    for n in (1, 2, 3, 8):
        assert 0 <= shard_of(d, n) < n
    # Uniform enough that 64 digests don't all land on one shard.
    hits = {shard_of(subject_digest(_betas(s)), 4) for s in range(64)}
    assert hits == {0, 1, 2, 3}
    with pytest.raises(ValueError):
        shard_of(d, 0)


@pytest.mark.quick
def test_config_validation():
    with pytest.raises(ValueError):
        SubjectStoreConfig(warm_capacity=0)
    st = SubjectStore(warm_capacity=4)
    assert not st.sharded
    assert st.shard_for(subject_digest(_betas(1))) is None


@pytest.mark.quick
def test_digest_is_content_addressed():
    a, b = _betas(0), _betas(0)
    assert subject_digest(a) == subject_digest(b)
    assert subject_digest(a) != subject_digest(_betas(1))


# ------------------------------------------------------------- store tiers
def test_warm_demote_promote_roundtrip():
    st = SubjectStore(warm_capacity=4)
    c = ServingCounters()
    st.bind(c)
    digest, row = _row(0)
    st.demote(digest, row)
    assert st.warm_digests() == [digest]
    # Prefetch starts the async host->device copy; fetch consumes it.
    assert st.prefetch(digest)
    got = st.fetch_row(digest)
    assert got is not None
    handles, tier = got
    assert tier == "warm"
    for k in ROW_KEYS:
        np.testing.assert_array_equal(np.asarray(handles[k]), row[k])
    snap = c.snapshot()
    assert snap["subject_store_warm_hits"] == 1
    assert snap["subject_store_prefetches"] == 1
    assert snap["subject_store_promotions"] == 1
    assert snap["subject_store_promotion_ms"]["n"] == 1
    # A row stays warm after promotion (inclusive tiers).
    assert st.warm_digests() == [digest]
    # Unknown digest: a plain miss, no exception.
    assert st.fetch_row("0" * 16) is None


def test_cold_roundtrip_inclusive_promotion(tmp_path):
    st = SubjectStore(warm_capacity=1, cold_dir=str(tmp_path),
                      backend="pickle")
    c = ServingCounters()
    st.bind(c)
    d0, r0 = _row(0)
    d1, r1 = _row(1)
    st.demote(d0, r0)
    st.demote(d1, r1)           # warm_capacity=1: d0 pages to cold
    assert st.warm_digests() == [d1]
    assert st.cold_digests() == [d0]
    assert st.cold_page_path(d0).exists()
    handles, tier = st.fetch_row(d0)
    assert tier == "cold"
    for k in ROW_KEYS:
        np.testing.assert_array_equal(np.asarray(handles[k]), r0[k])
    # Cold promotes THROUGH warm: d0 is now the warm resident (d1 was
    # paged out to make room) and the page remains on disk.
    assert st.warm_digests() == [d0]
    assert set(st.cold_digests()) == {d0, d1}
    snap = c.snapshot()
    assert snap["subject_store_cold_hits"] == 1
    assert snap["subject_store_demotions_cold"] == 2
    # Evicting d0 again does NOT rewrite its page (content-addressed):
    # the cold-demotion counter stays put.
    st.demote(*_row(2))
    assert c.snapshot()["subject_store_demotions_cold"] == 2


def test_damaged_cold_page_counted_rebake(tmp_path):
    from mano_hand_tpu.io import orbax_ckpt

    st = SubjectStore(warm_capacity=1, cold_dir=str(tmp_path),
                      backend="pickle")
    c = ServingCounters()
    st.bind(c)
    d0, r0 = _row(0)
    st.demote(d0, r0)
    st.demote(*_row(1))         # evict d0 to cold
    assert d0 in st.cold_digests()
    # A self-CONSISTENT page for the WRONG subject: per-array hashes
    # verify, the digest preimage does not.
    meta, arrays = orbax_ckpt.load_row_page(d0, str(tmp_path))
    arrays["shape"] = np.asarray(arrays["shape"]) + 1.0
    orbax_ckpt.save_row_page(d0, arrays, str(tmp_path), backend="pickle")
    assert st.fetch_row(d0) is None     # degrade, never raise
    assert c.snapshot()["subject_store_cold_damage"] == 1
    # One bad file costs ONE re-bake: the page left the index, so the
    # next access is a clean (uncounted-damage) miss.
    assert d0 not in st.cold_digests()
    assert st.fetch_row(d0) is None
    assert c.snapshot()["subject_store_cold_damage"] == 1


def test_store_adopts_existing_pages(tmp_path):
    d0, r0 = _row(0)
    first = SubjectStore(warm_capacity=1, cold_dir=str(tmp_path),
                         backend="pickle")
    first.bind(ServingCounters())
    first.demote(d0, r0)
    first.demote(*_row(1))
    assert d0 in first.cold_digests()
    # A new process's store adopts the pages a predecessor left.
    second = SubjectStore(warm_capacity=1, cold_dir=str(tmp_path),
                          backend="pickle")
    second.bind(ServingCounters())
    assert d0 in second.cold_digests()
    handles, tier = second.fetch_row(d0)
    assert tier == "cold"
    np.testing.assert_array_equal(np.asarray(handles["shape"]),
                                  r0["shape"])


def test_bind_twice_to_different_engines_raises():
    st = SubjectStore(warm_capacity=2)
    a, b = ServingCounters(), ServingCounters()
    st.bind(a)
    st.bind(a)                  # idempotent rebind: fine
    with pytest.raises(RuntimeError):
        st.bind(b)


# ---------------------------------------------------------- engine surgery
def test_cross_shard_batch_split_parity(params32, tmp_path):
    """Mixed-shard traffic through a 2-lane sharded fleet stays
    bit-identical to the single-device engine."""
    rng = np.random.default_rng(7)
    betas = [rng.normal(size=(params32.n_shape,)).astype(np.float32)
             for _ in range(6)]
    poses = [rng.normal(scale=0.4,
                        size=(2, params32.n_joints, 3)).astype(np.float32)
             for _ in range(12)]
    want = []
    with ServingEngine(params32, max_bucket=4,
                       max_delay_s=0.001) as ref:
        ref_keys = [ref.specialize(b) for b in betas]
        for i, p in enumerate(poses):
            want.append(ref.forward(p, subject=ref_keys[i % len(betas)]))
    store = SubjectStore(SubjectStoreConfig(
        warm_capacity=8, cold_dir=str(tmp_path), sharded=True,
        backend="pickle"))
    with ServingEngine(params32, max_bucket=4, max_delay_s=0.005,
                       lanes=2, subject_store=store) as eng:
        keys = [eng.specialize(b) for b in betas]
        # Both shards are populated (content-based placement over 6
        # digests), so coalesced windows mix owners and must split.
        shards = {store.shard_for(k) for k in keys}
        assert shards == {0, 1}
        futs = [eng.submit(p, subject=keys[i % len(betas)])
                for i, p in enumerate(poses)]
        got = [f.result(timeout=60) for f in futs]
        assert eng.load()["lanes"]["sharded"]
    worst = max(float(np.abs(g - w).max()) for g, w in zip(got, want))
    assert worst == 0.0


def test_eviction_under_stream_rebakes(params32):
    """A stream whose subject is evicted from the hot tier mid-session
    keeps producing bit-identical frames (store/warm re-bake)."""
    import jax.numpy as jnp

    from mano_hand_tpu.models import core

    rng = np.random.default_rng(11)
    betas0 = rng.normal(size=(params32.n_shape,)).astype(np.float32)
    # A STATIC target track (same joints every frame): the warm-started
    # fit re-converges to the same pose, so a re-baked frame must be
    # bit-identical to the first.
    pose_gt = np.zeros((1, params32.n_joints, 3), np.float32)
    target = np.asarray(core.jit_forward_batched(
        params32, jnp.asarray(pose_gt),
        jnp.asarray(betas0)[None]).posed_joints)[0]
    pose_frame = rng.normal(
        scale=0.4, size=(1, params32.n_joints, 3)).astype(np.float32)
    # Reference: the same two-frame warm-start chain with NO eviction.
    with ServingEngine(params32, max_bucket=2, max_delay_s=0.001,
                       max_subjects=8) as ref:
        with ref.open_stream(betas0, n_steps=4,
                             data_term="joints") as sess:
            want = [sess.submit_frame(target).result(timeout=60)
                    for _ in range(2)]
    store = SubjectStore(warm_capacity=8)
    with ServingEngine(params32, max_bucket=2, max_delay_s=0.001,
                       max_subjects=2, subject_store=store) as eng:
        with eng.open_stream(betas0, n_steps=4,
                             data_term="joints") as sess:
            first = sess.submit_frame(target).result(timeout=60)
            # Evict betas0's row: the 2-slot table takes 2 fresh
            # subjects, demoting the stream's row to the warm tier.
            for s in range(2):
                b = rng.normal(size=(params32.n_shape,)).astype(
                    np.float32)
                eng.forward(pose_frame, subject=eng.specialize(b))
            again = sess.submit_frame(target).result(timeout=60)
        c = eng.counters.snapshot()
    for got, ref_fr in ((first, want[0]), (again, want[1])):
        np.testing.assert_array_equal(np.asarray(got.verts),
                                      np.asarray(ref_fr.verts))
        np.testing.assert_array_equal(np.asarray(got.pose),
                                      np.asarray(ref_fr.pose))
    assert c["subject_store_demotions_warm"] >= 1


def test_load_subject_store_untorn(params32):
    """``load()["subject_store"]`` is present, complete, and internally
    consistent while demotions churn on another thread."""
    import threading

    store = SubjectStore(warm_capacity=4)
    with ServingEngine(params32, max_bucket=2, max_delay_s=0.001,
                       max_subjects=2, subject_store=store) as eng:
        assert eng.subject_store is store
        stop = threading.Event()

        def churn():
            rng = np.random.default_rng(23)
            while not stop.is_set():
                d, r = _row(int(rng.integers(0, 1 << 30)),
                            n_shape=params32.n_shape)
                store.demote(d, r)

        t = threading.Thread(target=churn)
        t.start()
        try:
            for _ in range(50):
                blk = eng.load()["subject_store"]
                assert set(blk) == {"warm_rows", "warm_capacity",
                                    "promotions_pending", "cold_pages",
                                    "cold_dir", "sharded", "shards"}
                assert 0 <= blk["warm_rows"] <= blk["warm_capacity"]
                assert blk["sharded"] is False
        finally:
            stop.set()
            t.join()
    # No store configured -> no block (absence is the signal).
    with ServingEngine(params32, max_bucket=2,
                       max_delay_s=0.001) as bare:
        assert "subject_store" not in bare.load()


def test_register_subjects_density(params32):
    """Betas-only registration: O(N) keys servable on demand without
    baking N device rows up front."""
    rng = np.random.default_rng(3)
    universe = rng.normal(size=(512, params32.n_shape)).astype(np.float32)
    with ServingEngine(params32, max_bucket=2, max_delay_s=0.001,
                       max_subjects=4) as eng:
        keys = eng.register_subjects(universe)
        assert len(keys) == 512
        assert keys == eng.register_subjects(universe)  # idempotent
        pose = rng.normal(scale=0.4,
                          size=(1, params32.n_joints, 3)).astype(
                              np.float32)
        got = eng.submit(pose, subject=keys[200]).result(timeout=60)
        want = eng.forward(pose, subject=eng.specialize(universe[200]))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tiny_drill_e2e(params32, tmp_path):
    """The config19 protocol end-to-end at plumbing size — the same
    artifact shape scripts/bench_report.py:judge_subject_store judges."""
    from mano_hand_tpu.serving.measure import subject_store_drill_run

    out = subject_store_drill_run(
        params32, subjects=300, requests_per_leg=16, lanes=2,
        max_subjects=8, warm_capacity=12, max_rows=2, max_bucket=4,
        pair_slice=8, workers=4, seed=0, cold_dir=str(tmp_path),
        backend="pickle")
    assert out["futures_resolved_fraction"] == 1.0
    assert out["outcomes"]["error"] == 0
    assert out["outcomes"]["stranded"] == 0
    for leg in out["legs"].values():
        assert leg["sharded_vs_reference_max_abs_err"] == 0.0
        if "replicated_vs_reference_max_abs_err" in leg:
            assert leg["replicated_vs_reference_max_abs_err"] == 0.0
    assert out["steady_recompiles"] == 0
    assert out["steady_recompiles_replicated"] == 0
    assert out["promotion_p99_within_window"]
    assert out["damage_probe"]["injected"]
    assert out["damage_probe"]["damage_counted"] >= 1
    assert out["damage_probe"]["request_max_abs_err"] == 0.0
    assert out["store_counters"]["subject_store_cold_hits"] >= 1
    rows_s = out["per_lane_device_rows_sharded"]
    rows_r = out["per_lane_device_rows_replicated"]
    assert max(rows_s) < min(rows_r)
    sp = out["spans"]
    assert sp["started"] == sp["closed"] and sp["open"] == 0
    assert out["lanes_sharded"] and out["subject_store"]["sharded"]
