"""Second-order registration of an UNCENTERED scan: LM + translation DOF.

Example 07 registers a centered cloud with the first-order pipeline; real
depth-sensor crops arrive in CAMERA coordinates — rigidly offset from the
model frame by an amount no pose articulation can absorb. This is the
round-5 LM answer, all second-order:

  1. closed-form Kabsch seed from 16 detected joints (one SVD: rotation
     AND the pivot-compensating translation);
  2. trimmed point-to-point ICP with ``fit_lm(fit_trans=True)`` — the
     translation column block is exact, so GN moves the rigid offset and
     the articulation together;
  3. one point-to-plane polish pass (normal-distance rows; the documented
     polish-only stage).

    python examples/18_uncentered_scan_lm.py [--platform cpu]
        [--points 500] [--offset 0.15] [--steps 15]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--points", type=int, default=500)
    ap.add_argument("--offset", type=float, default=0.15,
                    help="rigid offset magnitude, meters (a camera-frame "
                         "crop is typically decimeters off)")
    ap.add_argument("--noise", type=float, default=3e-4)
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--out", default="uncentered_registration.npz")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit_lm
    from mano_hand_tpu.fitting.initialize import initialize_from_joints
    from mano_hand_tpu.io.checkpoints import save_fit_result
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(7)

    # Ground truth: a posed hand, then the whole observation shifted into
    # a "camera frame" by a rigid offset.
    pose_true = rng.normal(scale=0.25, size=(16, 3)).astype(np.float32)
    offset = (args.offset * np.asarray([0.6, -0.3, 0.74])).astype(
        np.float32)
    truth = core.forward(params, jnp.asarray(pose_true), jnp.zeros(10))
    pick = rng.permutation(truth.verts.shape[0])[:args.points]
    cloud = (np.asarray(truth.verts)[pick] + offset
             + rng.normal(scale=args.noise, size=(len(pick), 3))
             ).astype(np.float32)
    joints_obs = (np.asarray(truth.posed_joints) + offset
                  + rng.normal(scale=2e-3, size=(16, 3))).astype(np.float32)

    # 1. Kabsch: rotation + translation in closed form from the detector
    #    joints (the offset lands almost entirely in seed["trans"]).
    seed = initialize_from_joints(params, jnp.asarray(joints_obs))
    print(f"Kabsch seed trans: {np.round(np.asarray(seed['trans']), 4)} "
          f"(true offset {np.round(offset, 4)})")

    # 2. Trimmed ICP with the translation DOF, warm-started by the seed.
    coarse = fit_lm(
        params, jnp.asarray(cloud), n_steps=args.steps,
        data_term="points", fit_trans=True, trim_fraction=0.05,
        shape_weight=0.1,
        init={"pose": seed["pose"], "trans": seed["trans"]},
    )

    # 3. Point-to-plane polish from the converged ICP state.
    polish = fit_lm(
        params, jnp.asarray(cloud), n_steps=max(3, args.steps // 3),
        data_term="point_to_plane", fit_trans=True, shape_weight=0.1,
        init={"pose": coarse.pose, "shape": coarse.shape,
              "trans": coarse.trans},
    )

    fitted = np.asarray(
        core.forward(params, polish.pose, polish.shape).verts
    ) + np.asarray(polish.trans)
    d = np.sqrt(((cloud[:, None] - fitted[None]) ** 2).sum(-1)).min(1)
    print(f"trans error:  {np.abs(np.asarray(polish.trans) - offset).max():.2e} m")
    print(f"cloud->mesh:  mean {d.mean():.2e} m, p95 "
          f"{np.quantile(d, 0.95):.2e} m")
    out_path = save_fit_result(polish, args.out)
    print(f"wrote {out_path}")
    # Registration must absorb the decimeter offset down to noise scale.
    ok = (np.abs(np.asarray(polish.trans) - offset).max() < 5e-3
          and float(np.quantile(d, 0.95)) < 5e-3)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
