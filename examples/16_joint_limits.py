"""Anatomical joint limits: corpus-derived bounds that wall off
hyperextension.

A 2D keypoint fit cannot tell a knuckle bent forward from one folded
backward — both project to the same pixels. The joint-limit prior
(`objectives.pose_limit_prior`) fixes the class of failure the
interior-shaping priors (l2 / Mahalanobis) cannot: it is exactly zero
inside a per-DOF axis-angle box and a squared hinge outside it, so it
never fights observations in range and only forbids the impossible.

The box comes from data — `pose_limits_from_corpus` over any pose
corpus (with official assets: the scan poses they ship). Nothing
anatomical is hardcoded in the framework.

    python examples/16_joint_limits.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import (
        fit, objectives, pose_limits_from_corpus,
    )
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(7)

    # 1. A pose corpus stands in for the official scan poses: flexion-only
    #    bends (x-axis positive rotations), the way real fingers move.
    corpus = np.zeros((500, 16, 3), np.float32)
    corpus[:, 1:, 0] = rng.uniform(0.0, 1.2, size=(500, 15))
    lo, hi = pose_limits_from_corpus(params, corpus, expand=0.15)
    print(f"corpus-derived bounds: lo in [{float(lo.min()):+.2f}, "
          f"{float(lo.max()):+.2f}], hi in [{float(hi.min()):+.2f}, "
          f"{float(hi.max()):+.2f}] rad")

    # 2. Ground truth inside the feasible box, observed only as 16 noisy
    #    3D joints (sparse data — the prior-hungry regime).
    true_pose = np.zeros((16, 3), np.float32)
    true_pose[1:, 0] = rng.uniform(0.2, 1.0, size=15)
    truth = core.forward(params, jnp.asarray(true_pose),
                         jnp.zeros(10, jnp.float32))
    noisy = np.asarray(truth.posed_joints) + rng.normal(
        scale=3e-3, size=(16, 3)).astype(np.float32)

    # 3. Fit with and without the wall. Same data, same steps.
    kw = dict(data_term="joints", n_steps=300, lr=0.05,
              shape_prior_weight=1e-3)
    res_free = fit(params, jnp.asarray(noisy), **kw)
    res_lim = fit(params, jnp.asarray(noisy),
                  joint_limits=(lo, hi), joint_limit_weight=1.0, **kw)

    def report(tag, res):
        flat = np.asarray(res.pose)[1:].reshape(-1)
        viol = np.maximum(np.asarray(lo) - flat, 0) \
            + np.maximum(flat - np.asarray(hi), 0)
        err = core.forward(params, res.pose, res.shape).posed_joints \
            - truth.posed_joints
        print(f"{tag} fit: joint err "
              f"{float(jnp.abs(err).max()) * 1e3:.2f} mm, "
              f"worst bound violation {float(viol.max()):.3f} rad")
        return float(viol.max())

    report("unconstrained", res_free)
    v = report("joint-limited", res_lim)
    assert v < 0.05, "limited fit escaped the admissible box"

    # 4. The hinge energy itself, directly: zero inside, quadratic out.
    inside = jnp.asarray((np.asarray(lo) + np.asarray(hi)) / 2)[None]
    assert float(objectives.pose_limit_prior(inside, lo, hi)) == 0.0
    print("hinge is exactly zero inside the box — the prior never "
          "fights in-range observations")
    return 0


if __name__ == "__main__":
    sys.exit(main())
