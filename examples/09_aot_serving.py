"""Deploy the forward as a self-contained AOT artifact (jax.export).

The serving story: compile the MANO forward once, serialize the StableHLO
program WITH the parameters baked in as constants, and run it anywhere jax
runs — no model asset, no package internals at inference time. One
artifact covers every batch size (symbolic batch dimension) and both CPU
and TPU (cross-platform lowering). With ``tip_vertex_ids`` the artifact
emits the 21-keypoint set detectors consume, in OpenPose order.

    python examples/09_aot_serving.py [--platform cpu]
"""

import argparse
import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--out", default="mano_fwd.jaxexp")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.io.export_aot import load_forward, save_forward
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)

    # -- export side: one call, one file ---------------------------------
    path = save_forward(
        params, args.out, tip_vertex_ids="smplx", keypoint_order="openpose"
    )
    print(f"wrote {path} ({os.path.getsize(path)} bytes, params baked in)")

    # -- serving side: load and run; no asset, any batch size ------------
    fwd = load_forward(path)
    print(repr(fwd))
    rng = np.random.default_rng(0)
    for batch in (1, 16):
        pose = jnp.asarray(
            rng.normal(scale=0.3, size=(batch, 16, 3)), jnp.float32
        )
        shape = jnp.asarray(rng.normal(size=(batch, 10)), jnp.float32)
        out = fwd(pose, shape)
        # Cross-check against the live forward: same program, same numbers.
        ref = core.forward_batched(params, pose, shape)
        err = float(jnp.abs(out["verts"] - ref.verts).max())
        print(
            f"batch={batch}: verts{tuple(out['verts'].shape)} "
            f"keypoints{tuple(out['keypoints'].shape)} "
            f"max err vs live forward {err:.2e}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
