"""Bulk offline registration: a stored dataset of target meshes, fitted
at throughput with the batched LM solver + the input pipeline.

The mocap post-processing workflow: thousands of captured frames on
disk, each needing (pose, shape) recovered — throughput matters, not
single-frame latency. The pieces composing here:

1. ``utils.data.batches`` slices the dataset into STATIC-shape batches
   (one XLA program total — a ragged tail would be a recompile);
2. ``utils.data.prefetch_to_device`` keeps the next batches' H2D copies
   in flight while the chip solves the current one;
3. ``fit_lm`` vmaps the damped Gauss-Newton solve across the batch —
   every frame in a batch converges in the same ~15 steps.

    python examples/20_bulk_registration.py [--platform cpu]
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--frames", type=int, default=512)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=15)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit_lm
    from mano_hand_tpu.models import core
    from mano_hand_tpu.utils.data import batches, prefetch_to_device

    params = synthetic_params(seed=0).astype(np.float32)

    # The "captured dataset": target vertex clouds for random poses.
    rng = np.random.default_rng(0)
    true_pose = rng.normal(scale=0.3, size=(args.frames, 16, 3)).astype(
        np.float32)
    true_beta = rng.normal(scale=0.5, size=(args.frames, 10)).astype(
        np.float32)
    targets = np.asarray(core.jit_forward_batched(
        params, jnp.asarray(true_pose), jnp.asarray(true_beta)).verts)
    print(f"dataset: {args.frames} frames of [778, 3] targets "
          f"({targets.nbytes / 2**20:.1f} MiB)")

    # Fit every batch through ONE compiled LM program; prefetch keeps the
    # next batch's transfer overlapped with the current solve.
    t0 = time.perf_counter()
    done = 0
    worst = 0.0
    for b in prefetch_to_device(
            batches({"target": targets}, batch_size=args.batch), size=2):
        res = fit_lm(params, b["target"], n_steps=args.steps)
        verts = core.jit_forward_batched(params, res.pose, res.shape).verts
        worst = max(worst, float(jnp.abs(verts - b["target"]).max()))
        done += len(b["target"])
    dt = time.perf_counter() - t0
    print(f"fit {done} frames in {dt:.2f} s "
          f"({done / dt:,.1f} frames/s, {args.steps} LM steps each); "
          f"worst vertex error {worst * 1e3:.4f} mm")
    assert worst < 1e-4
    return 0


if __name__ == "__main__":
    sys.exit(main())
