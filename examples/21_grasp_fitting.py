"""Hand-object grasp fitting: compose your own energy from the library's
objective terms.

The built-in solvers cover the common energies; when a workflow needs a
custom one — here, a hand grasping a RIGID OBJECT — the pure functions
compose directly into a jitted optax loop:

    E(theta, beta) = keypoint attraction        (objectives.joint_l2)
                   + object non-penetration     (objectives.inter_penetration
                                                 vs the object point cloud)
                   + pose prior                 (objectives.l2_prior)

The object term is the two-hand repulsion reused verbatim: a hinge on
hand-vertex-to-object-point distances inside a contact radius. Without
it, the keypoint fit drives fingers THROUGH the object; with it, the
hand wraps the surface (penetration drops orders of magnitude at
millimeter-level keypoint cost).

    python examples/21_grasp_fitting.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--steps", type=int, default=300)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import optax

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import objectives
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(21)

    # The grasp target: a curled pose, keypoints observed with noise.
    true_pose = np.zeros((16, 3), np.float32)
    true_pose[1:, 0] = rng.uniform(0.3, 0.9, size=15)
    truth = core.forward(params, jnp.asarray(true_pose),
                         jnp.zeros(10, jnp.float32))
    kp = np.array(core.keypoints(truth, "smplx"))
    kp = kp + rng.normal(scale=1.5e-3, size=kp.shape).astype(np.float32)

    # The rigid object: a small ball sitting against the palm — exactly
    # where a naive keypoint fit pushes vertices through.
    palm = np.asarray(truth.verts).mean(axis=0)
    centre = palm + np.float32([0.0, 0.015, 0.012])
    sph = rng.normal(size=(256, 3)).astype(np.float32)
    sph /= np.linalg.norm(sph, axis=1, keepdims=True)
    obj = jnp.asarray(centre + 0.012 * sph)   # r = 12 mm point cloud

    contact_r = 0.004  # hinge radius: "skin thickness" of the contact

    def penetration(verts):
        return objectives.inter_penetration(verts, obj, radius=contact_r)

    def energy(state, w_pen):
        out = core.forward(params, state["pose"], state["shape"])
        e_kp = objectives.joint_l2(
            core.keypoints(out, "smplx"), jnp.asarray(kp))
        return (e_kp + w_pen * penetration(out.verts)
                + 1e-3 * objectives.l2_prior(state["shape"]))

    def solve(w_pen):
        opt = optax.adam(0.02)
        state = {"pose": jnp.zeros((16, 3), jnp.float32),
                 "shape": jnp.zeros(10, jnp.float32)}
        opt_state = opt.init(state)

        @jax.jit
        def step(state, opt_state):
            loss, g = jax.value_and_grad(
                lambda s: energy(s, w_pen))(state)
            updates, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(state, updates), opt_state, loss

        for _ in range(args.steps):
            state, opt_state, loss = step(state, opt_state)
        out = core.forward(params, state["pose"], state["shape"])
        kp_err = float(jnp.abs(
            core.keypoints(out, "smplx") - jnp.asarray(kp)).max())
        return out, kp_err

    naive, kp_naive = solve(w_pen=0.0)
    pen_naive = float(penetration(naive.verts))
    grasp, kp_grasp = solve(w_pen=50.0)
    pen_grasp = float(penetration(grasp.verts))

    print(f"naive keypoint fit: kp err {kp_naive * 1e3:.2f} mm, "
          f"object penetration energy {pen_naive:.2e}")
    print(f"grasp fit (+object term): kp err {kp_grasp * 1e3:.2f} mm, "
          f"object penetration energy {pen_grasp:.2e} "
          f"({pen_naive / max(pen_grasp, 1e-12):.0f}x less)")
    assert kp_grasp < 0.01
    assert pen_grasp < pen_naive * 0.2 or pen_grasp < 1e-8
    return 0


if __name__ == "__main__":
    sys.exit(main())
