"""Train a 2D-to-3D lifter with MASK-ONLY supervision (no 3D labels).

The weakly-supervised setup differentiable rendering exists for: a
network maps noisy 2D keypoint detections to global rotation +
translation, the mesh head poses the hand, the soft rasterizer renders
it into TWO calibrated views, and the ONLY loss is silhouette IoU
against segmentation masks — no 3D pose, translation, or vertex labels
anywhere. Gradients flow network -> pose/trans -> FK/skinning ->
rasterizer -> IoU. Two views make translation (z included) observable;
a second view is cheaper than a single 3D label.

Tiny sizes so CI runs it; the structure is the real one.

    python examples/13_mask_supervised_training.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--size", type=int, default=24, help="mask resolution")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from mano_hand_tpu import ops
    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import objectives
    from mano_hand_tpu.models import core
    from mano_hand_tpu.viz import WeakPerspectiveCamera, view_rotation
    from mano_hand_tpu.viz.silhouette import soft_silhouette

    # Small asset: the rasterizer's [pixels, faces] slabs dominate the
    # step, and 96 faces keep CI fast with the full pipeline intact.
    params = synthetic_params(seed=0, n_verts=64, n_faces=96,
                              dtype=np.float32)
    h = w = args.size
    front = WeakPerspectiveCamera(rot=jnp.eye(3, dtype=jnp.float32),
                                  scale=3.0)
    side = WeakPerspectiveCamera(rot=view_rotation([0.0, np.pi / 2, 0.0]),
                                 scale=3.0)
    cams = (front, side)
    n_joints = params.j_regressor.shape[0]

    def pose_rotmats(rot6d):                     # [B, 6] global only
        """Full [B, 16, 3, 3] rotations: predicted global, rest fingers."""
        glob = ops.matrix_from_6d(rot6d)[:, None]          # [B, 1, 3, 3]
        eye = jnp.broadcast_to(
            jnp.eye(3, dtype=rot6d.dtype),
            (rot6d.shape[0], n_joints - 1, 3, 3),
        )
        return jnp.concatenate([glob, eye], axis=1)

    def geometry(rot6d, trans):
        out = core.forward_batched_rotmats(
            params, pose_rotmats(rot6d),
            jnp.zeros((rot6d.shape[0], params.shape_basis.shape[-1]),
                      rot6d.dtype),
        )
        verts = out.verts + trans[:, None, :]
        joints = out.posed_joints + trans[:, None, :]
        return verts, joints

    def render_views(verts):                     # [B, V, 3] -> [B, 2, H, W]
        return jnp.stack(
            [soft_silhouette(verts, params.faces, c, height=h, width=w,
                             sigma=1.0) for c in cams],
            axis=1,
        )

    def sample_batch(key, batch):
        """(noisy 2D keypoints, target masks, true trans, true rot6d)."""
        k1, k2, k3 = jax.random.split(key, 3)
        aa = 0.4 * jax.random.normal(k1, (batch, 3))       # global rot
        trans = 0.04 * jax.random.normal(k2, (batch, 3))
        rot6d_true = ops.matrix_to_6d(ops.rotation_matrix(aa[:, None, :])
                                      .reshape(batch, 3, 3))
        verts, joints = geometry(rot6d_true, trans)
        masks = (render_views(verts) > 0.5).astype(jnp.float32)
        kp2d = front.project(joints)[..., :2]
        kp2d = kp2d + 0.01 * jax.random.normal(k3, kp2d.shape)
        return kp2d, masks, trans, rot6d_true

    class LiftNet(nn.Module):
        """Noisy 2D keypoints -> (global 6D rotation, translation)."""

        @nn.compact
        def __call__(self, kp2d):                # [B, J, 2]
            x = kp2d.reshape(kp2d.shape[0], -1)
            for width in (96, 96):
                x = nn.relu(nn.Dense(width)(x))
            rot6d = nn.Dense(6)(x) + jnp.asarray(
                [1.0, 0, 0, 0, 1.0, 0], jnp.float32
            )
            trans = 0.1 * nn.Dense(3)(x)
            return rot6d, trans

    net = LiftNet()
    key = jax.random.PRNGKey(0)
    kp0 = sample_batch(key, args.batch)[0]
    variables = net.init(key, kp0)
    opt = optax.adam(2e-3)
    opt_state = opt.init(variables)

    @jax.jit
    def train_step(variables, opt_state, key):
        kp2d, masks, _, _ = sample_batch(key, args.batch)

        def loss_fn(v):
            rot6d, trans = net.apply(v, kp2d)
            verts, _ = geometry(rot6d, trans)
            sils = render_views(verts)
            # The ONLY supervision: per-view soft IoU against the masks.
            return jnp.mean(objectives.silhouette_iou_loss(sils, masks))

        loss, grads = jax.value_and_grad(loss_fn)(variables)
        updates, opt_state = opt.update(grads, opt_state, variables)
        return optax.apply_updates(variables, updates), opt_state, loss

    # The loss has an IRREDUCIBLE floor: a soft rendering scored against
    # a binarized mask pays for every fractional boundary pixel even at
    # the true pose (measured ~0.25 at these sizes). Judge training by
    # the EXCESS over that floor, computed from ground-truth geometry.
    kp_ev, masks_ev, trans_true, rot6d_ev = sample_batch(
        jax.random.PRNGKey(777), args.batch
    )
    verts_true, _ = geometry(rot6d_ev, trans_true)
    floor = float(jnp.mean(objectives.silhouette_iou_loss(
        render_views(verts_true), masks_ev
    )))

    losses = []
    for step in range(args.steps):
        key = jax.random.fold_in(key, step + 1)
        variables, opt_state, loss = train_step(variables, opt_state, key)
        if step % max(1, args.steps // 5) == 0 or step == args.steps - 1:
            losses.append(float(loss))
            print(f"step {step:4d}: 1 - IoU = {float(loss):.4f} "
                  f"(floor ~{floor:.3f})")

    excess0, excess1 = losses[0] - floor, losses[-1] - floor
    assert excess1 < 0.6 * excess0, (
        f"training did not close the gap to the floor: "
        f"{excess0:.4f} -> {excess1:.4f}"
    )
    # Held-out: translation error of the lifter — learned from masks
    # alone, never from a translation label.
    rot6d, trans = net.apply(variables, kp_ev)
    terr = float(jnp.mean(jnp.linalg.norm(trans - trans_true, axis=-1)))
    # No-information baseline: predicting zero translation.
    base = float(jnp.mean(jnp.linalg.norm(trans_true, axis=-1)))
    assert terr < 0.8 * base, (terr, base)
    print(f"trained (mask-only supervision): held-out mean translation "
          f"error {terr * 1e3:.1f} mm (predict-zero baseline "
          f"{base * 1e3:.1f} mm); excess-over-floor 1-IoU "
          f"{excess0:.3f} -> {excess1:.3f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
