"""Streaming (online) tracking: solve each frame as it arrives.

The causal counterpart of examples/05: no future frames, no joint clip
solve — each frame's inverse problem warm-starts from the previous
frame's solution, so a handful of second-order steps per frame keeps up
(``config5_track_ms_per_frame`` in bench.py measures the steady-state
latency). This is the live-sensor workflow; the reference's analogue is
its forward-only serial animation loop
(/root/reference/data_explore.py:12-15).

    python examples/08_streaming_tracking.py [--platform cpu]
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--frames", type=int, default=12)
    ap.add_argument("--steps", type=int, default=6,
                    help="LM steps per frame")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import make_tracker
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(8)
    t = args.frames

    # A smooth "sensor" clip: rest pose easing into a random grasp.
    end = rng.normal(scale=0.3, size=(16, 3)).astype("f")
    w = np.linspace(0.0, 1.0, t, dtype=np.float32)[:, None, None]
    true_poses = w * end[None]
    frames = np.asarray(core.jit_forward_batched(
        params, jnp.asarray(true_poses), jnp.zeros((t, 10), jnp.float32)
    ).verts)

    state, step = make_tracker(params, solver="lm", n_steps=args.steps)
    errs, times = [], []
    for i in range(t):
        t0 = time.perf_counter()
        state, res = step(state, frames[i])
        jax.block_until_ready(state.pose)
        times.append(time.perf_counter() - t0)
        got = core.jit_forward(params, state.pose, state.shape).verts
        errs.append(float(jnp.max(jnp.linalg.norm(
            got - frames[i], axis=-1))))
    # Frame 0 pays the compile; steady state is what a live loop sees.
    print(f"tracked {t} frames causally ({args.steps} LM steps each)")
    print(f"  first frame (compile): {times[0] * 1e3:8.1f} ms")
    print(f"  steady state:          {np.mean(times[1:]) * 1e3:8.1f} "
          f"ms/frame")
    print(f"  worst per-frame vertex error: {max(errs):.2e} m")
    return 0


if __name__ == "__main__":
    sys.exit(main())
