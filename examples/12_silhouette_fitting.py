"""Mask-based inverse MANO: fit translation + pose to segmentation masks.

The one supervision a segmenter provides with no keypoint detector: binary
[H, W] masks. The mesh is differentiably rasterized (SoftRas-style soft
silhouette, viz/silhouette.py) and scored by soft IoU. A single view
cannot observe depth — any outline-preserving motion is free — so this
example fits TWO calibrated weak-perspective views jointly (the
visual-hull setup, ``camera=(front, side)``): with the second view the
full 3D translation becomes observable, including the z that view one
cannot see.

    python examples/12_silhouette_fitting.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--size", type=int, default=32, help="mask resolution")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit, objectives
    from mano_hand_tpu.models import core
    from mano_hand_tpu.viz import WeakPerspectiveCamera, view_rotation
    from mano_hand_tpu.viz.silhouette import soft_silhouette

    params = synthetic_params(seed=0).astype(np.float32)
    h = w = args.size

    # Two calibrated views, 90 degrees apart around the vertical axis.
    front = WeakPerspectiveCamera(rot=jnp.eye(3, dtype=jnp.float32),
                                  scale=3.0)
    side = WeakPerspectiveCamera(rot=view_rotation([0.0, np.pi / 2, 0.0]),
                                 scale=3.0)
    cams = (front, side)

    # Ground truth: the hand displaced in all THREE axes. Binarize the
    # rendered silhouettes — the form real segmenter output takes.
    true_trans = jnp.asarray([0.03, 0.02, 0.04], jnp.float32)
    gt = core.forward(params)
    masks = jnp.stack([
        (soft_silhouette(gt.verts + true_trans, params.faces, c,
                         height=h, width=w, sigma=1.0) > 0.5
         ).astype(jnp.float32)
        for c in cams
    ])                                                     # [2, H, W]
    print(f"two {h}x{w} masks, {int(masks[0].sum())}/{int(masks[1].sum())} "
          "foreground px")

    res = fit(
        params, masks, n_steps=args.steps, lr=0.01,
        data_term="silhouette", camera=cams, sil_sigma=1.0,
        fit_trans=True, pose_prior_weight=1.0, shape_prior_weight=1.0,
    )
    trans = np.asarray(res.trans)
    err = np.linalg.norm(trans - np.asarray(true_trans))
    print(f"fit translation: {np.round(trans, 4).tolist()} "
          f"(true {np.round(np.asarray(true_trans), 4).tolist()}, "
          f"error {err * 1000:.1f} mm)")

    # Per-view IoU of the refit mesh against the target masks.
    refit = core.forward(params, res.pose, res.shape)
    for name, cam, mask in zip(("front", "side"), cams, masks):
        sil = soft_silhouette(refit.verts + res.trans, params.faces, cam,
                              height=h, width=w, sigma=1.0)
        iou = 1.0 - float(objectives.silhouette_iou_loss(sil, mask))
        print(f"{name} view soft IoU: {iou:.3f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
