"""Temporal tracking: fit a whole motion clip as one optimization problem.

Noisy per-frame 2D detections (with an occlusion) go in; a smooth,
temporally-coherent pose track with one shared shape comes out. The
squared-velocity smoothness priors let occluded frames borrow from their
neighbors, and the whole clip — every frame's forward and backward pass,
every Adam step — is one compiled XLA program.

    python examples/05_sequence_tracking.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--frames", type=int, default=12)
    ap.add_argument("--steps", type=int, default=400)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit_sequence
    from mano_hand_tpu.models import core
    from mano_hand_tpu.viz.camera import default_hand_camera

    params = synthetic_params(seed=0).astype(np.float32)
    camera = default_hand_camera()
    rng = np.random.default_rng(3)
    t = args.frames

    # Ground truth: a smooth pose track between two keyframes.
    a = rng.normal(scale=0.25, size=(16, 3)).astype("f")
    b = rng.normal(scale=0.25, size=(16, 3)).astype("f")
    w = np.linspace(0, 1, t, dtype=np.float32)[:, None, None]
    true_poses = (1 - w) * a + w * b
    gt = core.forward_batched(
        params, jnp.asarray(true_poses), jnp.zeros((t, 10), jnp.float32)
    )
    clean_xy = np.asarray(camera.project(gt.posed_joints)[..., :2])

    # Simulated detections: pixel noise everywhere, one joint occluded
    # (zero confidence, corrupted observation) for the middle third.
    observed = clean_xy + rng.normal(scale=2e-3, size=clean_xy.shape).astype("f")
    conf = np.ones((t, 16), "f")
    occ = slice(t // 3, 2 * t // 3)
    observed[occ, 7] += 3.0
    conf[occ, 7] = 0.0

    res = fit_sequence(
        params, observed, n_steps=args.steps, lr=0.02,
        data_term="keypoints2d", camera=camera, target_conf=conf,
        fit_trans=True, smooth_pose_weight=1e-2, smooth_trans_weight=1e-2,
        pose_prior_weight=1e-4,
    )

    out = core.forward_batched(
        params, res.pose, jnp.broadcast_to(res.shape, (t, 10))
    )
    track = np.asarray(
        camera.project(out.posed_joints + res.trans[:, None, :])[..., :2]
    )
    err = np.linalg.norm(track - clean_xy, axis=-1)
    print(f"tracked {t} frames x {args.steps} steps: "
          f"mean reprojection err {err.mean():.2e} NDC "
          f"(observation noise 2e-3)")
    print(f"occluded joint, occluded frames: {err[occ, 7].max():.2e} "
          "(bridged by temporal smoothness, not observed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
