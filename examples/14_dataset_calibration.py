"""Fit dataset-style annotations: pixel keypoints + mask + a real K matrix.

FreiHAND/HO-3D-style datasets ship a pixel-unit calibration matrix K,
OpenCV-convention pixel keypoints, and segmentation masks. This example
runs that workflow end to end: build the camera with ``from_intrinsics``,
convert the pixel keypoints ONCE with ``pixels_to_ndc``, fit the
combined detector+segmenter energy (keypoints pin the skeleton, the mask
soft-IoU refines the outline), and report mean reprojection error back
in PIXELS on the dataset image — the metric dataset leaderboards speak.

    python examples/14_dataset_calibration.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--steps", type=int, default=250)
    ap.add_argument("--size", type=int, default=48,
                    help="calibrated image size (square)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit
    from mano_hand_tpu.models import core
    from mano_hand_tpu.viz.camera import from_intrinsics
    from mano_hand_tpu.viz.silhouette import soft_silhouette

    params = synthetic_params(seed=0).astype(np.float32)
    s = args.size
    # A plausible calibration: ~2x image-width focal, principal point
    # slightly off center (real calibrations never sit exactly at W/2).
    K = np.array([[2.0 * s, 0.0, 0.52 * s],
                  [0.0, 2.0 * s, 0.47 * s],
                  [0.0, 0.0, 1.0]])
    cam = from_intrinsics(K, width=s, height=s, trans=(0.0, 0.0, 0.45))

    # "Dataset frame": ground truth the annotations were made from.
    true_t = jnp.asarray([0.02, -0.015, 0.0], jnp.float32)
    gt = core.forward(params)
    uv = np.asarray(cam.ndc_to_pixels(
        cam.project(gt.posed_joints + true_t)[..., :2]
    ))                                           # pixel keypoints
    mask = (soft_silhouette(gt.verts + true_t, params.faces, cam,
                            height=s, width=s, sigma=1.0) > 0.5
            ).astype(jnp.float32)                # segmentation mask
    print(f"{s}x{s} image, {int(mask.sum())} mask px, "
          f"keypoints in [{uv.min():.1f}, {uv.max():.1f}] px")

    res = fit(
        params, cam.pixels_to_ndc(jnp.asarray(uv, jnp.float32)),
        n_steps=args.steps, lr=0.02, data_term="keypoints2d", camera=cam,
        fit_trans=True, target_mask=mask, mask_weight=0.3,
        pose_prior_weight=1.0, shape_prior_weight=1.0,
    )
    out = core.forward(params, res.pose, res.shape)
    uv_fit = np.asarray(cam.ndc_to_pixels(
        cam.project(out.posed_joints + res.trans)[..., :2]
    ))
    px_err = float(np.linalg.norm(uv_fit - uv, axis=-1).mean())
    print(f"fit: mean reprojection error {px_err:.2f} px over "
          f"{uv.shape[0]} keypoints")
    assert px_err < 1.0, px_err
    return 0


if __name__ == "__main__":
    sys.exit(main())
