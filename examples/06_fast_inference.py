"""High-throughput inference: the fully-fused Pallas kernel paths.

The measured-fastest forward on a TPU v5e chip (9.4 M evals/s — 187x the
50 k evals/s target, docs/benchmarking.md) is the fully-fused Pallas kernel:
blendshapes + skinning in ONE kernel launch, blended vertices never leaving
VMEM. This example shows the three ways to consume it:

  * ``core.forward_batched_pallas_fused``   — one launch, moderate batches
  * ``core.forward_chunked(use_pallas_fused=True)`` — huge batches, bounded
    memory
  * ``parallel.pallas_forward_dp``          — the same kernel per-shard over
    a device mesh (multi-chip data parallelism, no collectives)

    python examples/06_fast_inference.py [--platform cpu]

On CPU the kernels run in the Pallas interpreter (functional, not fast);
on TPU they compile via Mosaic.
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--batch", type=int, default=4096)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu import parallel
    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.models import core
    from mano_hand_tpu.parallel import sharding as shd

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    interpret = not on_tpu  # CPU: Pallas interpreter; TPU: Mosaic-compiled
    params = synthetic_params(seed=0).astype(np.float32)

    rng = np.random.default_rng(0)
    b = args.batch if on_tpu else min(args.batch, 64)
    if b != args.batch:
        print(f"interpreter path: clamping --batch {args.batch} -> {b}")
    pose = jnp.asarray(rng.normal(scale=0.5, size=(b, 16, 3)), jnp.float32)
    beta = jnp.asarray(rng.normal(size=(b, 10)), jnp.float32)

    # 1. One fused-kernel launch. Differentiable: jax.grad flows through a
    #    hybrid custom VJP (including true parameter cotangents).
    fwd = jax.jit(lambda prm, p, s: core.forward_batched_pallas_fused(
        prm, p, s, interpret=interpret))
    verts = jax.block_until_ready(fwd(params, pose, beta))
    print(f"fused kernel: verts {verts.shape}")

    # Cross-check against the XLA path — the kernel must agree to <1e-4.
    want = core.forward_batched(params, pose, beta).verts
    err = float(jnp.abs(verts - want).max())
    print(f"max err vs XLA path: {err:.2e}")
    assert err < 1e-4

    # 2. Huge batches: chunked launches bound the live intermediate.
    big = core.forward_chunked(
        params, pose, beta, chunk_size=max(b // 4, 1),
        use_pallas_fused=True, interpret=interpret,
    )
    print(f"chunked fused: verts {big.shape}")

    # 3. Multi-chip shape: same kernel per batch shard over the mesh
    #    ('data' axis = all visible devices; 1 on a single chip).
    mesh = parallel.make_mesh()
    dp = shd.pallas_forward_dp(params, mesh, interpret=interpret)
    n_dev = mesh.size  # batch shards over every device in the mesh
    b_dp = (b // n_dev) * n_dev
    verts_dp = dp(pose[:b_dp], beta[:b_dp])
    print(f"sharded ({n_dev} device(s)): verts {verts_dp.shape}")

    if on_tpu:
        t0 = time.perf_counter()
        jax.block_until_ready(fwd(params, pose, beta))
        dt = time.perf_counter() - t0
        print(f"one warm launch: {dt * 1e3:.2f} ms wall "
              f"({b / dt:,.0f} evals/s incl. dispatch overhead; "
              "see docs/benchmarking.md for honest sustained numbers)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
