"""Quickstart: load an asset, run the forward pass, export a mesh.

Covers the reference's demo workflow (/root/reference/mano_np.py:205-219)
through the TPU-native API. Runs anywhere:

    python examples/01_quickstart.py [--platform cpu] [--asset path.npz]

Without a real MANO asset the synthetic generator stands in (same schema,
random arrays) — swap in a converted official asset via --asset.
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--asset", default="synthetic")
    ap.add_argument("--out", default="quickstart_hand.obj")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import load_model, synthetic_params
    from mano_hand_tpu.io import export_obj_pair
    from mano_hand_tpu.models import core

    params = (
        synthetic_params(seed=0) if args.asset == "synthetic"
        else load_model(args.asset)
    ).astype(np.float32)

    # One jitted forward: axis-angle pose [16, 3] + shape coeffs [10].
    rng = np.random.default_rng(0)
    pose = jnp.asarray(rng.normal(scale=0.3, size=(16, 3)), jnp.float32)
    shape = jnp.asarray(rng.normal(size=10), jnp.float32)
    out = core.jit_forward(params, pose, shape)
    print(f"verts {out.verts.shape}, joints {out.posed_joints.shape}, "
          f"device {jax.devices()[0].platform}")

    # Batched + differentiable come for free:
    batch = core.jit_forward_batched(
        params,
        jnp.asarray(rng.normal(scale=0.3, size=(64, 16, 3)), jnp.float32),
        jnp.zeros((64, 10), jnp.float32),
    )
    print(f"batched verts {batch.verts.shape}")

    export_obj_pair(np.asarray(out.verts), np.asarray(out.rest_verts),
                    np.asarray(params.faces), args.out)
    print(f"wrote {args.out} (+ restpose twin)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
