"""Inverse MANO: recover pose/shape from a target mesh or 3D keypoints.

The reference has no fitting at all; here it is a compiled optimization
loop (optax Adam in lax.scan, or damped Gauss-Newton) — zero host
round-trips per step, vmapped over a batch of independent problems.

    python examples/02_fitting.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--batch", type=int, default=8)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit, fit_lm, max_vertex_error
    from mano_hand_tpu.io.checkpoints import save_fit_result
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(1)

    # Ground truth to recover: a batch of random poses/shapes.
    true_pose = rng.normal(scale=0.3, size=(args.batch, 16, 3)).astype("f")
    true_shape = rng.normal(scale=0.5, size=(args.batch, 10)).astype("f")
    target = core.jit_forward_batched(
        params, jnp.asarray(true_pose), jnp.asarray(true_shape)
    )

    # 1. Dense: fit to the full 778-vertex mesh with Levenberg-Marquardt.
    res = fit_lm(params, target.verts, n_steps=20)
    out = core.forward_batched(params, res.pose, res.shape)
    err = float(np.max(np.asarray(
        jax.vmap(max_vertex_error)(out.verts, target.verts)
    )))
    print(f"LM mesh fit: worst max-vertex error {err:.2e} over "
          f"{args.batch} problems")

    # 2. Sparse: fit to 16 posed joints only (detector/mocap input).
    res_j = fit(params, target.posed_joints, n_steps=300, lr=0.05,
                data_term="joints", shape_prior_weight=1e-3)
    out_j = core.forward_batched(params, res_j.pose, res_j.shape)
    jerr = float(np.max(np.linalg.norm(
        np.asarray(out_j.posed_joints) - np.asarray(target.posed_joints),
        axis=-1,
    )))
    print(f"Adam joints fit: worst joint error {jerr:.2e}")

    path = save_fit_result(res, "fit_result")
    print(f"checkpointed LM fit -> {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
