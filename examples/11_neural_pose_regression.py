"""Train a neural hand-pose estimator THROUGH the differentiable mesh head.

The use case every torch MANO layer (manopth, smplx) exists for: a
network regresses pose from observations, the mesh head turns pose into
geometry, and the loss is on the geometry — gradients flow through
Rodrigues, FK, and skinning into the network weights. Here the whole
loop is JAX: `interop.flax_bridge.ManoLayer` (6D rotation output — the
standard continuous regression target) under `jax.jit` + `optax`.

The toy task: map noisy 21-keypoint detections to full pose, supervised
only by keypoint + vertex reconstruction (no pose labels — the mesh head
IS the decoder). Tiny sizes so it runs in CI; the structure is the real
one.

    python examples/11_neural_pose_regression.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--batch", type=int, default=64)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.interop.flax_bridge import ManoLayer
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)

    class PoseNet(nn.Module):
        """Keypoints -> 6D pose + shape, decoded by the MANO head.

        ``forward_full`` returns the complete ManoOutput, so ONE mesh-head
        pass serves both loss terms (verts and the 21 keypoints) — the
        head is the expensive differentiable part of the step.
        """

        @nn.compact
        def __call__(self, kp):                  # [B, 21, 3]
            x = kp.reshape(kp.shape[0], -1)
            for width in (128, 128):
                x = nn.relu(nn.Dense(width)(x))
            pose6d = nn.Dense(16 * 6)(x).reshape(-1, 16, 6)
            # Bias toward identity rotations: start at the rest pose.
            pose6d = pose6d + jnp.asarray(
                [1.0, 0, 0, 0, 1.0, 0], jnp.float32
            )
            shape = nn.Dense(params.shape_basis.shape[-1])(x)
            out = ManoLayer(params, pose_format="6d").forward_full(
                pose6d, shape
            )
            return out, shape

    def sample_batch(key, batch):
        kp_pose = jax.random.normal(key, (batch, 16, 3)) * 0.25
        out = core.forward_batched(
            params, kp_pose, jnp.zeros((batch, 10), jnp.float32)
        )
        kp = core.keypoints(out, "smplx")
        noise = jax.random.normal(
            jax.random.fold_in(key, 1), kp.shape
        ) * 0.002
        return kp + noise, out.verts, kp

    net = PoseNet()
    key = jax.random.PRNGKey(0)
    kp0, _, _ = sample_batch(key, args.batch)
    variables = net.init(key, kp0)
    opt = optax.adam(1e-3)
    opt_state = opt.init(variables)

    @jax.jit
    def train_step(variables, opt_state, key):
        kp_in, verts_gt, kp_gt = sample_batch(key, args.batch)

        def loss_fn(v):
            out, shape = net.apply(v, kp_in)
            kp_pred = core.keypoints(out, "smplx")
            return (
                jnp.mean(jnp.sum((out.verts - verts_gt) ** 2, axis=-1))
                + jnp.mean(jnp.sum((kp_pred - kp_gt) ** 2, axis=-1))
                + 1e-4 * jnp.mean(shape ** 2)
            )

        loss, grads = jax.value_and_grad(loss_fn)(variables)
        updates, opt_state = opt.update(grads, opt_state, variables)
        return optax.apply_updates(variables, updates), opt_state, loss

    losses = []
    for step in range(args.steps):
        key = jax.random.fold_in(key, step + 2)
        variables, opt_state, loss = train_step(variables, opt_state, key)
        if step % max(1, args.steps // 5) == 0 or step == args.steps - 1:
            losses.append(float(loss))
            print(f"step {step:4d}: loss {float(loss):.5f}")

    assert losses[-1] < 0.5 * losses[0], "training did not reduce the loss"
    # Held-out check: mean per-vertex error of the trained estimator.
    kp_in, verts_gt, _ = sample_batch(jax.random.PRNGKey(999), args.batch)
    out, _ = net.apply(variables, kp_in)
    mpve = float(jnp.mean(jnp.linalg.norm(out.verts - verts_gt, axis=-1)))
    print(f"trained: held-out mean per-vertex error {mpve * 1e3:.2f} mm "
          f"(loss {losses[0]:.4f} -> {losses[-1]:.4f})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
