"""Detector to engine: 21 noisy keypoints -> closed-form init ->
limit-constrained fit -> skinned glTF.

The full production path a pose-estimation stack needs, end to end:

1. a detector emits 21 noisy 3D keypoints of a hand rotated FAR from
   the rest orientation (the case that defeats cold-started local
   solvers);
2. ``initialize_from_joints`` recovers the global pose in ONE Kabsch
   SVD — no restart sweep;
3. the articulated fit runs with a corpus-derived anatomical joint-limit
   box (``pose_limits_from_corpus`` + the squared-hinge prior) walling
   off hyperextension the sparse keypoints cannot rule out;
4. the result ships as a SKINNED GLB (joint hierarchy, LBS weights,
   quaternion track) any engine can drive — not a baked mesh.

    python examples/17_detector_to_glb.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--out", default="detector_fit.glb")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import (
        fit, initialize_from_joints, pose_limits_from_corpus,
    )
    from mano_hand_tpu.io.gltf import export_glb_skinned
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(21)

    # Anatomical box from a flexion-style corpus (with official assets:
    # their scan poses via assets.scans.decode_scan_poses).
    corpus = np.zeros((400, 16, 3), np.float32)
    corpus[:, 1:, 0] = rng.uniform(0.0, 1.1, size=(400, 15))
    lo, hi = pose_limits_from_corpus(params, corpus)

    # "Detector output": 21 keypoints of a far-rotated, bent hand + noise.
    true_pose = np.zeros((16, 3), np.float32)
    true_pose[0] = [0.3, 2.8, 0.2]             # ~2.8 rad from rest
    true_pose[1:, 0] = rng.uniform(0.2, 0.9, size=15)
    truth = core.forward(params, jnp.asarray(true_pose),
                         jnp.zeros(10, jnp.float32))
    kp21 = np.asarray(core.keypoints(truth, "smplx")) \
        + rng.normal(scale=2e-3, size=(21, 3)).astype(np.float32)

    # 2. One SVD instead of a restart sweep.
    init = initialize_from_joints(params, jnp.asarray(kp21),
                                  tip_vertex_ids="smplx")
    print(f"Kabsch init: global rot |aa| = "
          f"{float(np.linalg.norm(init['pose'][0])):.2f} rad recovered "
          "closed-form")

    # 3. Articulated fit inside the anatomical box.
    res = fit(params, jnp.asarray(kp21), data_term="joints",
              tip_vertex_ids="smplx", n_steps=300, lr=0.03,
              shape_prior_weight=1e-3,
              joint_limits=(lo, hi), joint_limit_weight=1.0,
              init={"pose": init["pose"]})
    fitted = core.forward(params, res.pose, res.shape)
    kp_err = float(jnp.abs(
        core.keypoints(fitted, "smplx") - jnp.asarray(kp21)).max())
    flat = np.asarray(res.pose)[1:].reshape(-1)
    viol = max(float(np.maximum(np.asarray(lo) - flat, 0).max()),
               float(np.maximum(flat - np.asarray(hi), 0).max()))
    print(f"fit: keypoint err {kp_err * 1e3:.2f} mm, worst limit "
          f"violation {viol:.3f} rad")
    assert kp_err < 0.01 and viol < 0.05

    # 4. Ship the skeleton, not a baked mesh: pose clip = rest -> fit.
    clip = np.stack([np.zeros((16, 3), np.float32),
                     np.asarray(res.pose, np.float32)])
    rest = core.forward(params, jnp.zeros((16, 3), jnp.float32),
                        res.shape)
    path = export_glb_skinned(
        np.asarray(rest.verts), np.asarray(params.faces),
        np.asarray(rest.joints), params.parents,
        np.asarray(params.lbs_weights), args.out,
        pose_frames=clip, fps=2.0,
    )
    print(f"wrote skinned GLB to {path} (drivable joints, "
          "rest->fit clip)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
