"""Scan registration: fit pose/shape to a partial point cloud.

The classic depth-sensor workflow, correspondence-free: a synthetic "scan"
(a shuffled, subsampled, noisy view of a posed hand) is registered with the
canonical two-stage pipeline —

  1. coarse fit to 16 detected joints (well-conditioned, global);
  2. chamfer refinement against the raw points, warm-started from stage 1
     (ICP-family losses plateau from a cold start; the warm start is the
     point of the pipeline).

    python examples/07_scan_registration.py [--platform cpu]
        [--points 400] [--noise 0.0005] [--steps 300]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--points", type=int, default=400)
    ap.add_argument("--noise", type=float, default=5e-4,
                    help="per-point sensor noise sigma, meters")
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--out", default="registration.npz")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit
    from mano_hand_tpu.io.checkpoints import save_fit_result
    from mano_hand_tpu.models import core

    params = synthetic_params(seed=0).astype(np.float32)
    rng = np.random.default_rng(3)

    # Ground truth the "sensor" observed.
    pose_true = rng.normal(scale=0.3, size=(16, 3)).astype(np.float32)
    truth = core.forward(params, jnp.asarray(pose_true))
    verts = np.asarray(truth.verts)

    # The scan: half the surface, shuffled, with sensor noise. Nothing
    # reveals which mesh vertex any point came from.
    idx = rng.permutation(verts.shape[0])[: args.points]
    cloud = verts[idx] + rng.normal(scale=args.noise, size=(len(idx), 3))
    cloud = jnp.asarray(cloud, jnp.float32)

    # Stage 1: coarse joints fit (a keypoint detector's output).
    coarse = fit(params, truth.posed_joints, n_steps=200, lr=0.05,
                 data_term="joints", shape_prior_weight=1e-3)

    # Stage 2: chamfer refinement against the raw points.
    res = fit(params, cloud, n_steps=args.steps, lr=0.01,
              data_term="points", robust="huber", robust_scale=0.01,
              shape_prior_weight=1e-3, pose_prior_weight=1e-4,
              init={"pose": coarse.pose, "shape": coarse.shape})
    jax.block_until_ready(res.pose)

    from mano_hand_tpu.fitting import objectives

    out = core.forward(params, res.pose, res.shape)
    nn = np.sqrt(np.asarray(
        objectives.nearest_vertex_sq_dist(out.verts, cloud)
    ))
    path = save_fit_result(res, args.out)
    print(f"fit (two-stage, {args.steps} chamfer steps) -> {path}")
    print(f"scan-to-surface distance: mean {nn.mean() * 1e3:.2f} mm, "
          f"worst {nn.max() * 1e3:.2f} mm over {len(idx)} points "
          f"(sensor noise {args.noise * 1e3:.2f} mm)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
