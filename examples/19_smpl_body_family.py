"""Body models on the same engine: SMPL-family asset -> batched forward
-> pose recovery -> OBJ export.

The compute core is topology-generic (level-parallel FK over any
topologically ordered tree, blendshapes by contraction), so a 24-joint
SMPL-scale body is just a bigger asset for the SAME jitted programs the
hand runs — no body-specific code path exists anywhere:

1. write an official-style SMPL body pickle (the same chumpy-era
   container as MANO: sparse ``J_regressor``, ``kintree_table`` with a
   uint32 root sentinel, no hand-PCA keys) and load it with
   ``assets.load_smpl_pickle`` / ``load_model`` sniffing;
2. run the batched JAX forward and check it against the f64 oracle;
3. recover a body pose from target vertices with the stock second-order
   solver (Gauss-Newton/LM with the analytic Jacobian) — the derivative
   assembly is as topology-generic as the forward;
4. export the posed body as OBJ (+ rest-pose twin, reference format).

With a real SMPL download the pickle-writing step disappears: point
``load_model`` at the official ``.pkl``. Everything here is synthetic
(schema-true random body) because model assets are license-gated.

    python examples/19_smpl_body_family.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--steps", type=int, default=15)
    ap.add_argument("--out", default="body.obj")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import pickle

    import jax.numpy as jnp
    import scipy.sparse as sp

    from mano_hand_tpu.assets import load_model
    from mano_hand_tpu.assets.synthetic import synthetic_params
    from mano_hand_tpu.fitting import fit_lm
    from mano_hand_tpu.io.obj import export_obj_pair
    from mano_hand_tpu.models import core, oracle

    # 1. An official-style body pickle (stand-in for SMPL_NEUTRAL.pkl).
    body64 = synthetic_params(seed=19, n_verts=437, n_joints=24,
                              n_shape=16, n_faces=870)
    raw = {
        "v_template": np.asarray(body64.v_template),
        "shapedirs": np.asarray(body64.shape_basis),
        "posedirs": np.asarray(body64.pose_basis),
        "J_regressor": sp.csc_matrix(np.asarray(body64.j_regressor)),
        "weights": np.asarray(body64.lbs_weights),
        "f": np.asarray(body64.faces, np.uint32),
        "kintree_table": np.stack([
            np.asarray([2**32 - 1] + list(body64.parents[1:]), np.uint32),
            np.arange(24, dtype=np.uint32),
        ]),
    }
    with open("SMPL_NEUTRAL.pkl", "wb") as f:
        pickle.dump(raw, f, protocol=2)
    body64 = load_model("SMPL_NEUTRAL.pkl")
    body = body64.astype(np.float32)
    print(f"loaded body asset: V={body.n_verts} J={body.n_joints} "
          f"S={body.n_shape} side={body.side}")

    # 2. Batched forward on the generic core, pinned against the oracle.
    rng = np.random.default_rng(0)
    pose = rng.normal(scale=0.25, size=(4, 24, 3)).astype(np.float32)
    beta = rng.normal(scale=0.5, size=(4, 16)).astype(np.float32)
    out = core.forward_batched(body, jnp.asarray(pose), jnp.asarray(beta))
    want = oracle.forward(body64, pose=pose[0].astype(np.float64),
                          shape=beta[0].astype(np.float64)).verts
    err = float(np.abs(np.asarray(out.verts[0]) - want).max())
    print(f"forward batch=4: verts {tuple(out.verts.shape)}, "
          f"max err vs f64 oracle {err:.2e}")
    assert err < 1e-4

    # 3. Pose recovery with the stock LM solver — same API as the hand;
    # the analytic Jacobian assembly is topology-generic too.
    target = out.verts[:1]
    res = fit_lm(body, target, n_steps=args.steps)
    v_err = float(jnp.abs(
        core.forward_batched(body, res.pose, res.shape).verts - target
    ).max())
    print(f"fit: LM recovered the body pose to {v_err * 1e3:.4f} mm max "
          f"vertex error in {args.steps} steps")
    assert v_err < 1e-4

    # 4. Ship it (posed + rest twin, reference OBJ format).
    posed = core.forward(body, res.pose[0], res.shape[0])
    export_obj_pair(np.asarray(posed.verts), np.asarray(posed.rest_verts),
                    np.asarray(body.faces), args.out)
    print(f"wrote {args.out} (+ rest-pose twin)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
