"""Image-space inverse MANO: fit pose + global translation to 2D keypoints.

Detector-style input — 16 joints observed only as 2D image points through a
pinhole camera — fitted by projecting the model's posed joints through the
same differentiable camera and descending the confidence-weighted
reprojection error. One compiled program; depth enters only through
perspective scaling, so priors and the translation DOF do the work the
missing third coordinate can't.

    python examples/04_keypoint2d_fitting.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--steps", type=int, default=400)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit
    from mano_hand_tpu.models import core
    from mano_hand_tpu.viz.camera import default_hand_camera

    params = synthetic_params(seed=0).astype(np.float32)
    camera = default_hand_camera()
    rng = np.random.default_rng(2)

    # Ground truth: a posed hand, translated off the origin.
    true_pose = rng.normal(scale=0.25, size=(16, 3)).astype("f")
    true_trans = np.array([0.03, -0.02, 0.05], "f")
    gt = core.forward(params, jnp.asarray(true_pose))
    keypoints_2d = camera.project(gt.posed_joints + true_trans)[..., :2]

    # Simulated detector confidences: one joint "occluded" (zero weight),
    # its observation corrupted — the fit must ignore it.
    conf = np.ones(16, "f")
    conf[9] = 0.0
    observed = np.asarray(keypoints_2d).copy()
    observed[9] += 5.0

    res = fit(
        params, observed, n_steps=args.steps, lr=0.02,
        data_term="keypoints2d", camera=camera, target_conf=conf,
        fit_trans=True, pose_space="pca", n_pca=15,
        pose_prior_weight=1e-4, shape_prior_weight=1e-3,
    )

    out = core.forward(params, res.pose, res.shape)
    reproj = np.asarray(
        camera.project(out.posed_joints + res.trans)[..., :2]
    )
    err = np.linalg.norm(reproj - np.asarray(keypoints_2d), axis=-1)
    print(f"2D keypoint fit: {args.steps} steps, "
          f"trusted-joint reprojection max err {err[conf > 0].max():.2e} NDC, "
          f"occluded joint err {err[9]:.2e} (excluded from the loss)")
    print(f"recovered translation {np.asarray(res.trans).round(4).tolist()} "
          f"vs true {true_trans.tolist()}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
