"""Two-hand animation clip -> rendered AVI, all on-device.

The reference loops set_params per frame into an external OpenGL viewer
(/root/reference/data_explore.py:8-18). Here the whole clip — both hands,
every frame — evaluates as one XLA program, renders with the built-in
z-buffer rasterizer, and writes a dependency-free AVI.

    python examples/03_two_hands_video.py [--platform cpu] [--frames 24]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="")
    ap.add_argument("--frames", type=int, default=24)
    ap.add_argument("--size", type=int, default=128)
    ap.add_argument("--out", default="two_hands.avi")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu import viz
    from mano_hand_tpu.assets import synthetic_pair
    from mano_hand_tpu.models import anim

    left, right = (p.astype(np.float32) for p in synthetic_pair(seed=0))

    # A keyframed wiggle, slerp-retimed to the requested frame count:
    # [T, 2, 16, 3] — frame-major, hand axis (L, R).
    rng = np.random.default_rng(2)
    keys = rng.normal(scale=0.35, size=(4, 2, 16, 3))
    poses = anim.resample_poses_slerp(
        keys.reshape(4, 2 * 16, 3), args.frames
    ).reshape(args.frames, 2, 16, 3)

    verts = anim.evaluate_two_hand_sequence(
        left, right, jnp.asarray(poses, jnp.float32)
    )  # [T, 2, 778, 3]
    print(f"evaluated {args.frames} frames x 2 hands: {verts.shape}")

    # Offset the hands apart and render both meshes per frame by
    # concatenating their geometry (faces of the right hand re-indexed).
    lv = np.asarray(verts[:, 0]) + np.array([-0.12, 0, 0], "f")
    rv = np.asarray(verts[:, 1]) + np.array([+0.12, 0, 0], "f")
    both = np.concatenate([lv, rv], axis=1)
    faces = np.asarray(left.faces)
    both_faces = np.concatenate([faces, faces + lv.shape[1]])

    frames = viz.render_sequence(both, both_faces,
                                 height=args.size, width=args.size)
    viz.write_avi(frames, args.out, fps=12)
    print(f"wrote {args.out} "
          f"({viz.read_avi_info(args.out)['n_frames']} frames)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
