"""Depth-image fitting, and WHY it exists: depth observes z; outlines don't.

The same scene fitted two ways from one camera: a silhouette fit (the
mask term) and a depth fit (the soft z-buffer term). The hand is
displaced along ALL three axes — including straight toward the camera.
The mask fit recovers the image-plane motion but is structurally blind
to z; the depth fit recovers all three axes, because the depth image IS
the z measurement. This is the experiment to run when choosing a data
term for sensor input.

    python examples/15_depth_fitting.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--steps", type=int, default=250)
    ap.add_argument("--size", type=int, default=32, help="image resolution")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_params
    from mano_hand_tpu.fitting import fit
    from mano_hand_tpu.models import core
    from mano_hand_tpu.viz.camera import default_hand_camera
    from mano_hand_tpu.viz.silhouette import soft_depth, soft_silhouette

    params = synthetic_params(seed=0).astype(np.float32)
    cam = default_hand_camera()              # pinhole: depth is meaningful
    s = args.size

    # Ground truth: displaced in x, y AND z (toward the camera).
    true_t = jnp.asarray([0.02, 0.015, 0.03], jnp.float32)
    gt = core.forward(params)

    depth_img = soft_depth(gt.verts + true_t, params.faces, cam,
                           height=s, width=s, sigma=1.0)
    depth_img = jnp.where(depth_img > 5.0, 0.0, depth_img)  # sensor holes
    mask = (soft_silhouette(gt.verts + true_t, params.faces, cam,
                            height=s, width=s, sigma=1.0) > 0.5
            ).astype(jnp.float32)
    n_valid = int((depth_img > 0).sum())
    print(f"{s}x{s} depth image ({n_valid} valid px) + mask "
          f"({int(mask.sum())} px); true displacement "
          f"{np.round(np.asarray(true_t), 3).tolist()} m")

    kw = dict(n_steps=args.steps, lr=0.01, camera=cam, sil_sigma=1.0,
              fit_trans=True, pose_prior_weight=1.0, shape_prior_weight=1.0)
    res_mask = fit(params, mask, data_term="silhouette", **kw)
    res_depth = fit(params, depth_img, data_term="depth", **kw)

    for name, res in (("silhouette", res_mask), ("depth", res_depth)):
        t = np.asarray(res.trans)
        z_err = abs(t[2] - float(true_t[2]))
        xy_err = float(np.linalg.norm(t[:2] - np.asarray(true_t[:2])))
        print(f"{name:10s} fit: xy err {xy_err * 1e3:5.1f} mm, "
              f"z err {z_err * 1e3:5.1f} mm "
              f"(trans {np.round(t, 4).tolist()})")

    z_mask = abs(float(res_mask.trans[2] - true_t[2]))
    z_depth = abs(float(res_depth.trans[2] - true_t[2]))
    # The structural claim, asserted: depth sees z; the outline doesn't.
    assert z_depth < 0.005, z_depth
    assert z_depth < 0.5 * z_mask, (z_depth, z_mask)
    print("depth fit pinned z; the mask fit could not — choose the "
          "depth term for sensor input")
    return 0


if __name__ == "__main__":
    sys.exit(main())
