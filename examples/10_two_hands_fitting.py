"""Joint two-hand fitting: one observation, two hands, no interpenetration.

The reference treats hands as two unrelated model instances evaluated in
separate calls (/root/reference/dump_model.py:48-49). Real two-hand data
is one frame containing both — and fitting them independently lets noisy
or sparse observations pull the meshes through each other. ``fit_hands``
solves both hands as one jitted problem over stacked parameters, with an
inter-penetration hinge that lets the fitted surfaces touch but not
overlap.

    python examples/10_two_hands_fitting.py [--platform cpu]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default="",
                    help="force a JAX platform, e.g. 'cpu'")
    ap.add_argument("--steps", type=int, default=250)
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_pair
    from mano_hand_tpu.fitting import fit_hands, inter_penetration
    from mano_hand_tpu.models import core

    left, right = synthetic_pair(seed=0)
    stacked = core.stack_params(
        left.astype(np.float32), right.astype(np.float32)
    )

    # Ground truth: two hands ALMOST touching (4 mm apart) — then observe
    # only their 21-keypoint skeletons, the typical detector output.
    rng = np.random.default_rng(0)
    pose = jnp.asarray(rng.normal(scale=0.2, size=(2, 16, 3)), jnp.float32)
    shape = jnp.zeros((2, 10), jnp.float32)
    out = jax.vmap(
        lambda prm, p, s: core.forward(prm, p, s)
    )(stacked, pose, shape)
    trans = jnp.asarray([[0.0, 0, 0], [0.004, 0, 0]], jnp.float32)
    targets = core.keypoints(out, "smplx") + trans[:, None, :]

    def report(label, res):
        o = jax.vmap(
            lambda prm, p, s: core.forward(prm, p, s)
        )(stacked, res.pose, res.shape)
        verts = o.verts + res.trans[:, None, :]
        kp = core.keypoints(o, "smplx") + res.trans[:, None, :]
        pen = float(inter_penetration(verts[0], verts[1], radius=0.004))
        fit_err = float(jnp.abs(kp - targets).max())
        print(f"{label}: keypoint err {fit_err * 1e3:.2f} mm, "
              f"penetration energy {pen:.3e}")
        return pen

    common = dict(n_steps=args.steps, lr=0.03, data_term="joints",
                  fit_trans=True, tip_vertex_ids="smplx",
                  shape_prior_weight=1e-3)
    pen_off = report(
        "without repulsion",
        fit_hands(stacked, targets, repulsion_weight=0.0, **common),
    )
    pen_on = report(
        "with repulsion   ",
        fit_hands(stacked, targets, repulsion_weight=20.0,
                  repulsion_radius=0.004, **common),
    )
    print(f"fit: repulsion cut penetration {pen_off / max(pen_on, 1e-12):.1f}x "
          "while the keypoints still fit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
