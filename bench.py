"""Benchmark harness: MANO forward throughput on the attached accelerator.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N, ...}
Everything else goes to stderr.

Baseline: the reference publishes no numbers (BASELINE.md); the target is
the north-star >= 50,000 forward evals/sec on one v5e chip with max vertex
error < 1e-4 vs the float64 NumPy oracle (/root/repo/BASELINE.json).

Covers the BASELINE.json config suite:
  1. single zero-pose eval (vs oracle)        — accuracy anchor
  2. batch=1024 random pose+shape             — throughput
  3. batch=65536, left+right interleaved      — throughput (chunked)
  4. pose-fitting batch=256, 100 Adam steps   — fitting throughput
  5. 120-frame x 2-hand temporal sequence     — latency
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASELINE_EVALS_PER_SEC = 50_000.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def timeit(fn, iters: int = 10, warmup: int = 2):
    """Median wall time of fn() (which must block until ready)."""
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--big-batch", type=int, default=65536)
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--fit-steps", type=int, default=100)
    ap.add_argument("--skip-fit", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_pair
    from mano_hand_tpu.fitting import fit
    from mano_hand_tpu.models import core, oracle

    dev = jax.devices()[0]
    log(f"device: {dev.platform}:{dev.device_kind}")

    left64, right64 = synthetic_pair(seed=0)
    right = right64.astype(np.float32).device_put()
    left = left64.astype(np.float32).device_put()
    rng = np.random.default_rng(0)

    results = {}

    # -- config 1: single zero-pose eval, accuracy vs oracle ----------------
    out1 = core.jit_forward(
        right, jnp.zeros((16, 3), jnp.float32), jnp.zeros(10, jnp.float32)
    )
    want = oracle.forward(right64)
    err0 = float(np.abs(np.asarray(out1.verts) - want.verts).max())
    results["config1_zero_pose_max_err"] = err0
    log(f"config1 zero-pose max err vs oracle: {err0:.3e}")

    # accuracy at random poses (8 samples)
    poses = rng.normal(scale=0.6, size=(8, 16, 3)).astype(np.float32)
    betas = rng.normal(size=(8, 10)).astype(np.float32)
    outs = core.jit_forward_batched(right, jnp.asarray(poses), jnp.asarray(betas))
    max_err = 0.0
    for i in range(8):
        w = oracle.forward(right64, pose=poses[i], shape=betas[i]).verts
        max_err = max(max_err, float(np.abs(np.asarray(outs.verts[i]) - w).max()))
    results["max_err_vs_numpy"] = max_err
    log(f"random-pose max err vs oracle: {max_err:.3e}")

    # -- config 2: batch=1024 ----------------------------------------------
    b2 = 1024
    pose2 = jnp.asarray(rng.normal(scale=0.6, size=(b2, 16, 3)), jnp.float32)
    beta2 = jnp.asarray(rng.normal(size=(b2, 10)), jnp.float32)
    fwd2 = jax.jit(lambda p, s: core.forward_batched(right, p, s).verts)
    t2 = timeit(lambda: jax.block_until_ready(fwd2(pose2, beta2)), args.iters)
    results["config2_b1024_evals_per_sec"] = b2 / t2
    log(f"config2 batch=1024: {b2 / t2:,.0f} evals/s ({t2 * 1e3:.2f} ms)")

    # -- config 3: batch=65536, left+right interleaved (chunked) ------------
    b3 = max(2, args.big_batch - (args.big_batch % 2))
    half = b3 // 2
    chunk = max(1, min(args.chunk, half))
    while half % chunk:  # clamp to a divisor so odd CLI args can't crash
        chunk -= 1
    pose3 = jnp.asarray(rng.normal(scale=0.6, size=(b3, 16, 3)), jnp.float32)
    beta3 = jnp.asarray(rng.normal(size=(b3, 10)), jnp.float32)

    def interleaved(p, s):
        # alternate hands by halves of each chunk: two param sets, one graph
        vl = core.forward_chunked(left, p[:half], s[:half], chunk)
        vr = core.forward_chunked(right, p[half:], s[half:], chunk)
        return vl, vr

    fwd3 = jax.jit(interleaved)
    t3 = timeit(lambda: jax.block_until_ready(fwd3(pose3, beta3)), args.iters)
    results["config3_b65536_evals_per_sec"] = b3 / t3
    log(f"config3 batch={b3} L+R: {b3 / t3:,.0f} evals/s ({t3 * 1e3:.1f} ms)")

    # -- config 4: pose fitting batch=256 -----------------------------------
    if not args.skip_fit:
        b4 = 256
        pose4 = rng.normal(scale=0.3, size=(b4, 16, 3)).astype(np.float32)
        beta4 = rng.normal(scale=0.5, size=(b4, 10)).astype(np.float32)
        targets = core.jit_forward_batched(
            right, jnp.asarray(pose4), jnp.asarray(beta4)
        ).verts

        def run_fit():
            res = fit(right, targets, n_steps=args.fit_steps, lr=0.05)
            jax.block_until_ready(res.pose)
            return res

        t4 = timeit(run_fit, iters=max(2, args.iters // 3), warmup=1)
        fit_evals = b4 * args.fit_steps  # fwd+bwd per step
        results["config4_fit_steps_per_sec"] = args.fit_steps / t4
        results["config4_fit_evals_per_sec"] = fit_evals / t4
        log(f"config4 fit b=256 x {args.fit_steps} steps: {t4 * 1e3:.1f} ms "
            f"({fit_evals / t4:,.0f} fwd+bwd evals/s)")

    # -- config 5: 120-frame two-hand temporal sequence ---------------------
    t_frames, hands = 120, 2
    pose5 = jnp.asarray(
        rng.normal(scale=0.4, size=(t_frames * hands, 16, 3)), jnp.float32
    )
    beta5 = jnp.zeros((t_frames * hands, 10), jnp.float32)

    def seq(p, s):
        vl = core.forward_batched(left, p[:t_frames], s[:t_frames]).verts
        vr = core.forward_batched(right, p[t_frames:], s[t_frames:]).verts
        return vl, vr

    fwd5 = jax.jit(seq)
    t5 = timeit(lambda: jax.block_until_ready(fwd5(pose5, beta5)), args.iters)
    results["config5_seq240_ms"] = t5 * 1e3
    log(f"config5 120f x 2 hands: {t5 * 1e3:.2f} ms "
        f"({t_frames * hands / t5:,.0f} evals/s)")

    # -- headline ------------------------------------------------------------
    headline = max(
        results["config2_b1024_evals_per_sec"],
        results["config3_b65536_evals_per_sec"],
    )
    line = {
        "metric": "mano_forward_evals_per_sec",
        "value": round(headline, 1),
        "unit": "evals/s",
        "vs_baseline": round(headline / BASELINE_EVALS_PER_SEC, 3),
        "max_err_vs_numpy": max_err,
        "device": f"{dev.platform}:{dev.device_kind}",
        "detail": {k: (float(f"{v:.5g}") if isinstance(v, float) else v)
                   for k, v in results.items()},
    }
    print(json.dumps(line), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
