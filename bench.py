"""Benchmark harness: MANO forward throughput on the attached accelerator.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N, ...}
Everything else goes to stderr. On ANY terminal failure (backend never came
up, all configs crashed) the line is still valid JSON:
    {"metric": ..., "value": null, "error": "..."}

Baseline: the reference publishes no numbers (BASELINE.md); the target is
the north-star >= 50,000 forward evals/sec on one v5e chip with max vertex
error < 1e-4 vs the float64 NumPy oracle (/root/repo/BASELINE.json).

Covers the BASELINE.json config suite:
  1. single zero-pose eval (vs oracle)        — accuracy anchor
  2. batch=1024 random pose+shape             — throughput
  3. batch=65536, left+right interleaved      — throughput (chunked)
  3b. Pallas fused-skinning kernel            — block-size sweep, best wins
  4. pose-fitting batch=256, 100 Adam steps   — fitting throughput
  5. 120-frame x 2-hand temporal sequence     — latency
  8. shape-specialization split               — pose-only vs full forward,
     and the frozen-betas (48-col) LM step vs the 58-col solve
  9. cross-subject coalescing                 — mixed-subject gathered
     engine dispatch vs per-subject-split dispatch (serving/measure.py)

Resilience: the axon TPU tunnel is flaky — backend init can fail OR hang.
Bring-up therefore probes `jax.devices()` in a SUBPROCESS (a hang there is
killable) with bounded minutes-scale retries before initializing in-process,
and each config is individually fault-isolated so one crash never zeroes the
whole run. SIGTERM/SIGINT at ANY point (the driver harness kills long runs
with `timeout`, which sends SIGTERM) still produce the one valid JSON line:
a signal handler emits the null artifact, releases the device lock, and
exits 128+signum — round 4 shipped without this and the driver captured an
empty stdout (BENCH_r04.json rc=124, parsed null).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

BASELINE_EVALS_PER_SEC = 50_000.0

# TPU v5e (v5 lite) single-chip roofline constants, public spec sheet:
# 197 TFLOP/s bf16 on the MXU, 819 GB/s HBM bandwidth. The model default
# is f32 Precision.HIGH (3 bf16 passes per matmul), so the practical f32
# ceiling is ~197/3 = 66 TFLOP/s — well below the bf16 peak;
# pct_of_v5e_bf16_roofline is the honest (conservative) denominator.
V5E_BF16_FLOPS = 197e12
V5E_HBM_BYTES_PER_S = 819e9

# NB: a site hook on this image re-sets jax_platforms at interpreter
# startup (overriding the env var), so platform selection must go through
# the config API — in the probe and in-process alike.
_PROBE_CODE = (
    "import jax;"
    "plat = {platform!r};"
    "plat and jax.config.update('jax_platforms', plat);"
    "d = jax.devices();"
    "print(d[0].platform + ':' + d[0].device_kind)"
)


def log(msg: str) -> None:
    global _LAST_PROGRESS
    _LAST_PROGRESS = time.time()
    print(msg, file=sys.stderr, flush=True)


_EMITTED = False  # guards the one-line contract across the signal path
_ACTIVE_LOCK = None  # the live DeviceLock, for signal-time release
_LIVE_PROBE = None  # the in-flight backend-probe child, for signal-time kill
_PARTIAL = None  # (results, errors, device_str, is_tpu) live in run_benchmarks
_FINAL_LINE = None  # the complete line once run_benchmarks finishes
_LAST_PROGRESS = time.time()  # bumped by log(); the watchdog's stall clock
_WATCHDOG_ARMED = False  # stall detection live only once a TPU backend is up
_EMERGENCY = False  # single-shot latch shared by signal guard + watchdog
_EMERGENCY_LOCK = threading.Lock()  # makes the latch a true test-and-set
_CLEANUP_DONE = False  # first emergency caller finished device cleanup

_OUTAGE_NOTE = ("tunnel outage — archived on-chip runs + provenance: "
                "bench_results/README.md; verdict tool: "
                "scripts/bench_report.py")


_EMIT_LOCK = threading.Lock()


def emit(line: dict) -> None:
    """The ONE stdout JSON line, NaN/inf scrubbed so it always parses.

    Single-shot across THREADS as well as call sites: the watchdog thread
    and main() can both reach their emit concurrently (e.g. the emit-by
    deadline firing just as run_benchmarks completes), so the
    check-flag/print pair must be atomic — the lock makes the second
    caller a no-op instead of a second stdout line. The acquire carries a
    timeout for the one case a lock can't serialize: a SIGNAL handler on
    the main thread interrupting main() mid-emit (frame suspended while
    holding the lock). Then _EMITTED is already True (flag is set before
    print), so the post-timeout check still suppresses a double line.
    """
    global _EMITTED

    def _finite(x):
        if isinstance(x, float) and not np.isfinite(x):
            return None
        if isinstance(x, dict):
            return {k: _finite(v) for k, v in x.items()}
        return x

    # Serialize BEFORE taking the lock/flag (a dumps TypeError must leave
    # the backstop armed), and flag BEFORE printing (a signal landing
    # between print and assignment must not double-emit; worst case flips
    # to a partial line only if the print itself dies mid-write).
    text = json.dumps(_finite(line))
    got = _EMIT_LOCK.acquire(timeout=10.0)
    try:
        if _EMITTED:
            return
        _EMITTED = True
        print(text, flush=True)
    finally:
        if got:
            _EMIT_LOCK.release()


def _null_line(error: str, outage: bool = False) -> dict:
    """The guaranteed-null artifact; ``outage=True`` adds the pointer to
    archived on-chip evidence (only honest on bring-up/kill paths — a
    run_benchmarks crash on a live backend is a code bug, not an outage)."""
    line = {"metric": "mano_forward_evals_per_sec", "value": None,
            "unit": "evals/s", "vs_baseline": None, "error": error}
    if outage:
        line["note"] = _OUTAGE_NOTE
    return line


def _salvage(error: str) -> dict | None:
    """A partial line from run_benchmarks' live dicts, or None.

    Shared by the signal guard and main()'s crash handler: a kill OR an
    unisolated exception mid-run must both preserve the configs already
    measured — on the flaky tunnel they may be the round's only on-chip
    numbers."""
    if _PARTIAL is None:
        return None
    try:
        # Snapshot the LIVE dicts first: the watchdog thread can salvage
        # while the main thread is still healthily inserting results
        # (emit-by deadline on a slow run). assemble_line both iterates
        # and mutates its results dict — doing that on the shared object
        # from another thread risks 'dict changed size during iteration',
        # which the except below would turn into a null line, silently
        # discarding every number already measured.
        results, errors, device_str, is_tpu = _PARTIAL
        line = assemble_line(dict(results), dict(errors), device_str,
                             is_tpu)
    except Exception:
        return None
    line["partial"] = True
    line["error"] = error
    return line


def _signal_guard(signum, frame) -> None:
    """Emit the guaranteed artifact line on SIGTERM/SIGINT, then exit:
    the completed line if the run finished, a partial salvage if configs
    completed, else the null line.

    The driver harness bounds `python bench.py` with `timeout` (SIGTERM at
    ~30 min); without this handler a kill mid-probe leaves an EMPTY stdout
    — the exact BENCH_r04 failure. Constraints, each load-bearing:
    - mask both signals first (a second delivery mid-handler must not
      re-enter);
    - every step wrapped — a reentrant-BufferedWriter print error must
      not abort the handler before cleanup/_exit;
    - kill any in-flight probe child (the harness `timeout` signals only
      bench.py itself; an orphaned probe would later touch the single
      TPU chip with no device lock held);
    - remove OUR priority claim even when the signal lands inside
      DeviceLock.__enter__'s flock wait (claim written, _ACTIVE_LOCK not
      yet assigned) — a dead driver's claim wedges builders for 2 h;
    - hard-exit via os._exit: no unwinding through JAX/subprocess frames.
    """
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, signal.SIG_IGN)
        except Exception:
            pass
    name = signal.Signals(signum).name
    _emergency_exit(f"killed by {name}", 128 + signum)


def _emergency_exit(cause: str, rc: int) -> None:
    """The shared last-resort path (signal guard AND watchdog thread):
    emit the best available artifact line — complete > partial salvage >
    null — release the device, and hard-exit (no unwinding through
    JAX/subprocess frames). Single-shot: a second caller (e.g. SIGTERM
    landing while the watchdog is mid-emergency) exits without a second
    line."""
    global _EMERGENCY, _CLEANUP_DONE
    # True test-and-set: the watchdog thread and the main-thread signal
    # handler can race into this function; a bare check-then-assign has a
    # bytecode gap the GIL can switch in, running the whole body twice
    # (double lock release, nondeterministic rc). acquire() with timeout:
    # a stuck holder must not deadlock the signal handler forever.
    got = _EMERGENCY_LOCK.acquire(timeout=5.0)
    try:
        first = not _EMERGENCY
        _EMERGENCY = True
    finally:
        if got:
            _EMERGENCY_LOCK.release()
    if not first:
        # Another caller is mid-emergency. Exiting instantly could cut
        # its artifact line mid-write (os._exit does not flush stdio) or
        # its device cleanup mid-release (a dead driver's priority claim
        # wedges builders for 2 h) — wait, bounded, for the WHOLE first
        # pass to finish. sleep releases the GIL so the other thread
        # keeps making progress.
        deadline = time.monotonic() + 20.0
        while not _CLEANUP_DONE and time.monotonic() < deadline:
            time.sleep(0.1)
        os._exit(rc)
    kind = "already-emitted"
    if not _EMITTED:
        if _FINAL_LINE is not None:
            # The run COMPLETED; the kill landed between lock release and
            # the final emit. The full line, unlabeled, is the truth.
            line, kind = _FINAL_LINE, "complete"
        else:
            line = _salvage(f"{cause} mid-run; value covers "
                            "only the configs completed before it")
            kind = "partial" if line is not None else "null"
        try:
            if line is not None:
                emit(line)
        except Exception:
            line, kind = None, "null"  # bad salvage must not cost the null
        if line is None and not _EMITTED:  # _EMITTED: print died mid-line
            try:
                emit(_null_line(f"{cause} before completion",
                                outage=True))
            except Exception:
                pass
    try:
        log(f"bench: {cause}; {kind} artifact emitted, exiting")
    except Exception:
        pass
    probe = _LIVE_PROBE
    if probe is not None:
        try:
            probe.kill()
        except Exception:
            pass
    try:
        lock = _ACTIVE_LOCK
        if lock is not None:
            lock.__exit__(None, None, None)
        else:
            # Claim written but lock object not yet visible (mid-__enter__):
            # pid-verified removal, same rule as DeviceLock.__exit__.
            from mano_hand_tpu.utils import devicelock as _dl
            with open(_dl.CLAIM_PATH) as f:
                if json.load(f).get("pid") == os.getpid():
                    os.remove(_dl.CLAIM_PATH)
    except Exception:
        pass
    _CLEANUP_DONE = True
    os._exit(rc)


def install_signal_guard() -> None:
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _signal_guard)


WATCHDOG_RC = 3  # distinct from null-run 1 / DeviceBusy 2 / signal 128+N


def start_watchdog(stall_s: float, emit_by_s: float, t0: float) -> None:
    """Arm the emergency watchdog THREAD — the unified
    ``runtime.supervise.Watchdog`` (PR 3: one audited implementation
    behind this, cli.py serve-bench, and any future long device loop).
    Two triggers, both of which the signal guard alone cannot cover —
    SIGTERM is insufficient here because Python signal handlers run only
    on the MAIN thread between bytecodes, and a tunnel drop leaves that
    thread blocked inside a C-level PJRT RPC that never reaches the next
    bytecode (observed live r5, 2026-08-01: TERM no-op, only SIGKILL
    landed, stdout would have died empty — the BENCH_r04 failure,
    resurrected). A daemon watchdog thread keeps running because the
    blocked RPC releases the GIL, so it can emit the salvage line and
    ``os._exit``:

    - **stall**: no progress (``log()`` call) for ``stall_s`` seconds.
      Armed only once a TPU backend is up (``arm_watchdog_stall``) — the
      hang class is tunnel-specific, and CPU/interpreter lanes have
      legitimately long quiet gaps on a busy 1-core box.
    - **deadline**: ``emit_by_s`` seconds of wall clock since ``t0``.
      The driver harness kills flagless runs at ~30 min; a slow-but-live
      run must emit what it has BEFORE that, not be cut mid-line.

    ``stall_s``/``emit_by_s`` of 0 disable the respective trigger; with
    both off (the CPU/interpreter lanes) no thread is spawned at all.
    """
    if not (stall_s or emit_by_s):
        return
    from mano_hand_tpu.runtime.supervise import Watchdog

    Watchdog(
        lambda cause: _emergency_exit(cause, WATCHDOG_RC),
        deadline_s=emit_by_s or None,
        stall_s=stall_s or None,
        t0=t0,
        progress=lambda: _LAST_PROGRESS,
        armed=lambda: _WATCHDOG_ARMED,
        name="bench-watchdog",
    ).start()


def arm_watchdog_stall() -> None:
    global _WATCHDOG_ARMED, _LAST_PROGRESS
    _LAST_PROGRESS = time.time()
    _WATCHDOG_ARMED = True


def bring_up_backend(retries: int, probe_timeout: float,
                     platform: str = "",
                     budget_s: float = 1200.0) -> str:
    """Probe backend init in a subprocess until it succeeds, then init here.

    A failed OR HUNG init in a child is recoverable (kill + retry with
    backoff); the same hang in this process would take the whole bench
    down, which is exactly what happened in round 1 (BENCH_r01 rc=1).
    Returns the probed 'platform:device_kind' string.

    Budget sizing: the driver harness kills `python bench.py` at ~30 min
    (BENCH_r04: rc=124 with the probe loop cut at 27 min), so the DEFAULT
    budget must leave the whole run — probe + compile + configs — inside
    that window: 20 min of probing, then give up with the valid null line.
    Round 4's 75-min default was strictly worse than round 3's null: it
    turned an outage into a truncated non-artifact. Hours-scale waiting
    belongs to the builder wrapper (scripts/bench_tpu_wait.sh), which
    passes its own --init-budget per attempt and retries for the whole
    deadline; the SIGTERM guard backstops any budget misjudgment either
    way.
    """
    global _LIVE_PROBE
    last_err = "no attempts"
    t0 = time.time()
    for attempt in range(retries):
        # Popen (not run) so the signal guard can kill an in-flight child:
        # an orphaned probe would touch the single TPU chip lock-free
        # after this process is gone. Signals are masked across the
        # spawn→assign window — a kill landing exactly there would
        # otherwise orphan the child the guard exists to reap.
        signal.pthread_sigmask(
            signal.SIG_BLOCK, {signal.SIGTERM, signal.SIGINT})
        try:
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 _PROBE_CODE.format(platform=platform)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
            _LIVE_PROBE = proc
        finally:
            signal.pthread_sigmask(
                signal.SIG_UNBLOCK, {signal.SIGTERM, signal.SIGINT})
        try:
            out, err = proc.communicate(timeout=probe_timeout)
            if proc.returncode == 0 and out.strip():
                dev = out.strip().splitlines()[-1]
                log(f"backend probe ok (attempt {attempt + 1}): {dev}")
                return dev
            last_err = (err.strip() or "empty probe output")[-400:]
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            last_err = f"probe hung > {probe_timeout:.0f}s (killed)"
        finally:
            _LIVE_PROBE = None
        elapsed = time.time() - t0
        wait = min(15.0 * (attempt + 1), 120.0)
        log(f"backend probe failed (attempt {attempt + 1}/{retries}, "
            f"{elapsed / 60.0:.1f}/{budget_s / 60.0:.0f} min): "
            f"{last_err}; retrying in {wait:.0f}s")
        if elapsed + wait + probe_timeout > budget_s:
            log("probe budget exhausted")
            break
        if attempt + 1 < retries:
            time.sleep(wait)
    raise RuntimeError(
        f"backend never came up after {time.time() - t0:.0f}s of probing: "
        f"{last_err}")


def flops_per_eval(v: int = 778, j: int = 16, s: int = 10, p: int = 135) -> float:
    """FLOPs for ONE forward eval on the fused path (mul+add counted as 2).

    Mirrors models/core.py:forward_fused — one [V*3, S+P] vertex matmul,
    joint regression collapsed to [J,3,S], Rodrigues + FK (small), and the
    fused skinning contraction (ops/lbs.py: weights x rot/t then per-vertex
    transform, the T[B,778,4,4] materialization of mano_np.py:112-115
    eliminated).
    """
    vertex_blend = 2.0 * (v * 3) * (s + p)
    joint_blend = 2.0 * j * 3 * s
    rodrigues = j * 60.0
    fk = (j - 1) * 60.0
    skin_rot = 2.0 * v * j * 9
    skin_t = 2.0 * v * j * 3
    vert_xform = v * (2.0 * 9 + 3)
    return (vertex_blend + joint_blend + rodrigues + fk
            + skin_rot + skin_t + vert_xform)


def timeit(fn, iters: int = 10, warmup: int = 2):
    """Median wall time of fn() (which must block until ready)."""
    from mano_hand_tpu.utils.profiling import time_jax_fn

    return time_jax_fn(fn, iters=iters, warmup=warmup)["median_s"]


def slope_time(run_m, m1: int, m2: int, iters: int = 5,
               min_delta_s: float = 0.030, max_m: int = 500_000):
    """Per-iteration device time of ``run_m(m)`` via adaptive two-point slope.

    The axon TPU tunnel adds a fixed ~70 ms sync overhead per dispatch with
    ms-scale jitter (and ``block_until_ready`` alone under-reports, returning
    at enqueue). So each measurement runs the workload m times INSIDE one
    jitted program, syncs on a scalar readback, and the (m2 - m1) slope
    cancels the fixed overhead — leaving honest sustained device time per
    workload pass.

    Adaptive part: a fast workload (e.g. one batch-1024 forward ~ 80 us) is
    invisible under the jitter at small m, so when the measured delta is
    below ``min_delta_s`` the repeat counts are scaled up — jumping straight
    to the scale the measured delta implies when it is positive — until the
    delta dominates noise or ``max_m`` / a 2 s-per-call budget is hit.
    """
    import math

    scale = 1
    while True:
        a, b = m1 * scale, m2 * scale
        t1 = timeit(run_m(a), iters=iters, warmup=1)
        t2 = timeit(run_m(b), iters=iters, warmup=1)
        delta = t2 - t1
        if delta >= min_delta_s:
            return delta / (b - a)
        # Delta lost in noise: grow the loop counts, bounded by max_m AND a
        # projected ~2.5 s-per-measurement budget (t2 scales at most
        # linearly in m). If no in-budget growth remains, the honest answer
        # is NaN — a below-noise delta is never reported as throughput
        # (that is exactly the round-1 inflated-headline failure mode).
        factor = (min(16, max(2, math.ceil(min_delta_s / delta)))
                  if delta > 0 else 8)
        factor = min(factor, max_m // b, int(2.5 / max(t2, 1e-9)))
        if factor < 2:
            log(f"WARNING: slope delta {delta * 1e3:.2f} ms still below the "
                f"{min_delta_s * 1e3:.0f} ms noise floor at m={b} with no "
                "in-budget rescale left — measurement unreliable, "
                "reporting NaN")
            return float("nan")
        scale *= factor
        log(f"slope delta {delta * 1e3:.2f} ms @ m=({a},{b}) lost in noise; "
            f"rescaling x{factor} -> m=({m1 * scale},{m2 * scale})")


def looped(jit_fn, m: int, *args):
    """Thunk running jit_fn(*args, m) and truly syncing via scalar D2H."""
    return lambda: float(jit_fn(*args, m))


def parse_mesh(spec: str):
    """'data=8' or 'data=4,model=2' -> dict of axis sizes."""
    out = {}
    for part in spec.split(","):
        k, _, val = part.partition("=")
        out[k.strip()] = int(val)
    return out


def _enable_compile_cache(locked: bool = True) -> None:
    """Persistent XLA compile cache for bench runs (.jax_bench_cache).

    Window math again: a cold full-sweep run pays ~dozens of TPU
    compilations at 20-40 s each — a large slice of the driver's ~30-min
    kill window. The builder wrapper's attempts warm this cache, so the
    driver's end-of-round run (same machine, same programs) starts warm.
    Safety vs the round-3 deserialize-segfault class: that crash needs
    (a) hundreds of live executables in one process (the test suite's
    conftest clears per module; a bench run compiles ~dozens) or (b) two
    processes sharing one cache dir concurrently — the device lock
    serializes real bench runs (``locked=False`` — an advisory-timeout
    driver proceeding UNLOCKED — skips the shared dir for a per-pid one
    so a wedged lock-holder can't share it), and the bench tests point
    MANO_BENCH_CACHE_DIR at their own tmp dirs.
    """
    import jax

    cache_dir = os.environ.get("MANO_BENCH_CACHE_DIR")
    if cache_dir:
        pass  # explicit override: the caller owns isolation (tests do)
    elif locked:
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_bench_cache")
    else:
        import atexit
        import shutil

        cache_dir = os.path.join("/tmp", f"mano_bench_cache_{os.getpid()}")
        # Per-pid dirs hold full executable blobs; repeated unlocked runs
        # during an outage must not steadily eat /tmp.
        atexit.register(shutil.rmtree, cache_dir, ignore_errors=True)
        log("lock-free run: per-pid compile cache (no warm reuse)")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    log(f"compile cache: {cache_dir}")


def run_benchmarks(args, device_str: str) -> dict:
    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_pair
    from mano_hand_tpu.fitting import fit, fit_lm
    from mano_hand_tpu.models import core, oracle

    # run_benchmarks is only entered after ensure_backend_up()'s
    # KILLABLE SUBPROCESS probe proved the backend answers (the
    # CLAUDE.md rule: a bare jax.devices() on a downed tunnel hangs
    # for hours and the probe must be killable) — by here the call is
    # a warm lookup, and the watchdog guards the rest of the run.
    dev = jax.devices()[0]       # analysis: allow(bare-devices)
    log(f"device: {dev.platform}:{dev.device_kind} "
        f"({len(jax.devices())} visible)")  # analysis: allow(bare-devices)
    is_tpu = dev.platform in ("tpu", "axon")
    # --pallas-interpret: run every kernel config through the Pallas
    # interpreter so the SWEEP LOGIC (config3b-3e, chunk mini-sweep,
    # winner re-measure) executes end-to-end in CI — a Python-level bug
    # in bench plumbing must not debut on the scarce real-chip window.
    # Rates measured this way are interpreter overhead, not perf.
    ikw = {"interpret": True} if args.pallas_interpret else {}

    left64, right64 = synthetic_pair(seed=0)
    right = right64.astype(np.float32).device_put()
    left = left64.astype(np.float32).device_put()
    rng = np.random.default_rng(0)

    results: dict = {}
    errors: dict = {}
    # Register the LIVE dicts for the signal guard: a kill mid-run then
    # salvages every config completed so far into a partial artifact.
    global _PARTIAL
    _PARTIAL = (results, errors, device_str, is_tpu)

    # Sections are REGISTERED here in source order and executed by the
    # runner at the bottom of this function in done-criteria-first order
    # (see the priority list there). All cross-section data flows through
    # `results` or the nonlocals each consumer section reads. Two known
    # deferral effects beyond the schedule itself: inline (non-section)
    # code now runs BEFORE every section (so observational probes must be
    # sections — see hbm_peak), and sections' rng draws land after all
    # inline draws, so input values differ draw-for-draw from pre-r5
    # artifacts (shape-bound rates are unaffected).
    _registered: list = []

    def section(name, fn):
        """Register one fault-isolated config for the ordered runner."""
        _registered.append((name, fn))

    def run_section(name, fn):
        """Fault-isolate one config; a crash records an error, not a wipe."""
        if args.mesh_scaling_only and name != "mesh_scaling":
            return
        if args.serving_only and name not in ("config7_serving",
                                              "config7_recovery",
                                              "config9_coalesce",
                                              "config10_overload",
                                              "config11_coldstart",
                                              "config12_tracing",
                                              "config13_metrics",
                                              "config14_posed_kernel",
                                              "config15_streams",
                                              "config16_lanes",
                                              "config17_precision",
                                              "config18_edge",
                                              "config19_subject_store",
                                              "config20_dispatch_pipeline",
                                              "config21_fleet",
                                              "config22_control",
                                              "config23_selfheal"):
            return
        try:
            fn()
        except Exception as e:  # noqa: BLE001 — isolation is the point
            msg = f"{type(e).__name__}: {e}"
            errors[name] = msg[:300]
            log(f"{name} FAILED: {msg[:600]}")

    # -- config 1: single zero-pose eval + random-pose accuracy --------------
    # Outputs stay ON DEVICE here; the np.asarray readbacks happen only
    # after every timed section. On the axon TPU tunnel the first
    # device->host readback permanently degrades all later dispatches in
    # the process to ~70 ms, so timing must complete before any D2H.
    poses = rng.normal(scale=0.6, size=(8, 16, 3)).astype(np.float32)
    betas = rng.normal(size=(8, 10)).astype(np.float32)
    out1 = outs = None

    def config1_warmup():
        nonlocal out1, outs
        out1 = core.jit_forward(
            right, jnp.zeros((16, 3), jnp.float32), jnp.zeros(10, jnp.float32)
        )
        outs = core.jit_forward_batched(
            right, jnp.asarray(poses), jnp.asarray(betas)
        )
        jax.block_until_ready((out1.verts, outs.verts))

    section("config1_warmup", config1_warmup)

    # Enter the tunnel's synchronous mode deterministically (the first D2H
    # readback flips it process-wide) and record the fixed sync overhead
    # that slope_time cancels out of every reported number.
    def sync_probe():
        tiny_sum = jax.jit(lambda x: x.sum())
        float(tiny_sum(jnp.zeros(4)))
        t_sync = timeit(lambda: float(tiny_sum(jnp.zeros(4))),
                        iters=5, warmup=1)
        results["tunnel_sync_ms"] = t_sync * 1e3
        log(f"tunnel fixed sync overhead: {t_sync * 1e3:.1f} ms "
            "(cancelled by slope)")

    section("sync_probe", sync_probe)

    def loop_scalar(forward_sum):
        """m passes of forward_sum inside one program. forward_sum must
        return a FULL reduction (.sum()) of the output verts: the loop carry
        then depends on every batch element and vertex, so XLA can neither
        elide a pass, hoist it (input varies with i), nor slice-sink the
        batch away (a [0,0,0] probe would let the simplifier compute just
        one batch element)."""

        def run(prm_args, pose, shape, m):
            def body(i, acc):
                p = pose + i.astype(pose.dtype) * 1e-6
                return acc + forward_sum(prm_args, p, shape)

            return jax.lax.fori_loop(0, m, body, jnp.zeros((), pose.dtype))

        return jax.jit(run, static_argnums=3)

    # -- config 1 latency: single-eval device time --------------------------
    def config1_latency():
        pose1 = jnp.asarray(rng.normal(scale=0.5, size=(16, 3)), jnp.float32)
        beta1 = jnp.asarray(rng.normal(size=10), jnp.float32)
        fwd1 = loop_scalar(
            lambda prm, p, s: core.forward(prm, p, s).verts.sum()
        )
        # Single evals are dispatch-dominated through the tunnel; the slope
        # over in-program repeats isolates pure device time per eval.
        t1 = slope_time(lambda m: looped(fwd1, m, right, pose1, beta1),
                        8, 64, iters=max(1, args.iters // 2))
        results["config1_single_eval_us"] = t1 * 1e6
        log(f"config1 single eval: {t1 * 1e6:.1f} us device time")

    section("config1_latency", config1_latency)

    # -- config 2: batch=1024 ----------------------------------------------
    b2 = 1024
    pose2 = jnp.asarray(rng.normal(scale=0.6, size=(b2, 16, 3)), jnp.float32)
    beta2 = jnp.asarray(rng.normal(size=(b2, 10)), jnp.float32)

    def config2():
        fwd2 = loop_scalar(
            lambda prm, p, s: core.forward_batched(prm, p, s).verts.sum()
        )
        t2 = slope_time(lambda m: looped(fwd2, m, right, pose2, beta2), 1, 9,
                        iters=max(1, args.iters // 2))
        results["config2_b1024_evals_per_sec"] = b2 / t2
        log(f"config2 batch=1024: {b2 / t2:,.0f} evals/s ({t2 * 1e3:.2f} ms)")

    section("config2", config2)

    # -- config 2p: precision tradeoff (bf16-multipass cost on the MXU) -----
    # The model default is f32 Precision.HIGH (3 bf16 passes per matmul;
    # measured 3.8e-6 max vertex err on v5e — see ops/common.py). The two
    # variants bracket it: DEFAULT (single-pass bf16, fails the 1e-4 gate
    # at ~5e-4) and HIGHEST (6-pass, 2.8e-8, the accuracy reference).
    # Errors for both are measured post-timing in the accuracy section.
    outs_fast = outs_highest = None

    def _precision_variant(tag, prec):
        fwd2d = loop_scalar(
            lambda prm, p, s: core.forward_batched(
                prm, p, s, precision=prec
            ).verts.sum()
        )
        t2d = slope_time(lambda m: looped(fwd2d, m, right, pose2, beta2),
                         1, 9, iters=max(1, args.iters // 2))
        results[f"config2_{tag}_precision_evals_per_sec"] = b2 / t2d
        out = core.forward_batched(
            right, jnp.asarray(poses), jnp.asarray(betas), precision=prec
        )
        log(f"config2 precision={tag.upper()}: {b2 / t2d:,.0f} evals/s "
            f"({t2d * 1e3:.2f} ms)")
        return out

    def config2_precision():
        nonlocal outs_fast
        outs_fast = _precision_variant("default", jax.lax.Precision.DEFAULT)

    def config2_precision_highest():
        nonlocal outs_highest
        outs_highest = _precision_variant("highest",
                                          jax.lax.Precision.HIGHEST)

    section("config2_precision", config2_precision)
    section("config2_precision_highest", config2_precision_highest)

    # -- compiled cost analysis: XLA's own FLOP/byte count for config2 ------
    # Cross-checks the hand FLOP model (flops_per_eval) against what the
    # compiler actually scheduled; compile-only, nothing executes.
    def cost_analysis():
        fwd = jax.jit(lambda prm, p, s: core.forward_batched(prm, p, s).verts)
        ca = fwd.lower(right, pose2, beta2).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not ca:
            log("cost_analysis empty on this backend")
            return
        flops = float(ca.get("flops", 0.0))
        byts = float(ca.get("bytes accessed", 0.0))
        if flops:
            results["xla_flops_per_eval"] = flops / b2
        if byts:
            results["xla_hbm_bytes_per_eval"] = byts / b2
        log(f"XLA cost analysis (batch {b2}): {flops / b2:,.0f} FLOP/eval, "
            f"{byts / b2:,.0f} bytes/eval "
            f"(hand model: {flops_per_eval():,.0f} FLOP/eval)")

    section("cost_analysis", cost_analysis)

    # -- config 3: batch=65536, left+right interleaved (chunked) ------------
    b3 = max(2, args.big_batch - (args.big_batch % 2))
    half = b3 // 2
    chunk = max(1, min(args.chunk, half))  # forward_chunked auto-pads ragged
    pose3 = jnp.asarray(rng.normal(scale=0.6, size=(b3, 16, 3)), jnp.float32)
    beta3 = jnp.asarray(rng.normal(size=(b3, 10)), jnp.float32)

    def chunked_interleaved(chunk_size=None, **chunk_kw):
        """Full-batch two-hand workload, halves on separate param sets.

        chunk_size=half collapses host-side chunking to ONE launch per
        hand — for the full-fusion kernel the grid over batch tiles
        lives in-kernel, so lax.map sequencing is pure overhead there
        (VERDICT r3 item 3)."""
        ck = chunk if chunk_size is None else chunk_size

        def interleaved(prm_pair, p, s):
            pl, pr = prm_pair
            vl = core.forward_chunked(pl, p[:half], s[:half], ck,
                                      **chunk_kw)
            vr = core.forward_chunked(pr, p[half:], s[half:], ck,
                                      **chunk_kw)
            return vl.sum() + vr.sum()

        return interleaved

    def time_chunked(chunk_size=None, **chunk_kw):
        fwd3 = loop_scalar(chunked_interleaved(chunk_size, **chunk_kw))
        t3 = slope_time(lambda m: looped(fwd3, m, (left, right), pose3, beta3),
                        1, 3, iters=max(3, args.iters // 3))
        return b3 / t3, t3

    def config3():
        rate, t3 = time_chunked()
        results["config3_b65536_evals_per_sec"] = rate
        log(f"config3 batch={b3} L+R: {rate:,.0f} evals/s "
            f"({t3 * 1e3:.1f} ms)")

    section("config3", config3)

    # -- configs 3b/3c share one sweep harness ------------------------------
    def interleaved_rate(forward_fn, launch_b, iters):
        """Evals/s of a two-hand `forward_fn(params, pose, shape)` path at
        one launch size, slope-timed like every other config."""
        def interleaved(prm_pair, p, s):
            pl_, pr_ = prm_pair
            vl = forward_fn(pl_, p[:half][:launch_b], s[:half][:launch_b])
            vr = forward_fn(pr_, p[half:][:launch_b], s[half:][:launch_b])
            return vl.sum() + vr.sum()

        fwd = loop_scalar(interleaved)
        t = slope_time(
            lambda m: looped(fwd, m, (left, right), pose3, beta3),
            1, 5, iters=iters,
        )
        return 2 * launch_b / t

    def sweep_kernel(tag, make_fn, cfgs, base_launch):
        """Block-config sweep at base_launch, then a launch-size sweep at the
        winning config (bigger launches amortize grid setup and keep the MXU
        busier, until pre-stage intermediates start paying HBM round-trips).

        The winner is RE-MEASURED after the whole sweep and the re-measured
        rate is what gets reported: round 3's 19.6-vs-13.4 M evals/s
        winner flip between an isolated probe and the full-run sweep
        showed within-process drift the single first-touch measurement
        can't see. The first/re-measured pair is recorded per sweep as
        ``hysteresis_pct`` so drift is a number, not a mystery.
        Returns (best_rate, best_cfg, best_launch, stability_dict)."""
        iters = max(3, args.iters // 3)
        best = None
        per_cfg = {}
        for cfg in cfgs:
            try:
                rate = interleaved_rate(make_fn(*cfg), base_launch, iters)
                per_cfg[str(cfg)] = float(f"{rate:.5g}")
                log(f"{tag} {cfg}: {rate:,.0f} evals/s")
                if np.isfinite(rate) and (best is None or rate > best[0]):
                    best = (rate, cfg)
            except Exception as e:  # per-config isolation
                log(f"{tag} {cfg} failed: "
                    f"{type(e).__name__}: {str(e)[:200]}")
        if best is None:
            raise RuntimeError(f"no {tag} block config succeeded")
        best_launch = base_launch
        for launch_b in (16384, 32768):
            if launch_b > half or launch_b == base_launch:
                continue
            try:
                rate = interleaved_rate(make_fn(*best[1]), launch_b, iters)
                per_cfg[f"launch={launch_b}"] = float(f"{rate:.5g}")
                log(f"{tag} launch={launch_b}: {rate:,.0f} evals/s")
                if np.isfinite(rate) and rate > best[0]:
                    best = (rate, best[1])
                    best_launch = launch_b
            except Exception as e:
                log(f"{tag} launch {launch_b} failed: "
                    f"{type(e).__name__}: {str(e)[:200]}")
        first_rate = best[0]
        final_rate = first_rate
        try:
            remeasured = interleaved_rate(
                make_fn(*best[1]), best_launch, iters)
        except Exception as e:
            log(f"{tag} winner re-measure failed (keeping first): "
                f"{type(e).__name__}: {str(e)[:200]}")
            remeasured = float("nan")
        if np.isfinite(remeasured):
            final_rate = remeasured
            hyst = 100.0 * (first_rate - final_rate) / final_rate
        else:
            # A failed re-measure must not masquerade as zero drift: the
            # NaN survives into the record (emit() scrubs it to null).
            hyst = float("nan")
        stability = {"first": float(f"{first_rate:.5g}"),
                     "remeasured": (float(f"{remeasured:.5g}")
                                    if np.isfinite(remeasured)
                                    else float("nan")),
                     "hysteresis_pct": (float(f"{hyst:.3g}")
                                        if np.isfinite(hyst)
                                        else float("nan")),
                     "per_cfg": per_cfg}
        if np.isfinite(hyst) and abs(hyst) > 10.0:
            log(f"{tag} WARNING: winner drifted {hyst:+.1f}% between "
                "first measurement and re-measure — within-process state "
                "(cache/launch order) is moving the number")
        log(f"{tag} winner re-measured: {final_rate:,.0f} evals/s "
            f"(first {first_rate:,.0f}, drift {hyst:+.1f}%)")
        return final_rate, best[1], best_launch, stability

    def prove_vjp(forward_fn):
        """The kernel's fwd+bwd Mosaic lowering must EXECUTE on this backend
        (round-1 gap: only ever ran interpreted); correctness is tested."""
        import jax as _jax

        gfn = _jax.jit(_jax.grad(
            lambda p: forward_fn(right, p, beta2[:64]).sum()
        ))
        _jax.block_until_ready(gfn(pose2[:64]))

    # -- config 3b: Pallas fused-skinning kernel, block-size sweep ----------
    verts_pallas = None  # [8, V, 3] accuracy probe through the COMPILED kernel
    pallas_best = {}     # sweep winner, consumed by config3p below

    def config3b():
        nonlocal verts_pallas
        sweep = {
            "off": [],
            "quick": [core.PALLAS_BEST_BLOCK],
            # Trimmed to the configs that have ever been competitive.
            # Dropped, with their measured rates vs the same-run winner
            # (M evals/s, v5e, 2026-07-30 sweeps): (8,128) 2.56-2.94 and
            # (32,256) 4.77-5.57 vs winners 6.63-8.53; (64,512) 6.01 vs
            # 8.53. Each config costs ~2 min of driver wall clock; re-add
            # if a new chip generation changes the tiling calculus.
            "full": [(32, 128), (128, 128), (32, 896), (128, 256),
                     (64, 896), (128, 896), (16, 896)],
        }[args.pallas_sweep]
        if not sweep:
            return

        def make_fn(block_b, block_v):
            return lambda prm, p, s: core.forward_batched_pallas(
                prm, p, s, block_b=block_b, block_v=block_v, **ikw)

        b3b = min(half, 8192)  # one un-chunked pallas launch per hand
        rate, (bb, bv), best_launch, stab = sweep_kernel(
            "config3b pallas", make_fn, sweep, b3b)
        results["config3_pallas_evals_per_sec"] = rate
        results["pallas_best_block"] = f"b={bb},v={bv}"
        results["pallas_best_launch"] = best_launch
        results["pallas_sweep_stability"] = stab
        pallas_best["block"] = (bb, bv)
        log(f"config3b best: {rate:,.0f} evals/s at block_b={bb} "
            f"block_v={bv} launch={best_launch}")

        # Accuracy probe through the COMPILED kernel at the winning block,
        # under jit with params as traced args — the same compilation
        # context as the timed path. (An eager probe once missed an
        # XLA-level fold that zeroed the jitted path's bf16 residuals.)
        # Readback deferred to the accuracy section (D2H poisons axon
        # dispatch).
        verts_pallas = jax.jit(
            lambda prm, p, s: core.forward_batched_pallas(
                prm, p, s, block_b=bb, block_v=bv, **ikw)
        )(right, jnp.asarray(poses), jnp.asarray(betas))
        prove_vjp(make_fn(bb, bv))
        results["pallas_vjp_compiles"] = True
        log("config3b pallas VJP compiled + executed")

    section("config3b", config3b)

    # -- config 3p: full batch again, pallas-skinned chunks at the winning
    # block (runs after the sweep so it measures the per-chip best, not a
    # stale default).
    def config3_pallas_chunked():
        if args.pallas_sweep == "off":
            return
        bb, bv = pallas_best.get("block", core.PALLAS_BEST_BLOCK)
        rate, t3p = time_chunked(use_pallas=True, block_b=bb, block_v=bv,
                                 **ikw)
        results["config3_pallas_chunked_evals_per_sec"] = rate
        log(f"config3p batch={b3} L+R pallas chunks (b={bb},v={bv}): "
            f"{rate:,.0f} evals/s ({t3p * 1e3:.1f} ms)")

    section("config3_pallas_chunked", config3_pallas_chunked)

    # -- config 3c: fully-fused Pallas forward (blend + skin in ONE kernel,
    # ops/pallas_forward.py) — block_b x launch-size sweep, plus the full
    # 65536 batch through pallas-fused chunks at the winner.
    verts_fused = None   # accuracy probe through the COMPILED fused kernel
    fused_best = {}

    def config3c():
        nonlocal verts_fused
        if args.pallas_sweep == "off":
            return

        def make_fn(block_b):
            return lambda prm, p, s: core.forward_batched_pallas_fused(
                prm, p, s, block_b=block_b, **ikw)

        blocks = ([(core.FUSED_BEST_BLOCK_B,)]
                  if args.pallas_sweep == "quick"
                  else [(32,), (64,), (128,), (256,)])
        rate, (bb,), best_launch, stab = sweep_kernel(
            "config3c fused", make_fn, blocks, min(half, 8192))
        # config3d runs FIRST under the criteria-ordered runner and may
        # already have promoted its (faster) full-fusion rate into this
        # key; only overwrite when the pre-stage kernel actually wins,
        # and then drop the stale full_fusion variant tag.
        if rate > results.get("config3_fused_evals_per_sec", 0.0):
            results["config3_fused_evals_per_sec"] = rate
            results.pop("config3_fused_variant", None)
        results["fused_best_block_b"] = bb
        results["fused_best_launch"] = best_launch
        results["fused_sweep_stability"] = stab
        fused_best["block_b"] = bb
        log(f"config3c best: {rate:,.0f} evals/s at block_b={bb} "
            f"launch={best_launch}")

        # On-chip accuracy probe in the SAME compilation context as the
        # timed path (jit, params as traced args — see config3b note);
        # readback deferred to the accuracy section. Plus a VJP execute
        # proof for the hybrid backward.
        verts_fused = jax.jit(
            lambda prm, p, s: core.forward_batched_pallas_fused(
                prm, p, s, block_b=bb, **ikw)
        )(right, jnp.asarray(poses), jnp.asarray(betas))
        prove_vjp(make_fn(bb))
        results["fused_vjp_compiles"] = True
        log("config3c fused VJP compiled + executed")

    section("config3c", config3c)

    def config3_fused_chunked():
        if args.pallas_sweep == "off" or "block_b" not in fused_best:
            return
        rate, t3f = time_chunked(use_pallas_fused=True,
                                 block_b=fused_best["block_b"], **ikw)
        results["config3_fused_chunked_evals_per_sec"] = rate
        log(f"config3f batch={b3} L+R fused chunks "
            f"(block_b={fused_best['block_b']}): {rate:,.0f} evals/s "
            f"({t3f * 1e3:.1f} ms)")

    section("config3_fused_chunked", config3_fused_chunked)

    # -- config 3d: FULL-fusion kernel — Rodrigues + joint regression + FK
    # run IN-kernel too (ops/pallas_forward.py:forward_verts_fused_full),
    # eliminating the XLA pre-stage and its r/t slab HBM round-trips
    # (round-2 judge item #1). Same sweep harness; its own block default.
    verts_fused_full = None
    fused_full_best = {}

    def config3d():
        nonlocal verts_fused_full
        if args.pallas_sweep == "off":
            return

        def make_fn(block_b):
            return lambda prm, p, s: core.forward_batched_pallas_fused_full(
                prm, p, s, block_b=block_b, **ikw)

        # 512 exceeds v5e's 16M scoped-vmem limit (measured); the sweep's
        # per-config isolation would catch it anyway — not worth the slot.
        # 192 joined in r4: the 64-vs-128 winner flip (19.6 vs 13.4 M)
        # says the optimum sits in this range; one more probe point.
        blocks = ([(core.FUSED_FULL_BEST_BLOCK_B,)]
                  if args.pallas_sweep == "quick"
                  else [(32,), (64,), (128,), (192,), (256,)])
        rate, (bb,), best_launch, stab = sweep_kernel(
            "config3d fused-full", make_fn, blocks, min(half, 8192))
        results["config3_fused_full_evals_per_sec"] = rate
        results["fused_full_best_block_b"] = bb
        results["fused_full_best_launch"] = best_launch
        results["fused_full_sweep_stability"] = stab
        fused_full_best["block_b"] = bb
        log(f"config3d best: {rate:,.0f} evals/s at block_b={bb} "
            f"launch={best_launch}")

        # On-chip accuracy probe in the SAME compilation context as the
        # timed path; readback deferred to the accuracy section.
        verts_fused_full = jax.jit(
            lambda prm, p, s: core.forward_batched_pallas_fused_full(
                prm, p, s, block_b=bb, **ikw)
        )(right, jnp.asarray(poses), jnp.asarray(betas))
        prove_vjp(make_fn(bb))
        results["fused_full_vjp_compiles"] = True
        log("config3d fused-full VJP compiled + executed")

        # stack_skin variants at the winning block: the skinny K=16 skin
        # dots batched 4-way (per output coordinate, [4*TB, J]) or
        # 12-way ("full", [12*TB, J]) — same FLOPs, 4x/12x fewer MXU
        # pipeline fills on the skin stage (the profiled-blind candidate
        # for the ~5x headroom; interpret-parity pinned in
        # tests/test_pallas_forward.py). Measured with the sweep's
        # first/re-measure protocol; only a finite re-measured win is
        # promoted, after accuracy + VJP probes through the compiled
        # winning path.
        def make_fn_stacked(block_b, variant):
            return lambda prm, p, s: core.forward_batched_pallas_fused_full(
                prm, p, s, block_b=block_b, stack_skin=variant, **ikw)

        st_iters = max(3, args.iters // 3)
        best_stacked = None
        for variant, tag in ((True, "stacked"), ("full", "stacked12")):
            try:
                fn = make_fn_stacked(bb, variant)
                first = interleaved_rate(fn, best_launch, st_iters)
                remeas = interleaved_rate(fn, best_launch, st_iters)
                results[f"config3_fused_full_{tag}_evals_per_sec"] = remeas
                results[f"fused_full_{tag}_stability"] = {
                    "first": float(f"{first:.5g}"),
                    "remeasured": float(f"{remeas:.5g}"),
                    "hysteresis_pct": float(
                        f"{100.0 * (first / remeas - 1.0):.3g}")
                    if remeas else None,
                }
                log(f"config3d stack_skin={variant} at block_b={bb} "
                    f"launch={best_launch}: {remeas:,.0f} evals/s "
                    f"re-measured (first {first:,.0f}; "
                    f"{remeas / rate - 1:+.1%} vs unstacked)")
                if np.isfinite(remeas) and (
                        best_stacked is None or remeas > best_stacked[0]):
                    best_stacked = (remeas, variant)
            except Exception as e:
                log(f"config3d stack_skin={variant} failed: "
                    f"{type(e).__name__}: {str(e)[:200]}")
        if best_stacked is not None and best_stacked[0] > rate:
            rate_st, variant = best_stacked
            try:
                # Probes must SUCCEED before promotion mutates anything:
                # a VMEM overflow here (the 12-way product is untested on
                # hardware) keeps the valid unstacked headline intact.
                probe = jax.jit(
                    lambda prm, p, s: core.forward_batched_pallas_fused_full(
                        prm, p, s, block_b=bb, stack_skin=variant, **ikw)
                )(right, jnp.asarray(poses), jnp.asarray(betas))
                prove_vjp(make_fn_stacked(bb, variant))
            except Exception as e:
                log(f"config3d stack_skin={variant} won timing but its "
                    f"probe failed — keeping unstacked headline: "
                    f"{type(e).__name__}: {str(e)[:200]}")
            else:
                verts_fused_full = probe
                results["fused_full_stacked_vjp_compiles"] = True
                results["config3_fused_full_evals_per_sec"] = rate_st
                results["fused_full_variant"] = f"stack_skin={variant}"
                fused_full_best["stack_skin"] = variant
                rate = rate_st

        # The full-fusion kernel subsumes the XLA-pre-stage fused kernel
        # (same math, strictly more fusion): when faster, it IS the fused
        # forward path — promote it into the headline fused key and
        # record which variant produced the number.
        if rate > results.get("config3_fused_evals_per_sec", 0.0):
            results["config3_fused_evals_per_sec"] = rate
            results["config3_fused_variant"] = "full_fusion"

    section("config3d", config3d)

    # -- config 3e: BOTH hands in ONE full-fusion launch (hand-major grid,
    # ops/pallas_forward.py:forward_verts_fused_full_hands) — the two-hand
    # workload otherwise pays two sequenced launches per pass.
    verts_hands = None
    def config3e_hands():
        nonlocal verts_hands
        if args.pallas_sweep == "off" or "block_b" not in fused_full_best:
            return
        stacked = core.stack_params(left, right)
        bb = fused_full_best["block_b"]
        ss = fused_full_best.get("stack_skin", False)
        iters = max(3, args.iters // 3)
        best = None
        for launch in dict.fromkeys((min(half, 8192), half)):
            pose_h = jnp.stack([pose3[:half][:launch],
                                pose3[half:][:launch]])
            beta_h = jnp.stack([beta3[:half][:launch],
                                beta3[half:][:launch]])
            fwd = loop_scalar(
                lambda prm, p, s: core.forward_hands_pallas_fused_full(
                    prm, p, s, block_b=bb, stack_skin=ss, **ikw).sum()
            )
            try:
                t = slope_time(
                    lambda m: looped(fwd, m, stacked, pose_h, beta_h),
                    1, 5, iters=iters)
                rate = 2 * launch / t
                log(f"config3e hands launch={launch}: {rate:,.0f} evals/s")
                if np.isfinite(rate) and (best is None or rate > best[0]):
                    best = (rate, launch)
            except Exception as e:
                log(f"config3e launch {launch} failed: "
                    f"{type(e).__name__}: {str(e)[:200]}")
        if best is None:
            raise RuntimeError("no config3e launch succeeded")
        results["config3_fused_full_hands_evals_per_sec"] = best[0]
        results["fused_full_hands_launch"] = best[1]
        # Accuracy probe through the COMPILED hands kernel (same
        # compilation context as the timed path); hand 1 is `right`, the
        # oracle side checked in the accuracy section.
        verts_hands = jax.jit(
            lambda prm, p, s: core.forward_hands_pallas_fused_full(
                prm, p, s, block_b=bb, stack_skin=ss, **ikw)
        )(stacked, jnp.stack([jnp.asarray(poses)] * 2),
          jnp.stack([jnp.asarray(betas)] * 2))[1]

    section("config3e_hands", config3e_hands)

    def config3_fused_full_chunked():
        if args.pallas_sweep == "off" or "block_b" not in fused_full_best:
            return
        # Chunk-size mini-sweep: host chunking (lax.map at args.chunk)
        # exists to bound XLA-path intermediates, but the full-fusion
        # kernel grids over batch tiles IN-KERNEL — one launch per hand
        # over the whole half-batch removes the lax.map sequencing and
        # per-chunk operand prep entirely (VERDICT r3 item 3: bring the
        # named B=65536 config within 15% of the headline).
        bb = fused_full_best["block_b"]
        ss = fused_full_best.get("stack_skin", False)
        best = None
        for ck in dict.fromkeys((chunk, half)):
            try:
                rate, t3g = time_chunked(chunk_size=ck,
                                         use_pallas_fused_full=True,
                                         block_b=bb, stack_skin=ss, **ikw)
                tag = "single-launch" if ck == half else f"chunk={ck}"
                log(f"config3g batch={b3} L+R full-fusion {tag} "
                    f"(block_b={bb}): {rate:,.0f} evals/s "
                    f"({t3g * 1e3:.1f} ms)")
                if np.isfinite(rate) and (best is None or rate > best[0]):
                    best = (rate, ck)
            except Exception as e:
                log(f"config3g chunk={ck} failed: "
                    f"{type(e).__name__}: {str(e)[:200]}")
        if best is None:
            raise RuntimeError("no config3g chunk size succeeded")
        results["config3_fused_full_chunked_evals_per_sec"] = best[0]
        results["config3_fused_full_chunk_size"] = best[1]

    section("config3_fused_full_chunked", config3_fused_full_chunked)

    # -- optional: XLA profiler trace of the winning kernel ------------------
    def profile_kernels():
        # Captures the full-fusion winner (and the chunked B=65536 route)
        # under the XLA profiler so the HBM-roofline gap (VERDICT r3 #2:
        # kernel math bounds ~68 M evals/s, measured ~13-20 M) can be
        # attacked from a trace instead of guesses. Off by default; the
        # builder pipeline passes --profile so archived runs carry it.
        if not args.profile:
            return
        if "block_b" not in fused_full_best:
            log("profile skipped: no fused-full winner this run")
            return
        from mano_hand_tpu.utils.profiling import xla_trace

        bb = fused_full_best["block_b"]
        # Trace the kernel THAT WON — when stack_skin carries the
        # headline, an unstacked trace would describe the wrong program.
        ss = fused_full_best.get("stack_skin", False)

        def fn(prm, p, s):
            return core.forward_batched_pallas_fused_full(
                prm, p, s, block_b=bb, stack_skin=ss, **ikw)

        with xla_trace(args.profile):
            interleaved_rate(fn, min(half, 8192), 2)
            time_chunked(chunk_size=half, use_pallas_fused_full=True,
                         block_b=bb, stack_skin=ss, **ikw)
        results["profile_dir"] = args.profile
        log(f"xla profiler trace captured to {args.profile}")

    section("profile", profile_kernels)

    # -- config 4: pose fitting batch=256 -----------------------------------
    b4 = 256
    pose4 = rng.normal(scale=0.3, size=(b4, 16, 3)).astype(np.float32)
    beta4 = rng.normal(scale=0.5, size=(b4, 10)).astype(np.float32)
    fit_targets = None

    def config4():
        nonlocal fit_targets
        fit_targets = core.jit_forward_batched(
            right, jnp.asarray(pose4), jnp.asarray(beta4)
        ).verts

        def run_fit(steps):
            # fit is jitted with static n_steps; the whole Adam loop is one
            # lax.scan program, so the steps-count slope cancels sync cost.
            return lambda: float(
                fit(right, fit_targets, n_steps=steps,
                    lr=0.05).final_loss.sum()
            )

        s1, s2 = args.fit_steps // 2, args.fit_steps + args.fit_steps // 2
        t_step = slope_time(run_fit, s1, s2, iters=max(2, args.iters // 3))
        t4 = t_step * args.fit_steps
        fit_evals = b4 * args.fit_steps  # fwd+bwd per step
        results["config4_fit_steps_per_sec"] = 1.0 / t_step
        results["config4_fit_evals_per_sec"] = fit_evals / t4
        log(f"config4 fit b=256 x {args.fit_steps} steps: {t4 * 1e3:.1f} ms "
            f"({fit_evals / t4:,.0f} fwd+bwd evals/s)")

    def config4b_lm():
        # Second-order solver throughput: each LM step builds the [R, 58]
        # residual Jacobian + normal equations + batched LU solve per problem.
        # Default backend is the analytic assembly (fitting/jacobian.py,
        # measured 1.96x the jacfwd replay); record which one ran so the
        # number is attributable.
        if fit_targets is None:
            raise RuntimeError("config4 did not produce targets")
        lm_jacobian = "analytic"  # the one constant both the call and
        #   the recorded field read — they cannot drift apart.

        def run_lm(steps):
            return lambda: float(
                fit_lm(right, fit_targets, n_steps=steps,
                       jacobian=lm_jacobian).final_loss.sum()
            )

        t_step = slope_time(run_lm, 5, 15, iters=max(2, args.iters // 3))
        results["config4_lm_steps_per_sec"] = 1.0 / t_step
        results["config4_lm_jacobian"] = lm_jacobian
        log(f"config4b LM b={b4}: {1.0 / t_step:,.1f} steps/s "
            f"({t_step * 1e3:.2f} ms/step, analytic Jacobian)")

        # One-pass bf16 normal equations (fit_lm normal_eq="bf16", the
        # roadmap's next 200+ steps/s candidate): measure speed AND the
        # convergence ratio in the same compilation context — a silent
        # precision collapse must show up here, not in production.
        def run_lm_bf16(steps):
            return lambda: float(
                fit_lm(right, fit_targets, n_steps=steps,
                       jacobian=lm_jacobian,
                       normal_eq="bf16").final_loss.sum()
            )

        t_bf16 = slope_time(run_lm_bf16, 5, 15,
                            iters=max(2, args.iters // 3))
        results["config4_lm_bf16_steps_per_sec"] = 1.0 / t_bf16
        # Convergence probe at n_steps=15: REUSES the slope-timed
        # executables (n_steps is static on fit_lm — any other count
        # would be a fresh compile AND a different compilation context
        # than the timed path, against the CLAUDE.md numerics rule).
        loss_hi = float(fit_lm(right, fit_targets, n_steps=15,
                               jacobian=lm_jacobian).final_loss.mean())
        loss_bf = float(fit_lm(right, fit_targets, n_steps=15,
                               jacobian=lm_jacobian,
                               normal_eq="bf16").final_loss.mean())
        # The finite flag carries the collapse signal even when the ratio
        # is unrepresentable (NaN scrubs to null in the artifact, which
        # would look identical to "unmeasured").
        results["config4_lm_bf16_finite"] = bool(np.isfinite(loss_bf))
        results["config4_lm_bf16_loss_ratio"] = (
            loss_bf / max(loss_hi, 1e-30))
        log(f"config4b LM bf16-JtJ: {1.0 / t_bf16:,.1f} steps/s "
            f"(final-loss ratio vs high {loss_bf / max(loss_hi, 1e-30):.3g},"
            f" finite={np.isfinite(loss_bf)})")

    if not args.skip_fit:
        section("config4", config4)
        section("config4b_lm", config4b_lm)

    # -- config 8: the shape-specialization split ---------------------------
    # Full vs pose-only forward, and 58-col vs frozen-betas (48-col) LM.
    # Both halves compare the SAME numeric path with and without the baked
    # shape stage (models/core.py:specialize) — comparing across numeric
    # paths (fused vs staged) would conflate the fusion win with the
    # specialization win; the fused-full rate is config2/3's job.
    def config8_specialization():
        b8 = args.spec_batch
        pose8 = jnp.asarray(rng.normal(scale=0.6, size=(b8, 16, 3)),
                            jnp.float32)
        beta8 = jnp.asarray(rng.normal(size=(10,)), jnp.float32)
        beta8_b = jnp.broadcast_to(beta8, (b8, 10))
        shaped = jax.jit(core.specialize)(right, beta8)

        # Full side: betas are a per-call INPUT, so they must vary inside
        # the loop — with a loop-constant shape operand XLA hoists the
        # whole shape stage out of the fori_loop (loop-invariant code
        # motion, verified live) and the "full" side silently times the
        # pose-only program.
        def full_run(prm, pose, shape, m):
            def body(i, acc):
                pp = pose + i.astype(pose.dtype) * 1e-6
                ss = shape + i.astype(shape.dtype) * 1e-6
                out = jax.vmap(lambda q, s: core.forward(prm, q, s))(pp, ss)
                return acc + out.verts.sum()

            return jax.lax.fori_loop(0, m, body, jnp.zeros((), pose.dtype))

        full_j = jax.jit(full_run, static_argnums=3)

        def posed_run(sh, pose, m):
            def body(i, acc):
                pp = pose + i.astype(pose.dtype) * 1e-6
                return acc + core.forward_posed_batched(sh, pp).verts.sum()

            return jax.lax.fori_loop(0, m, body, jnp.zeros((), pose.dtype))

        posed_j = jax.jit(posed_run, static_argnums=2)

        def paired_slope(run_a, run_b, m1, m2, trials,
                         min_delta_s=0.030):
            """Two-point slope for BOTH sides of a comparison, with the
            serving leg's drift defense (serving/measure.py) applied to
            slope timing: each trial measures all four points
            INTERLEAVED (order alternating), the per-point estimate is
            the min over trials, and the slopes come from those mins —
            on this busy 1-core box a sequential pair of slope_time
            calls hands one side the load spike and the ratio lies
            (observed live: 0.86x..3.1x scatter for the same programs).
            Shares slope_time's adaptive rescale: grow the loop counts
            until both deltas clear the noise floor.
            """
            scale = 1
            while True:
                a, b = m1 * scale, m2 * scale
                thunks = {"a1": run_a(a), "a2": run_a(b),
                          "b1": run_b(a), "b2": run_b(b)}
                for th in thunks.values():  # compile + settle
                    th()
                best = {k: float("inf") for k in thunks}
                for t in range(trials):
                    keys = sorted(thunks) if t % 2 == 0 \
                        else sorted(thunks, reverse=True)
                    for k in keys:
                        t0 = time.perf_counter()
                        thunks[k]()
                        best[k] = min(best[k],
                                      time.perf_counter() - t0)
                d_a = best["a2"] - best["a1"]
                d_b = best["b2"] - best["b1"]
                if min(d_a, d_b) >= min_delta_s:
                    return d_a / (b - a), d_b / (b - a)
                # Same growth policy as slope_time: bounded by a
                # ~2.5 s-per-call budget; below-noise never reports.
                worst = max(best["a2"], best["b2"])
                factor = min(8, int(2.5 / max(worst, 1e-9)))
                if factor < 2:
                    log("WARNING: paired slope still below the noise "
                        f"floor at m={b} with no in-budget rescale "
                        "left — reporting NaN")
                    return float("nan"), float("nan")
                scale *= factor
                log(f"paired slope delta ({d_a * 1e3:.1f}, "
                    f"{d_b * 1e3:.1f}) ms lost in noise; rescaling "
                    f"x{factor} -> m=({m1 * scale},{m2 * scale})")

        # Starting loop counts sized so small-batch lanes (interpret,
        # the in-suite tiny run) clear the noise floor WITHOUT the
        # adaptive rescale — a rescale doubles the compile count, and in
        # a fresh-cache subprocess the compiles, not the runs, are the
        # budget (the suite's 870 s tier-1 window).
        ms = max(1, 256 // max(1, b8))
        t_full, t_posed = paired_slope(
            lambda m: looped(full_j, m, right, pose8, beta8_b),
            lambda m: looped(posed_j, m, shaped, pose8),
            2 * ms, 10 * ms, trials=max(3, args.iters))
        # Numerics probe in the same process/backend as the timed path
        # (CLAUDE.md on-chip rule): full vs pose-only, compiled, one
        # scalar readback. The staged pair is bit-identical at matched
        # batching structure; the broadcast-shaped batched program read
        # here may differ by float rounding — same 1e-4 gate as every
        # compiled path.
        err = float(jax.jit(
            lambda prm, sh, pp, ss: jnp.max(jnp.abs(
                jax.vmap(lambda q, s: core.forward(prm, q, s).verts)(pp, ss)
                - core.forward_posed_batched(sh, pp).verts))
        )(right, shaped, pose8, beta8_b))
        # In-context supplement (the CLAUDE.md rule's strict reading):
        # the TIMED executables' own scalar outputs, compared at the
        # already-compiled m=2*ms point — a precision collapse that only
        # manifests inside the fori_loop fusion context shows up HERE.
        # The sides' inputs differ by the full side's i-scaled shape
        # perturbation (~1e-5 relative at most), so the gate is
        # collapse-scale (1e-3, vs the ~2.4e-3 single-pass-bf16 class),
        # not rounding-scale; the elementwise probe above carries the
        # tight 1e-4 gate.
        s_full = float(full_j(right, pose8, beta8_b, 2 * ms))
        s_posed = float(posed_j(shaped, pose8, 2 * ms))
        rel = abs(s_full - s_posed) / max(abs(s_full), 1e-30)
        spec = results.setdefault("specialization", {})
        spec.update({
            "batch": b8,
            "full_evals_per_sec": float(f"{b8 / t_full:.5g}"),
            "posed_evals_per_sec": float(f"{b8 / t_posed:.5g}"),
            "posed_speedup": float(f"{t_full / t_posed:.4g}"),
            "posed_vs_full_max_abs_err": err,
            "timed_loop_rel_diff": float(f"{rel:.3g}"),
        })
        log(f"config8 specialization b={b8}: full {b8 / t_full:,.0f} vs "
            f"pose-only {b8 / t_posed:,.0f} evals/s "
            f"({t_full / t_posed:.2f}x), max err {err:.3e}")

    if args.spec_batch > 0:
        section("config8_specialization", config8_specialization)

    def config8_spec_lm():
        # Frozen-betas LM vs the 58-col solve on the same targets/steps —
        # the tracking-serving criterion (>= 1.1x at b >= 64). Registered
        # REGARDLESS of --skip-fit: the leg is sized small
        # (--spec-fit-batch) and bench-interpret (which passes
        # --skip-fit to dodge config4's cost) must still cover its
        # plumbing off-chip.
        bf = args.spec_fit_batch
        pose_f = rng.normal(scale=0.3, size=(bf, 16, 3)).astype(np.float32)
        beta_f = jnp.asarray(rng.normal(size=(10,)), jnp.float32)
        targets = core.jit_forward_batched(
            right, jnp.asarray(pose_f),
            jnp.broadcast_to(beta_f, (bf, 10))).verts

        def run_full(steps):
            return lambda: float(
                fit_lm(right, targets, n_steps=steps).final_loss.sum())

        def run_frozen(steps):
            return lambda: float(
                fit_lm(right, targets, n_steps=steps,
                       frozen_shape=beta_f).final_loss.sum())

        it = max(2, args.iters // 3)
        t58 = slope_time(run_full, 4, 12, iters=it)
        t48 = slope_time(run_frozen, 4, 12, iters=it)
        # Convergence probe at n_steps=12 REUSES the slope-timed
        # executables (static n_steps — any other count would be a fresh
        # compile in a different compilation context).
        loss58 = float(fit_lm(right, targets,
                              n_steps=12).final_loss.mean())
        loss48 = float(fit_lm(right, targets, n_steps=12,
                              frozen_shape=beta_f).final_loss.mean())
        spec = results.setdefault("specialization", {})
        spec.update({
            "fit_batch": bf,
            "lm_full_steps_per_sec": float(f"{1.0 / t58:.5g}"),
            "lm_frozen_steps_per_sec": float(f"{1.0 / t48:.5g}"),
            "lm_frozen_speedup": float(f"{t58 / t48:.4g}"),
            "lm_full_cols": 58,
            "lm_frozen_cols": 48,
            "lm_frozen_loss_ratio": float(f"{loss48 / max(loss58, 1e-30):.4g}"),
            "lm_frozen_finite": bool(np.isfinite(loss48)),
        })
        log(f"config8 LM b={bf}: 58-col {1.0 / t58:,.1f} vs frozen 48-col "
            f"{1.0 / t48:,.1f} steps/s ({t58 / t48:.2f}x), loss ratio "
            f"{loss48 / max(loss58, 1e-30):.3g}")

    if args.spec_fit_batch > 0:
        section("config8_spec_lm", config8_spec_lm)

    # -- config 5: 120-frame two-hand temporal sequence ---------------------
    def config5():
        t_frames, hands = 120, 2
        pose5 = jnp.asarray(
            rng.normal(scale=0.4, size=(t_frames * hands, 16, 3)), jnp.float32
        )
        beta5 = jnp.zeros((t_frames * hands, 10), jnp.float32)

        def seq(prm_pair, p, s):
            pl, pr = prm_pair
            vl = core.forward_batched(pl, p[:t_frames], s[:t_frames]).verts
            vr = core.forward_batched(pr, p[t_frames:], s[t_frames:]).verts
            return vl.sum() + vr.sum()

        fwd5 = loop_scalar(seq)
        t5 = slope_time(lambda m: looped(fwd5, m, (left, right), pose5, beta5),
                        1, 9, iters=max(1, args.iters // 2))
        results["config5_seq240_ms"] = t5 * 1e3
        log(f"config5 120f x 2 hands: {t5 * 1e3:.2f} ms "
            f"({t_frames * hands / t5:,.0f} evals/s)")

        # Variant: both hands as ONE hand-batched program (vmap over the
        # stacked param PyTree) — hand-major [2, T, ...] inputs.
        stacked = core.stack_params(left, right)
        pose5h = pose5.reshape(hands, t_frames, 16, 3)
        beta5h = beta5.reshape(hands, t_frames, 10)

        def seq_stacked(prm, p, s):
            return core.forward_hands(prm, p, s).verts.sum()

        fwd5s = loop_scalar(seq_stacked)
        t5s = slope_time(
            lambda m: looped(fwd5s, m, stacked, pose5h, beta5h),
            1, 9, iters=max(1, args.iters // 2))
        results["config5_stacked_ms"] = t5s * 1e3
        log(f"config5 stacked forward_hands: {t5s * 1e3:.2f} ms")

    section("config5", config5)

    # -- optional: sharded forward over an explicit mesh --------------------
    def mesh_bench():
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mano_hand_tpu.parallel import make_mesh, shard_params
        from mano_hand_tpu.parallel.mesh import DATA_AXIS

        axes = parse_mesh(args.mesh)
        mesh = make_mesh(data=axes.get("data", -1),
                         model=axes.get("model", 1))
        sharded = shard_params(right, mesh)
        bm = b2
        data_sh = NamedSharding(mesh, P(DATA_AXIS))

        # Same slope methodology as every other config: m sharded passes
        # inside ONE jitted program with a scalar carry, synced by a single
        # scalar readback — the per-dispatch tunnel sync cancels in the
        # slope instead of scaling with m.
        import functools as _ft

        @_ft.partial(jax.jit, static_argnums=3,
                     in_shardings=(None, data_sh, data_sh),
                     out_shardings=NamedSharding(mesh, P()))
        def run_mesh(prm, pose, shape, m):
            def body(i, acc):
                p = pose + i.astype(pose.dtype) * 1e-6
                return acc + core.forward_batched(prm, p, shape).verts.sum()

            return jax.lax.fori_loop(0, m, body, jnp.zeros((), pose.dtype))

        pose_m = jax.device_put(pose2, data_sh)
        beta_m = jax.device_put(beta2, data_sh)

        def run(m):
            return lambda: float(run_mesh(sharded.params, pose_m, beta_m, m))

        t = slope_time(run, 1, 5, iters=3)
        key = ("mesh_"
               + args.mesh.replace("=", "").replace(",", "_")
               + "_evals_per_sec")
        results[key] = bm / t
        note = "" if is_tpu else " (VIRTUAL CPU MESH — not a perf number)"
        log(f"mesh {args.mesh}: {bm / t:,.0f} evals/s{note}")
        if not is_tpu:
            results[key + "_note"] = "virtual cpu mesh; correctness only"

    if args.mesh:
        section("mesh", mesh_bench)

    # -- optional: per-device-count scaling table ---------------------------
    def mesh_scaling():
        """One row per device count d | 1,2,4,... <= visible devices:
        compile the GSPMD data-parallel forward AND the full sharded fit
        step over a data=d mesh, record per-shard shapes + the collective
        ops XLA inserted + a slope-timed rate, and execute one fit step.

        On the virtual CPU mesh the rows validate sharding/collective
        STRUCTURE (rates are correctness-only); on real multi-chip
        hardware the same code emits the scaling curve with zero changes
        (VERDICT r3 item 7; SURVEY.md §2.2). Run via `make mesh-scaling`.
        """
        import functools as _ft
        import re

        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mano_hand_tpu.parallel import make_mesh
        from mano_hand_tpu.parallel.fit import init_state, make_fit_step
        from mano_hand_tpu.parallel.mesh import DATA_AXIS

        # Same bring-up contract as run_benchmarks: the killable
        # subprocess probe already proved the backend answers before
        # the mesh-scaling leg runs.  # analysis: allow(bare-devices)
        n_dev = len(jax.devices())
        counts = [d for d in (1, 2, 4, 8, 16, 32) if d <= n_dev]
        bm = args.mesh_scaling_batch
        bm -= bm % max(counts)        # divisible by every mesh size
        if bm <= 0:
            raise ValueError(
                f"--mesh-scaling-batch {args.mesh_scaling_batch} is "
                f"smaller than the largest mesh ({max(counts)} devices); "
                "nothing to shard")
        rng_ms = np.random.default_rng(5)
        pose_ms = jnp.asarray(rng_ms.normal(scale=0.6, size=(bm, 16, 3)),
                              jnp.float32)
        beta_ms = jnp.asarray(rng_ms.normal(size=(bm, 10)), jnp.float32)
        table = {}
        coll_ops = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

        def count_collectives(hlo: str) -> dict:
            # HLO text puts the op name right before its operand list:
            # `%x = f32[...] all-reduce(...)` / `all-gather-start(...)`.
            found = {op: len(re.findall(rf"\s{op}(?:-start)?\(", hlo))
                     for op in coll_ops}
            return {k: v for k, v in found.items() if v}

        for d in counts:
            mesh = make_mesh(data=d, model=1,  # analysis: allow(bare-devices)
                             devices=jax.devices()[:d])
            data_sh = NamedSharding(mesh, P(DATA_AXIS))
            pose_d = jax.device_put(pose_ms, data_sh)
            beta_d = jax.device_put(beta_ms, data_sh)

            fwd = jax.jit(
                lambda prm, p, s: core.forward_batched(prm, p, s).verts,
                in_shardings=(None, data_sh, data_sh),
                out_shardings=data_sh,
            )
            fwd_hlo = fwd.lower(right, pose_d, beta_d).compile().as_text()

            @_ft.partial(jax.jit, static_argnums=3,
                         in_shardings=(None, data_sh, data_sh),
                         out_shardings=NamedSharding(mesh, P()))
            def run_d(prm, p, s, m):
                def body(i, acc):
                    pp = p + i.astype(p.dtype) * 1e-6
                    return acc + core.forward_batched(prm, pp, s).verts.sum()

                return jax.lax.fori_loop(0, m, body, jnp.zeros((), p.dtype))

            t = slope_time(
                lambda m: (lambda: float(run_d(right, pose_d, beta_d, m))),
                1, 5, iters=3)

            opt = optax.adam(1e-2)
            fs = make_fit_step(right, mesh, opt)
            targets = jax.device_put(
                np.zeros((bm, right.v_template.shape[0], 3), np.float32),
                data_sh)
            state = init_state(right, bm, opt)
            step_hlo = fs.jitted.lower(
                fs.bound_params, state, targets).compile().as_text()
            state2, loss = fs(state, targets)
            jax.block_until_ready(state2.pose)

            table[str(d)] = {
                "per_shard_batch": bm // d,
                "per_shard_pose": [bm // d, 16, 3],
                "per_shard_targets": [bm // d,
                                      int(right.v_template.shape[0]), 3],
                "forward_collectives": count_collectives(fwd_hlo),
                "fit_step_collectives": count_collectives(step_hlo),
                "programs": 2,
                "fit_step_loss_finite": bool(np.isfinite(float(loss))),
                "evals_per_sec": (bm / t) if np.isfinite(t) else None,
            }
            log(f"mesh-scaling d={d}: per-shard B={bm // d}, "
                f"fwd colls={table[str(d)]['forward_collectives']}, "
                f"fit colls={table[str(d)]['fit_step_collectives']}, "
                f"{bm / t:,.0f} evals/s"
                + ("" if is_tpu else " (virtual mesh, correctness only)"))
        results["mesh_scaling"] = table
        if not is_tpu:
            results["mesh_scaling_note"] = ("virtual cpu mesh; structure "
                                            "validation, not perf")

    if args.mesh_scaling or args.mesh_scaling_only:
        section("mesh_scaling", mesh_scaling)

    if args.mesh_scaling_only:
        # Early-return path: drive the deferred runner here (its
        # mesh-scaling-only skip reduces the schedule to this section).
        for name, fn in _registered:
            run_section(name, fn)
        table = results.get("mesh_scaling", {})
        rates = [row["evals_per_sec"] for row in table.values()
                 if row.get("evals_per_sec")]
        line = {
            "metric": "mesh_scaling_evals_per_sec",
            "value": round(max(rates), 1) if rates else None,
            "unit": "evals/s",
            "vs_baseline": None,
            "device": device_str,
            "detail": results,
        }
        if errors:
            line["config_errors"] = errors
        return line

    # -- accuracy readbacks (after ALL timing; D2H poisons axon dispatch) ----
    def accuracy():
        if out1 is None or outs is None:
            raise RuntimeError("config1 warm-up failed; no outputs to check")
        want = oracle.forward(right64)
        err0 = float(np.abs(np.asarray(out1.verts) - want.verts).max())
        results["config1_zero_pose_max_err"] = err0
        log(f"config1 zero-pose max err vs oracle: {err0:.3e}")
        max_err = fast_err = highest_err = pallas_err = fused_err = 0.0
        fused_full_err = hands_err = 0.0
        for i in range(8):
            w = oracle.forward(right64, pose=poses[i], shape=betas[i]).verts
            max_err = max(
                max_err, float(np.abs(np.asarray(outs.verts[i]) - w).max())
            )
            if outs_fast is not None:
                fast_err = max(fast_err, float(
                    np.abs(np.asarray(outs_fast.verts[i]) - w).max()
                ))
            if outs_highest is not None:
                highest_err = max(highest_err, float(
                    np.abs(np.asarray(outs_highest.verts[i]) - w).max()
                ))
            if verts_pallas is not None:
                pallas_err = max(pallas_err, float(
                    np.abs(np.asarray(verts_pallas[i]) - w).max()
                ))
            if verts_fused is not None:
                fused_err = max(fused_err, float(
                    np.abs(np.asarray(verts_fused[i]) - w).max()
                ))
            if verts_fused_full is not None:
                fused_full_err = max(fused_full_err, float(
                    np.abs(np.asarray(verts_fused_full[i]) - w).max()
                ))
            if verts_hands is not None:
                hands_err = max(hands_err, float(
                    np.abs(np.asarray(verts_hands[i]) - w).max()
                ))
        results["max_err_vs_numpy"] = max_err
        log(f"random-pose max err vs oracle (model default precision): "
            f"{max_err:.3e}")
        if outs_fast is not None:
            results["default_precision_max_err"] = fast_err
            log(f"precision=DEFAULT max err vs oracle: {fast_err:.3e} "
                "(informational; fails the 1e-4 gate on TPU)")
        if outs_highest is not None:
            results["highest_precision_max_err"] = highest_err
            log(f"precision=HIGHEST max err vs oracle: {highest_err:.3e}")
        if verts_pallas is not None:
            results["pallas_max_err_vs_numpy"] = pallas_err
            log(f"compiled pallas path max err vs oracle: {pallas_err:.3e}")
        if verts_fused is not None:
            results["fused_max_err_vs_numpy"] = fused_err
            log(f"compiled fused-forward path max err vs oracle: "
                f"{fused_err:.3e}")
        if verts_fused_full is not None:
            results["fused_full_max_err_vs_numpy"] = fused_full_err
            log(f"compiled FULL-fusion path max err vs oracle: "
                f"{fused_full_err:.3e}")
        if verts_hands is not None:
            results["fused_full_hands_max_err_vs_numpy"] = hands_err
            log(f"compiled two-hand single-launch path max err vs "
                f"oracle: {hands_err:.3e}")

    section("accuracy", accuracy)

    # -- segmented-tree kernel probe (SMPL-H): first Mosaic lowering of the
    # generalized level layout must happen HERE with a recorded verdict,
    # not in a user's hands — the spanning-range concats and per-wrist
    # segments only existed under the interpreter until a chip ran this
    # (the CLAUDE.md probe-every-compiled-path rule). Readback tail:
    # it compares on host.
    def smplh_tree_probe():
        if not (is_tpu or args.pallas_interpret):
            return  # Mosaic path needs a TPU; CPU runs use --pallas-interpret
        import dataclasses

        from mano_hand_tpu import constants as C2
        from mano_hand_tpu.assets import synthetic_params as synth

        rig = dataclasses.replace(
            synth(seed=13, n_verts=389, n_joints=52, n_shape=16,
                  n_faces=700),
            parents=C2.SMPLH_PARENTS,
        ).astype(np.float32)
        rngp = np.random.default_rng(6)
        pose_s = jnp.asarray(
            rngp.normal(scale=0.3, size=(8, 52, 3)), jnp.float32)
        beta_s = jnp.asarray(rngp.normal(size=(8, 16)), jnp.float32)
        want = core.forward_batched(rig, pose_s, beta_s).verts
        got = core.forward_batched_pallas_fused_full(
            rig, pose_s, beta_s, block_b=8, **ikw)
        err = float(np.abs(np.asarray(got) - np.asarray(want)).max())
        results["smplh_fused_full_max_err"] = err
        log(f"SMPL-H segmented-tree fused-full kernel: max err {err:.3e} "
            f"vs the staged path (52-joint rig{' , Mosaic' if is_tpu else ''})")

    section("smplh_tree_probe", smplh_tree_probe)

    # -- config 5t: streaming tracker per-frame latency ---------------------
    def config5_track():
        # Online (causal) tracking: one warm-started LM solve per frame —
        # the realtime counterpart of config5's offline batch. Frames
        # after the first reuse one compiled program, so this measures
        # steady-state per-frame latency, warm-start included.
        from mano_hand_tpu.fitting import make_tracker

        t_frames = 16
        end_pose = rng.normal(scale=0.3, size=(16, 3)).astype(np.float32)
        alphas = np.linspace(0.0, 1.0, t_frames, dtype=np.float32)
        clip = core.jit_forward_batched(
            right,
            jnp.asarray(alphas[:, None, None] * end_pose[None]),
            jnp.zeros((t_frames, 10), jnp.float32),
        ).verts
        state, step = make_tracker(right, solver="lm", n_steps=5)
        state, _ = step(state, clip[0])        # compile + settle frame 0
        jax.block_until_ready(state.pose)
        t0 = time.perf_counter()
        for t in range(1, t_frames):
            state, _ = step(state, clip[t])
        jax.block_until_ready(state.pose)
        per_frame = (time.perf_counter() - t0) / (t_frames - 1)
        results["config5_track_ms_per_frame"] = per_frame * 1e3
        results["config5_track_fps"] = 1.0 / per_frame
        log(f"config5t streaming tracker (LM x5 steps/frame): "
            f"{per_frame * 1e3:.2f} ms/frame ({1.0 / per_frame:,.0f} fps)")

    section("config5_track", config5_track)

    # -- config 6: the differentiable mask path -----------------------------
    def config6_silhouette():
        # Soft-rasterizer throughput (the render half of
        # fit(data_term="silhouette")) and the end-to-end mask-fit step
        # rate (16 renders fwd+bwd per Adam step). [P, F] pair slabs are
        # row-chunked inside the renderer, so one render is 8 dense
        # [512, F] distance blocks — VPU work, not MXU.
        from mano_hand_tpu.viz.camera import (
            WeakPerspectiveCamera, default_hand_camera,
        )
        from mano_hand_tpu.viz.silhouette import soft_depth, soft_silhouette

        b6, hw = 16, args.sil_size
        cam = WeakPerspectiveCamera(rot=jnp.eye(3, dtype=jnp.float32),
                                    scale=3.0)
        pose6 = jnp.asarray(rng.normal(scale=0.2, size=(b6, 16, 3)),
                            jnp.float32)
        beta6 = jnp.zeros((b6, 10), jnp.float32)

        sil_sum = loop_scalar(
            lambda prm, p, s: soft_silhouette(
                core.forward_batched(prm, p, s).verts, prm.faces, cam,
                height=hw, width=hw,
            ).sum()
        )
        t_render = slope_time(
            lambda m: looped(sil_sum, m, right, pose6, beta6),
            1, 3, iters=max(2, args.iters // 3),
        )
        results["config6_sil_renders_per_sec"] = b6 / t_render
        log(f"config6 soft silhouette {hw}x{hw} (batch {b6} incl. "
            f"forward): {b6 / t_render:,.0f} renders/s")

        pin = default_hand_camera()          # depth needs a real projection
        depth_sum = loop_scalar(
            lambda prm, p, s: soft_depth(
                core.forward_batched(prm, p, s).verts, prm.faces, pin,
                height=hw, width=hw,
            ).sum()
        )
        t_depth = slope_time(
            lambda m: looped(depth_sum, m, right, pose6, beta6),
            1, 3, iters=max(2, args.iters // 3),
        )
        results["config6_depth_renders_per_sec"] = b6 / t_depth
        log(f"config6 soft depth {hw}x{hw} (batch {b6} incl. forward): "
            f"{b6 / t_depth:,.0f} renders/s")

        if args.skip_fit:
            return
        verts6 = core.jit_forward_batched(right, pose6, beta6).verts
        masks = (soft_silhouette(
            verts6 + jnp.asarray([0.02, 0.01, 0.0], jnp.float32),
            right.faces, cam, height=hw, width=hw, sigma=1.0,
        ) > 0.5).astype(jnp.float32)

        def run_fit(steps):
            return lambda: float(
                fit(right, masks, n_steps=steps, lr=0.01,
                    data_term="silhouette", camera=cam, sil_sigma=1.0,
                    fit_trans=True, pose_prior_weight=1.0,
                    shape_prior_weight=1.0).final_loss.sum()
            )

        t_step = slope_time(run_fit, 4, 12, iters=max(2, args.iters // 3))
        results["config6_sil_fit_steps_per_sec"] = 1.0 / t_step
        log(f"config6 mask fit b={b6} {hw}x{hw}: {1.0 / t_step:,.1f} "
            f"steps/s ({t_step * 1e3:.2f} ms/step, fwd+bwd through the "
            "rasterizer)")

    section("config6_silhouette", config6_silhouette)

    # -- config 7: the bucketed serving engine ------------------------------
    # Engine-vs-direct throughput, steady-state recompile count, and
    # padding waste for the micro-batching layer (serving/engine.py).
    # Registered in the READBACK TAIL (after accuracy): the engine hands
    # results back as host arrays, and the first D2H permanently degrades
    # later axon dispatches — so it must never run before the timed
    # sections. Wall-clock timing is the honest metric here: the engine
    # IS the host+device pipeline (on the tunnel the per-batch sync
    # overhead is part of what it amortizes), so slope-timing would
    # measure the wrong thing. Everything except the absolute rate is
    # meaningful on CPU (recompiles, waste, ratio) — the lane
    # `make serve-smoke` and the bench-interpret run both exercise it.
    def config7_serving():
        # THE shared protocol (serving/measure.py:serve_bench_run — the
        # same code path `mano serve-bench` prints): warm every bucket,
        # settle, one timed ragged pass, then the fixed-warm-bucket
        # overhead bound as a MEDIAN over interleaved engine/direct
        # trials (background load on this box drifts 5x between
        # seconds; a non-interleaved pass once read 0.12x from a spike).
        from mano_hand_tpu.serving.measure import serve_bench_run

        srv = serve_bench_run(
            right,
            requests=args.serving_requests,
            max_rows=args.serving_max_rows,
            max_bucket=args.serving_max_bucket,
            seed=7,
            log=lambda m: log(f"config7 {m}"),
        )
        results["serving"] = srv
        log(f"config7 serving: engine {srv['engine_evals_per_sec']:,.0f} "
            f"evals/s ragged, {srv['engine_fixed_evals_per_sec']:,.0f} "
            f"fixed b={srv['warm_bucket']} vs direct "
            f"{srv['direct_evals_per_sec']:,.0f} (ratio "
            f"{srv['engine_vs_direct_ratio']:.2f}x, median "
            f"{srv['ratio_median']:.2f} over trials "
            f"{srv['ratio_trials']}), "
            f"{srv['steady_recompiles']} steady recompiles, "
            f"{srv['padding_waste']:.1%} padding waste")

    section("config7_serving", config7_serving)

    # -- config 7r: fault-recovery drill (runtime/, PR 3) -------------------
    # THE shared protocol (serving/measure.py:recovery_drill_run — the
    # same code path behind `mano serve-bench --chaos drill` and the
    # quick-lane chaos matrix in tests/test_runtime.py): one SUPERVISED
    # engine driven through every tunnel failure class — transient
    # error, latency spike, hang, persistent outage — via deterministic
    # chaos injection, then through recovery. Faults are injected
    # in-process (nothing stresses the real chip), so the criteria —
    # 100% of futures resolved under every fault, bit-identical CPU
    # failover, zero post-recovery recompiles — gate EVERY lane, CPU
    # and interpreter included. Rides in the readback tail for the same
    # D2H reason as config7.
    def config7_recovery():
        from mano_hand_tpu.serving.measure import recovery_drill_run

        rec = recovery_drill_run(
            right,
            requests_per_class=args.recovery_requests,
            max_bucket=8,
            deadline_s=5.0,
            seed=11,
            log=lambda m: log(f"config7r {m}"),
        )
        results["recovery"] = rec
        log(f"config7r recovery drill: "
            f"{rec['futures_resolved_fraction']:.0%} futures resolved, "
            f"failover overhead {rec['failover_overhead_ratio']}x, "
            f"failover-vs-cpu err "
            f"{rec['failover_vs_cpu_direct_max_abs_err']}, "
            f"{rec['post_recovery_steady_recompiles']} post-recovery "
            f"recompiles (breaker: {rec['breaker_opens']} opens, "
            f"{rec['breaker_probes']} probes)")

    section("config7_recovery", config7_recovery)

    # -- config 9: cross-subject coalescing (PR 4) --------------------------
    # THE shared protocol (serving/measure.py:coalesce_bench_run — also
    # behind `mano serve-bench --subjects`): a mixed-subject pose-only
    # stream (many users, each their own baked betas) through the
    # gathered engine dispatch vs the pre-PR-4 per-subject-split
    # dispatch, with the interleaved min-over-trials drift defense.
    # Criteria (scripts/bench_report.py): engine >= 1.3x split on a
    # >= 8-subject stream, the gathered path f32 BIT-identical to the
    # per-subject posed program, zero steady recompiles after warmup +
    # table growth. Rides in the readback tail for the same D2H reason
    # as config7; everything except the absolute rates is meaningful on
    # CPU, which is where the criterion is defined.
    def config9_coalesce():
        from mano_hand_tpu.serving.measure import coalesce_bench_run

        cz = coalesce_bench_run(
            right,
            subjects=args.coalesce_subjects,
            requests=args.coalesce_requests,
            max_rows=args.coalesce_max_rows,
            max_bucket=args.coalesce_max_bucket,
            seed=9,
            log=lambda m: log(f"config9 {m}"),
        )
        results["coalesce"] = cz
        log(f"config9 coalesce: engine {cz['engine_evals_per_sec']:,.0f} "
            f"vs split {cz['split_evals_per_sec']:,.0f} evals/s (ratio "
            f"{cz['engine_vs_split_ratio']:.2f}x, median "
            f"{cz['ratio_median']:.2f}), width "
            f"{cz['coalesce_width_mean']}, "
            f"{cz['mixed_subject_batches']} mixed batches, "
            f"{cz['table_growths']} growths, "
            f"{cz['steady_recompiles']} steady recompiles, gather err "
            f"{cz['gather_vs_posed_max_abs_err']:.1e}")

    if args.coalesce_subjects > 0:
        section("config9_coalesce", config9_coalesce)

    # -- config 10: overload/saturation drill (PR 5) ------------------------
    # THE shared protocol (serving/measure.py:overload_drill_run — also
    # behind `mano serve-bench --overload`): a burst submitter drives a
    # bounded-admission, deadline-carrying engine at N x its MEASURED
    # service rate (the device half throttled by a chaos "sat" plan, so
    # saturation is deterministic and no chip is harmed). Criteria
    # (scripts/bench_report.py): every future resolves within its
    # deadline budget as result/shed/expired, shed decisions touch no
    # device (the max_queued=0 probe), tier-0 goodput >= 95% at 4x
    # achieved saturation, zero steady recompiles. Rides in the
    # readback tail for the same D2H reason as config7; every criterion
    # is CPU-defined.
    def config10_overload():
        from mano_hand_tpu.serving.measure import overload_drill_run

        ov = overload_drill_run(
            right,
            saturation=args.overload_saturation,
            bursts=args.overload_bursts,
            seed=13,
            log=lambda m: log(f"config10 {m}"),
        )
        results["overload"] = ov
        log(f"config10 overload: {ov['submitted']} submitted at "
            f"{ov['saturation_achieved']}x achieved saturation "
            f"({ov['offered_rate_req_per_s']:,.0f} offered vs "
            f"{ov['service_rate_req_per_s']:,.0f} served req/s), "
            f"{ov['resolved_within_budget_fraction']:.0%} in budget, "
            f"tier-0 goodput {ov['tier0_goodput']}, "
            f"{ov['outcomes']['shed']} shed / "
            f"{ov['outcomes']['expired']} expired, shed decision p50 "
            f"{ov['shed_probe']['decision_p50_us']} µs, "
            f"{ov['steady_recompiles']} steady recompiles")

    if args.overload_saturation > 0:
        section("config10_overload", config10_overload)

    # -- config 11: cold-start/restart drill (PR 6) -------------------------
    # THE shared protocol (serving/measure.py:cold_start_drill_run — also
    # behind `mano serve-bench --cold-start`): bake the full executable
    # lattice + SubjectTable checkpoint, kill the engine mid-traffic,
    # cold-boot a fresh one, and measure process-start -> first served
    # result -> p99-stable. Criteria (scripts/bench_report.py): ZERO jit
    # compiles after restore with every reachable program served from
    # the lattice (aot_loads accounting), restored subjects f32
    # BIT-identical to fresh bakes, every damage injection (truncated
    # entry, schema bump, digest mismatch, half-written checkpoint)
    # degraded to a counted recompile with 100% of futures resolved,
    # and a hang fault during boot cleared by the supervised path.
    # Restarts are simulated in-process; every criterion is CPU-defined.
    def config11_coldstart():
        from mano_hand_tpu.serving.measure import cold_start_drill_run

        cs = cold_start_drill_run(
            right,
            subjects=args.coldstart_subjects,
            requests=args.coldstart_requests,
            max_bucket=args.coldstart_max_bucket,
            p99_waves=args.coldstart_waves,
            seed=17,
            log=lambda m: log(f"config11 {m}"),
        )
        results["coldstart"] = cs
        log(f"config11 cold start: {cs['compiles_after_restore']} "
            f"compiles after restore ({cs['aot_loads']}/"
            f"{cs['expected_programs']} programs from the lattice), "
            f"first result {cs['t_first_result_s'] * 1e3:,.0f} ms, "
            f"p99 stable {cs['t_p99_stable_s'] * 1e3:,.0f} ms, "
            f"restored-vs-fresh err {cs['restored_vs_fresh_max_abs_err']}, "
            f"{len(cs['injections'])} damage injections degraded, hang "
            f"leg {cs['hang_leg']['deadline_kills']} deadline kill(s)")

    if args.coldstart_requests > 0:
        section("config11_coldstart", config11_coldstart)

    # -- config 12: tracing-overhead leg (PR 8) -----------------------------
    # THE shared protocol (serving/measure.py:tracing_overhead_run):
    # the same ragged stream through a traced and an untraced engine,
    # interleaved per trial — observability must cost <= 3% or it gets
    # turned off in the incident it exists for. Criteria
    # (scripts/bench_report.py): median paired overhead ratio <= 1.03,
    # zero steady recompiles with tracing ON (events must never change
    # program identity), and every submitted span closed exactly once.
    # With --profile set, the traced engine's Chrome-trace host
    # timeline is exported NEXT TO the XLA device capture, so
    # `scripts/trace_report.py <profile-dir>` merges both halves of
    # the run into one stage-breakdown report (ROADMAP item 2: the
    # traces "have never been read"). Every criterion is CPU-defined.
    def config12_tracing():
        from mano_hand_tpu.serving.measure import tracing_overhead_run

        trc = tracing_overhead_run(
            right,
            requests=args.tracing_requests,
            max_rows=args.serving_max_rows,
            max_bucket=args.serving_max_bucket,
            trace_dir=args.profile or None,
            seed=19,
            log=lambda m: log(f"config12 {m}"),
        )
        results["tracing"] = trc
        acc = trc["span_accounting"]
        log(f"config12 tracing: overhead ratio "
            f"{trc['tracing_overhead_ratio']:.3f} (trials "
            f"{trc['ratio_trials']}), {trc['steady_recompiles']} steady "
            f"recompiles, {acc['spans_closed']}/{acc['spans_started']} "
            f"spans closed ({acc['spans_open']} open), "
            f"{len(trc['stage_breakdown']['by_bucket_tier'])} stage "
            f"cells")

    if args.tracing_requests > 0:
        section("config12_tracing", config12_tracing)

    # -- config 13: metrics + numerics-sentinel leg (PR 9) ------------------
    # THE shared protocol (serving/measure.py:metrics_overhead_run):
    # the same ragged stream through an OBSERVED engine (tracer +
    # metrics registry scraped in-window + numerics sentinel probing
    # every live program family against golden digests) and a bare
    # engine, interleaved per trial — the aggregate health surface
    # must cost <= 3% or it gets turned off in the incident it exists
    # for; plus the sentinel drill (an injected chaos wrong-output
    # fault MUST raise a numerics_drift incident while every future
    # still resolves). Criteria (scripts/bench_report.py): median
    # paired overhead <= 1.03 at >= 64 requests, zero steady
    # recompiles observed, drill detection + recovery, spans closed
    # once, SLO burn rates reported. Every criterion is CPU-defined.
    # With --profile set, the final registry snapshot exports next to
    # the XLA capture (metrics.json/metrics.prom — `mano status
    # --metrics-dir` re-reads them).
    def config13_metrics():
        from mano_hand_tpu.serving.measure import metrics_overhead_run

        mx = metrics_overhead_run(
            right,
            requests=args.metrics_requests,
            max_rows=args.serving_max_rows,
            max_bucket=args.serving_max_bucket,
            metrics_dir=args.profile or None,
            seed=23,
            log=lambda m: log(f"config13 {m}"),
        )
        results["metrics"] = mx
        acc = mx["span_accounting"]
        drill = mx["sentinel_drill"]
        log(f"config13 metrics: overhead ratio "
            f"{mx['metrics_overhead_ratio']:.3f} (trials "
            f"{mx['ratio_trials']}), {mx['steady_recompiles']} steady "
            f"recompiles, {mx['registry_metrics']} exported metrics, "
            f"golden {mx['golden']['golden_status']}, sentinel drill "
            f"detected={drill['detected']} recovered="
            f"{drill['recovered']} ({drill['incidents']} incident(s)), "
            f"{acc['spans_closed']}/{acc['spans_started']} spans closed")

    if args.metrics_requests > 0:
        section("config13_metrics", config13_metrics)

    # -- config 14: fused gathered serving kernel (PR 10) -------------------
    # THE shared protocol (serving/measure.py:posed_kernel_bench_run):
    # the SAME mixed-subject pose-only stream through two engines — the
    # fused Pallas gathered kernel tier (posed_kernel="fused",
    # ops/pallas_posed.py) vs the PR-4 XLA gathered program — slope-
    # timed through the engine (marginal cost of the stream's tail, so
    # the fixed dispatch overhead both sides share cancels), all four
    # timing points interleaved per trial. Criteria
    # (scripts/bench_report.py): fused parity <= 1e-5 vs the posed
    # reference (mixed-subject coalesced batches included), XLA side
    # bit-identical (0.0), zero steady recompiles on BOTH tiers; the
    # speed ratio is judged only on a real TPU (the CPU lane runs the
    # kernel through the Pallas interpreter — emulation overhead, not
    # perf; the chip leg is queued via scripts/bench_tpu_wait.sh).
    # The lm_e2e sub-leg (ROADMAP 2b: end-to-end fit_lm steps/s with
    # the landed batched-LU solve — 8x in isolation, never measured
    # end-to-end on chip) rides in the same artifact so the first
    # tunnel-up window measures both halves of ROADMAP item 2. With
    # --profile set, the fused engine's span timeline exports to
    # <profile>/posed_kernel/ for scripts/trace_report.py.
    def config14_posed_kernel():
        from mano_hand_tpu.serving.measure import posed_kernel_bench_run

        pk = posed_kernel_bench_run(
            right,
            subjects=args.posed_subjects,
            requests=args.posed_requests,
            max_rows=args.posed_max_rows,
            max_bucket=args.posed_max_bucket,
            lm_batch=args.posed_lm_batch,
            interpret=True if args.pallas_interpret else None,
            trace_dir=args.profile or None,
            seed=29,
            log=lambda m: log(f"config14 {m}"),
        )
        results["posed_kernel"] = pk
        log(f"config14 posed kernel: fused "
            f"{pk['fused_evals_per_sec']:,.0f} vs xla "
            f"{pk['xla_evals_per_sec']:,.0f} evals/s (slope ratio "
            f"{pk['fused_vs_xla_ratio']}x, platform {pk['platform']}, "
            f"interpret={pk['interpret']}), parity fused "
            f"{pk['fused_vs_gather_max_abs_err']:.2e} / xla "
            f"{pk['xla_vs_gather_max_abs_err']:.2e}, steady recompiles "
            f"{pk['steady_recompiles_fused']}/"
            f"{pk['steady_recompiles_xla']}"
            + (f", lm_e2e {pk['lm_e2e_steps_per_sec']:,.1f} steps/s "
               f"at b={pk['lm_e2e_batch']}"
               if "lm_e2e_steps_per_sec" in pk else ""))

    if args.posed_requests > 0:
        section("config14_posed_kernel", config14_posed_kernel)

    # -- config 15: streaming-session drill (PR 12) -------------------------
    # THE shared protocol (serving/measure.py:stream_drill_run — also
    # behind `mano serve-bench --streams`): hundreds of concurrent
    # per-user tracking sessions (ServingEngine.open_stream), each
    # frame a frozen-shape LM fit warm-started from the last converged
    # pose then served through the gathered SubjectTable dispatch at
    # tier 0 — the product shape the serving PRs were for. Criteria
    # (scripts/bench_report.py): 100% of frames resolved (ok/shed/
    # expired, never stranded) THROUGH a mid-drill chaos plan with
    # bit-identical CPU failover, warm-started fits >= 1.2x the
    # loss-matched cold fit (slope-timed), per-stream tier-0 frame-
    # latency SLO reported as a burn rate, zero steady recompiles,
    # every stream span closed exactly once. Faults are injected
    # in-process; every criterion is CPU-defined.
    def config15_streams():
        from mano_hand_tpu.serving.measure import stream_drill_run

        st = stream_drill_run(
            right,
            streams=args.stream_streams,
            frames_per_stream=args.stream_frames,
            subjects=args.stream_subjects or None,
            workers=args.stream_workers,
            max_bucket=args.stream_max_bucket,
            seed=31,
            log=lambda m: log(f"config15 {m}"),
        )
        results["streams"] = st
        oc = st["outcomes"]
        log(f"config15 streams: {st['streams']} streams x "
            f"{st['frames_per_stream']} frames -> "
            f"{st['frames_resolved_fraction']:.0%} resolved "
            f"({oc['ok']} ok / {oc['shed']} shed / {oc['expired']} "
            f"expired / {oc['stranded']} stranded), "
            f"{st['frames_per_sec']} frames/s steady, p99 "
            f"{st['frame_p99_ms']} ms, warm/cold fit ratio "
            f"{st['warm_vs_cold_fit_ratio']}x "
            f"(matched={st['warm_loss_matched']}), "
            f"{st['failovers']} failover(s) at err "
            f"{st['failover_vs_cpu_direct_max_abs_err']}, "
            f"{st['steady_recompiles']} steady recompiles")

    if args.stream_streams > 0:
        section("config15_streams", config15_streams)

    # -- config 16: lane-loss chaos drill (PR 13) ---------------------------
    # THE fleet-serving failure story (serving/measure.py:lane_drill_run):
    # N per-device dispatch lanes (virtual CPU devices off-chip — the
    # drill records n_devices; `make serve-smoke` forces 8 via
    # --virtual-devices, everywhere else lanes oversubscribe round-robin
    # and the logic is identical) driven by concurrent submitters while
    # a %LANE-tagged chaos plan kills exactly one lane mid-stream.
    # Criteria (scripts/bench_report.py:judge_lanes): 100% of futures
    # resolved through the lane loss with ZERO errors/strands, failover
    # results bit-identical to the single-device engine, the sibling
    # ladder (not the CPU tier) absorbing the loss, zero steady
    # recompiles before AND after failback, the killed lane's re-probe
    # backoff growing while it is down, and every span closed exactly
    # once. Faults are injected in-process; every criterion is
    # CPU-defined.
    def config16_lanes():
        from mano_hand_tpu.serving.measure import lane_drill_run

        ln = lane_drill_run(
            right,
            lanes=args.lane_lanes,
            requests_per_pass=args.lane_requests,
            subjects=args.lane_subjects,
            workers=args.lane_workers,
            max_bucket=args.lane_max_bucket,
            seed=41,
            log=lambda m: log(f"config16 {m}"),
        )
        results["lanes"] = ln
        oc = ln["outcomes"]
        log(f"config16 lanes: {ln['lanes']} lanes over "
            f"{ln['distinct_devices']} device(s), "
            f"{ln['futures_resolved_fraction']:.0%} resolved "
            f"({oc['ok']} ok / {oc['error']} err / {oc['stranded']} "
            f"stranded / {oc['cancelled']} cancelled) through lane "
            f"{ln['kill_lane']} loss; {ln['lane_failovers']} ladder "
            f"hop(s), {ln['cpu_failovers']} cpu failover(s), loss err "
            f"{ln['loss_vs_reference_max_abs_err']}, recompiles "
            f"{ln['steady_recompiles_pre']}/"
            f"{ln['steady_recompiles_post']} pre/post, failback "
            f"served={ln['failback_served']}")

    if args.lane_lanes > 0:
        section("config16_lanes", config16_lanes)

    # -- config 17: precision-tiered serving (PR 14) ------------------------
    # THE shared protocol (serving/measure.py:precision_bench_run): the
    # same mixed-subject tier-0 stream through two live engines — one
    # under a PrecisionPolicy (tier 0 -> the bf16-compute/f32-accumulate
    # gathered family), one the f32 control — slope-timed (the config14
    # protocol). Criteria (scripts/bench_report.py:judge_precision):
    # bf16 max vertex error within the policy's stated envelope through
    # the LIVE engine (mixed coalesced batches included), f32 control
    # bit-identical (0.0), zero steady recompiles on BOTH precision
    # families, the sentinel drill detecting an injected bf16 drift and
    # recovering (every future resolved, spans closed once), and the
    # speedup ratio recorded — judged >= 1.2x on a real TPU only (the
    # config14 convention: off-chip the bf16 MXU passes are emulated,
    # so the CPU-lane ratio measures emulation overhead; the chip leg
    # is queued via scripts/bench_tpu_wait.sh).
    def config17_precision():
        from mano_hand_tpu.serving.measure import precision_bench_run

        pr = precision_bench_run(
            right,
            subjects=args.precision_subjects,
            requests=args.precision_requests,
            max_rows=args.precision_max_rows,
            max_bucket=args.precision_max_bucket,
            posed_kernel=args.precision_posed_kernel,
            interpret=True if args.pallas_interpret else None,
            trace_dir=args.profile or None,
            seed=43,
            log=lambda m: log(f"config17 {m}"),
        )
        results["precision"] = pr
        drl = pr.get("sentinel_drill") or {}
        log(f"config17 precision: bf16 {pr['bf16_evals_per_sec']:,.0f} "
            f"vs f32 {pr['f32_evals_per_sec']:,.0f} evals/s (slope "
            f"ratio {pr['bf16_vs_f32_ratio']}x, platform "
            f"{pr['platform']}), bf16 err {pr['bf16_max_abs_err']:.2e} "
            f"vs envelope {pr['bf16_err_envelope']:.1e}, f32 control "
            f"{pr['f32_control_max_abs_err']:.2e}, steady recompiles "
            f"{pr['steady_recompiles_bf16']}/"
            f"{pr['steady_recompiles_f32']}, sentinel bf16 detected="
            f"{drl.get('bf16_family_detected')} recovered="
            f"{drl.get('recovered')}")

    if args.precision_requests > 0:
        section("config17_precision", config17_precision)

    # -- config 18: loopback edge drill (PR 15) -----------------------------
    # THE network-edge protocol (serving/measure.py:edge_drill_run): a
    # live edge.EdgeServer over the saturated engine, driven through
    # real loopback sockets — the PR-5 overload acceptance numbers
    # reproduced THROUGH the wire (every request an HTTP terminal
    # within budget, tier-0 goodput >= 95% at >= 3x achieved
    # saturation, shed decisions still O(µs) engine-side with every
    # one mapped to 429 + Retry-After, zero steady recompiles), plus
    # the wire-only legs: stream frames bit-identical to in-process
    # submit_frame, client disconnect -> future.cancel() (terminal
    # kind "cancelled") + session close, SIGTERM-path drain with
    # requests in flight, and /healthz + /metrics scraped through the
    # socket. Criteria (scripts/bench_report.py:judge_edge) are all
    # CPU-defined: saturation is throttled in-process and the sockets
    # are loopback — no chip required, none harmed.
    def config18_edge():
        from mano_hand_tpu.serving.measure import edge_drill_run

        ed = edge_drill_run(
            right,
            saturation=args.edge_saturation,
            bursts=args.edge_bursts,
            workers=args.edge_workers,
            streams=args.edge_streams,
            frames_per_stream=args.edge_frames,
            max_bucket=args.edge_max_bucket,
            seed=47,
            log=lambda m: log(f"config18 {m}"),
        )
        results["edge"] = ed
        oc = ed["outcomes"]
        acc = ed["span_accounting"]
        log(f"config18 edge: {ed['submitted']} wire requests at "
            f"{ed['saturation_achieved']}x achieved -> "
            f"{ed['wire_resolved_within_budget_fraction']:.0%} in "
            f"budget ({oc['ok']} ok / {oc['shed']} shed / "
            f"{oc['expired']} expired / {oc['unresolved']} "
            f"unresolved), tier-0 goodput {ed['tier0_goodput']}, "
            f"stream parity err "
            f"{ed['stream']['wire_vs_inprocess_max_abs_err']}, "
            f"disconnect cancelled {ed['disconnect']['cancelled_total']}"
            f", drain {ed['drain']['drain_wall_s']}s, "
            f"{ed['steady_recompiles']} steady recompiles, spans "
            f"{acc['spans_closed']}/{acc['spans_started']}")

    if args.edge_bursts > 0:
        section("config18_edge", config18_edge)

    # -- config 19: tiered subject store drill (PR 16) ----------------------
    # THE memory-hierarchy protocol (serving/measure.py:
    # subject_store_drill_run): O(100k) registered subjects paged
    # through the device/host/disk hierarchy under Zipf traffic, a
    # capacity-sharded lane fleet judged against its replicated twin on
    # interleaved paired slices. Criteria (scripts/bench_report.py:
    # judge_subject_store) are all CPU-defined: every leg bit-identical
    # to a single-device reference, warm-promotion p99 inside the
    # coalesce window, zero steady recompiles across the capacity
    # ladder (hot-only -> warm-spill -> cold-spill -> cold-revisit),
    # a damaged cold page counted + re-baked (never an error), and
    # per-lane device rows strictly below the replicated baseline.
    # Throughput ratio is [info] off-chip — registration density and
    # row accounting are the point, not CPU wall-clock.
    def config19_subject_store():
        from mano_hand_tpu.serving.measure import subject_store_drill_run

        sd = subject_store_drill_run(
            right,
            subjects=args.subject_store_subjects,
            requests_per_leg=args.subject_store_requests,
            seed=53,
            log=lambda m: log(f"config19 {m}"),
        )
        results["subject_store"] = sd
        oc = sd["outcomes"]
        log(f"config19 subject store: {sd['subjects_registered']} "
            f"subjects through {sd['lanes']} shards, "
            f"{sd['requests_total']} requests ({oc['ok']} ok / "
            f"{oc['error']} error / {oc['stranded']} stranded), "
            f"hot-tier hit rate {sd['hot_tier_hit_rate']}, "
            f"promotion p99 {sd['promotion_stall_ms']['p99_ms']:.3g}ms, "
            f"device rows {sd['per_lane_device_rows_sharded']} vs "
            f"{sd['per_lane_device_rows_replicated']} replicated, "
            f"{sd['steady_recompiles']} steady recompiles, "
            f"damage counted {sd['damage_probe'].get('damage_counted')}")

    if args.subject_store_requests > 0:
        section("config19_subject_store", config19_subject_store)

    # -- config 20: pipelined dispatch drill (PR 17) ------------------------
    # THE dispatch-pipeline protocol (serving/measure.py:
    # dispatch_pipeline_drill_run): a pipelined engine (bounded
    # completion stage, overlapped in-flight dispatches, strict FIFO
    # delivery) judged against its depth-1 serial twin on interleaved
    # legs over the same request streams — drain (saturated capacity),
    # paced steady (queue wait at matched saturated load, plus a
    # mid-leg cancel probe), and chaos (faults landing on in-flight
    # batches). Criteria (scripts/bench_report.py:
    # judge_dispatch_pipeline) are CPU-defined: every leg bit-identical
    # to an unbatched reference AND across the two engines, queue p50
    # cut >= 1.5x, drain throughput >= 1.2x, zero steady recompiles on
    # both sides, every future resolved, every span closed exactly
    # once (chaos leg included), and the serial side's telemetry kept
    # byte-for-byte serial in shape (no pipeline stage rows).
    def config20_dispatch_pipeline():
        from mano_hand_tpu.serving.measure import (
            dispatch_pipeline_drill_run,
        )

        pd = dispatch_pipeline_drill_run(
            right,
            requests_steady=args.pipeline_requests,
            calibrate_requests=args.pipeline_calibrate,
            trials=args.pipeline_trials,
            inflight_depth=args.pipeline_depth,
            max_bucket=args.pipeline_max_bucket,
            device_rtt_s=args.pipeline_rtt,
            seed=0,
            log=lambda m: log(f"config20 {m}"),
        )
        results["dispatch_pipeline"] = pd
        log(f"config20 dispatch pipeline: queue p50 "
            f"{pd['serial_queue_p50_ms']} -> "
            f"{pd['pipelined_queue_p50_ms']}ms "
            f"({pd['queue_p50_speedup']}x), throughput "
            f"{pd['serial_throughput_per_sec']} -> "
            f"{pd['pipelined_throughput_per_sec']}/s "
            f"({pd['throughput_speedup']}x), bit-identical "
            f"{pd['cross_engine_bit_identical']}, futures resolved "
            f"{pd['futures_resolved_fraction']}, inflight peak "
            f"{pd['pipelined_pipeline_inflight_peak']}")

    if args.pipeline_requests > 0:
        section("config20_dispatch_pipeline", config20_dispatch_pipeline)

    # -- config 21: fleet chaos drill (PR 18) -------------------------------
    # THE rolling-deploy protocol (serving/measure.py:fleet_drill_run):
    # N real `mano serve` worker PROCESSES cold-booting from a per-lane
    # executable lattice, fronted by the edge proxy (health-aware
    # routing + live stream migration), with one worker SIGKILLed
    # mid-frame-wave and a second drained under the surviving live
    # streams. Criteria (scripts/bench_report.py:judge_fleet) are all
    # CPU-defined — workers pin `--platform cpu` and the sockets are
    # loopback, no chip involved: per-worker cold boot with ZERO jit
    # compiles at lanes=N (aot_loads > 0), 100% of frames reaching an
    # HTTP terminal through the chaos, migrated warm starts bit-equal
    # (pose chains identical fleet-wide AND vs the in-process
    # reference), drain inside its budget, zero steady recompiles
    # fleet-wide (exit-line counters minus post-warm baselines), and
    # every span closed exactly once across process boundaries (the
    # exit-line accounting of every worker that reported).
    def config21_fleet():
        from mano_hand_tpu.serving.measure import fleet_drill_run

        fd = fleet_drill_run(
            right,
            workers=args.fleet_workers,
            lanes=args.fleet_lanes,
            streams=args.fleet_streams,
            frames_per_stream=args.fleet_frames,
            stream_workers=args.fleet_stream_workers,
            unique_tracks=args.fleet_tracks,
            max_bucket=args.fleet_max_bucket,
            max_subjects=args.fleet_max_subjects,
            drain_budget_s=args.fleet_drain_budget,
            seed=59,
            log=lambda m: log(f"config21 {m}"),
        )
        results["fleet"] = fd
        oc = fd["outcomes"]
        log(f"config21 fleet: {fd['workers']} workers x "
            f"{fd['lanes']} lanes, cold boot zero-compile "
            f"{fd['cold_boot_zero_compiles']}, {fd['streams']} streams"
            f" x {fd['frames_per_stream']} frames -> "
            f"{fd['terminal_fraction']:.0%} terminal ({oc['ok']} ok / "
            f"{oc['http_error']} http / {oc['exception']} exc), "
            f"kill {fd['kill']['victim']} migrated "
            f"{fd['proxy']['migrated_frames']} in-flight, drain "
            f"{fd['drain']['wall_s']}s/{fd['drain']['budget_s']}s, "
            f"pose parity intra {fd['intra_fleet_pose_max_abs_err']} / "
            f"ref {fd['wire_vs_inprocess_pose_max_abs_err']}, "
            f"{fd['steady_recompiles_total']} steady recompiles, "
            f"spans once {fd['spans_closed_exactly_once']}")

    if args.fleet_streams > 0:
        section("config21_fleet", config21_fleet)

    # -- config 22: closed-loop control drill (PR 19) -----------------------
    # THE adaptive-control protocol (serving/measure.py:
    # control_drill_run): the serving.control.Controller versus its own
    # static defaults on ONE seeded flash-crowd trace
    # (serving/traffic.py), replayed through a live edge.EdgeServer on
    # interleaved paired legs, plus a controller-crash leg mid-crowd.
    # Criteria (scripts/bench_report.py:judge_control) are all
    # CPU-defined — saturation is a chaos throttle and the sockets are
    # loopback, no chip involved: controlled tier-0 goodput >= the
    # static baseline on the pooled pairs AND controlled tier-1 served
    # STRICTLY greater (same arrivals — the digest in the artifact is
    # the determinism receipt), zero steady recompiles every leg,
    # every actuation evented (runtime-event count == the counter
    # ledger), spans closed exactly once per leg, and the crash leg
    # reverted to static defaults with 100% of requests reaching an
    # HTTP terminal (a dead controller degrades to today's behavior,
    # never wedges admission).
    def config22_control():
        from mano_hand_tpu.serving.measure import control_drill_run

        cd = control_drill_run(
            right,
            trace_duration_s=args.control_trace_s,
            pairs=args.control_pairs,
            workers=args.control_workers,
            max_bucket=args.control_max_bucket,
            max_queued=args.control_max_queued,
            tier1_quota=args.control_tier1_quota,
            seed=61,
            log=lambda m: log(f"config22 {m}"),
        )
        results["control"] = cd
        cl = cd["crash_leg"]
        log(f"config22 control: {cd['pairs']} pairs on "
            f"{cd['trace']['stats']['arrivals']} arrivals, tier-0 "
            f"goodput {cd['controlled_tier0_goodput']} vs static "
            f"{cd['static_tier0_goodput']}, tier-1 served "
            f"{cd['controlled_tier1_served']} vs "
            f"{cd['static_tier1_served']}, {cd['actuations_total']} "
            f"actuations evented={cd['actuations_evented']}, "
            f"{cd['steady_recompiles_total']} steady recompiles, "
            f"{cd['unresolved_total']} unresolved, crash reverted="
            f"{cl['reverted_to_static']}, spans once "
            f"{cd['spans_closed_exactly_once']}")

    if args.control_pairs > 0:
        section("config22_control", config22_control)

    # -- config 23: self-healing chaos campaign (PR 20) ---------------------
    # THE recovery protocol (serving/measure.py:selfheal_drill_run): a
    # seeded cross-process ChaosCampaign (worker SIGKILL, ACTIVE-proxy
    # SIGKILL, SIGSTOP partition) against a FleetSupervisor-watched
    # fleet behind an active/standby proxy pair, plus the restart-storm
    # leg (budget exhausted -> degraded-with-incident, never flapping)
    # and the in-process leg closing the PR-16 remainder (shard
    # rebalance onto surviving lanes + damaged-cold-page re-bake).
    # Criteria (scripts/bench_report.py:judge_selfheal) are all
    # CPU-defined — workers pin `--platform cpu`, chaos is seeded
    # signals on loopback processes, no chip involved: every death
    # auto-healed with ZERO human invocations (replacements boot from
    # the per-lane lattice with zero jit compiles), 100% of frames
    # reaching an HTTP terminal with continuous numbering and bit-equal
    # poses through the takeover, MTTR p99 inside budget, zero steady
    # recompiles post-heal (live /metrics deltas), spans closed exactly
    # once across process boundaries, storm leg degraded-with-incident,
    # rebalanced shard bit-identical with zero recompiles, damaged page
    # detected and re-baked bit-exactly.
    def config23_selfheal():
        from mano_hand_tpu.serving.measure import selfheal_drill_run

        sd = selfheal_drill_run(
            right,
            workers=args.selfheal_workers,
            lanes=args.selfheal_lanes,
            streams=args.selfheal_streams,
            frames_per_stream=args.selfheal_frames,
            stream_workers=args.selfheal_stream_workers,
            unique_tracks=args.selfheal_tracks,
            max_bucket=args.selfheal_max_bucket,
            max_subjects=args.selfheal_max_subjects,
            mttr_budget_ms=args.selfheal_mttr_budget_ms,
            seed=67,
            log=lambda m: log(f"config23 {m}"),
        )
        results["selfheal"] = sd
        oc = sd["outcomes"]
        log(f"config23 selfheal: {sd['workers']} workers x "
            f"{sd['lanes']} lanes, lattice boot {sd['lattice_boot_ok']}"
            f", {sd['streams']} streams x {sd['frames_per_stream']} "
            f"frames -> {sd['terminal_fraction']:.0%} terminal "
            f"({oc['ok']} ok / {oc['http_error']} http / "
            f"{oc['exception']} exc), {sd['supervisor_restarts']} "
            f"heals for {sd['expected_heals']} deaths (MTTR p99 "
            f"{sd['heal_p99_mttr_ms']} ms), takeover "
            f"{sd['takeover_walls_ms']} ms, pose parity "
            f"{sd['pose_max_abs_err']}, {sd['steady_recompiles_total']}"
            f" steady recompiles, storm incidents "
            f"{sd['storm']['incidents'] if sd.get('storm') else None}, "
            f"rebalance err {sd['rebalance']['max_abs_err']}, damage "
            f"re-bake err {sd['damage']['request_max_abs_err']}, "
            f"spans once {sd['spans_closed_exactly_once']}")

    if args.selfheal_streams > 0:
        section("config23_selfheal", config23_selfheal)

    if args.serving_only:
        # Fast serving-layer artifact (`make serve-smoke`): the deferred
        # runner's serving-only skip reduces the schedule to config7
        # (+ the recovery drill, the config9 coalescing leg and the
        # config10 overload drill).
        for name, fn in _registered:
            run_section(name, fn)
        srv = results.get("serving", {})
        line = {
            "metric": "serving_engine_evals_per_sec",
            "value": srv.get("engine_evals_per_sec"),
            "unit": "evals/s",
            "vs_baseline": None,
            "device": device_str,
            "detail": results,
        }
        if errors:
            line["config_errors"] = errors
        return line

    # -- memory high-water mark ---------------------------------------------
    # A SECTION (not inline code): under the deferred runner, inline code
    # executes at registration time — before any benchmark ran — and
    # would record the pre-benchmark peak.
    def hbm_peak():
        try:
            stats = dev.memory_stats() or {}
            # Key name varies by PJRT plugin; take the first peak-ish one.
            peak = next((stats[k] for k in
                         ("peak_bytes_in_use", "peak_bytes",
                          "max_bytes_in_use")
                         if k in stats), None)
            if peak is not None:
                results["hbm_peak_bytes"] = int(peak)
                log(f"HBM peak: {peak / 2**30:.2f} GiB")
            else:
                log("no peak-memory key; memory_stats keys = "
                    f"{sorted(stats)}")
        except Exception as e:
            log(f"memory stats unavailable: {type(e).__name__}")

    section("hbm_peak", hbm_peak)

    # -- analytic peak memory (compiler-reported, backend-independent) ------
    # The axon runtime exposes no memory_stats; XLA's own buffer assignment
    # does better anyway: temp + argument + output - aliased is the
    # compiled program's high-water mark, available from .memory_analysis()
    # without executing anything. Closes SURVEY §7's "throughput cliff"
    # loop with a number instead of "didn't OOM".
    def memory_probe():
        def analyze(tag, jitted, *xs):
            try:
                mem = jitted.lower(*xs).compile().memory_analysis()
            except Exception as e:  # backend without the hook: skip, note
                log(f"memory_analysis[{tag}] unavailable: "
                    f"{type(e).__name__}: {e}")
                return
            if mem is None:
                log(f"memory_analysis[{tag}] returned None")
                return
            temp = int(getattr(mem, "temp_size_in_bytes", 0))
            arg = int(getattr(mem, "argument_size_in_bytes", 0))
            out = int(getattr(mem, "output_size_in_bytes", 0))
            alias = int(getattr(mem, "alias_size_in_bytes", 0))
            peak = temp + arg + out - alias
            results[f"{tag}_temp_bytes"] = temp
            results[f"{tag}_peak_hbm_bytes"] = peak
            log(f"memory[{tag}]: temp {temp / 2**20:.1f} MiB, "
                f"peak {peak / 2**20:.1f} MiB "
                f"(args {arg / 2**20:.1f} + out {out / 2**20:.1f} "
                f"- alias {alias / 2**20:.1f})")

        analyze(
            "config2_b1024",
            jax.jit(lambda prm, p, s: core.forward_batched(prm, p, s).verts),
            right, pose2, beta2,
        )
        analyze(
            "config3_chunked",
            jax.jit(chunked_interleaved()),
            (left, right), pose3, beta3,
        )
        # The UNchunked full-batch program, for the record: the SAME
        # two-hand B=65536 workload as config3_chunked but with no
        # lax.map bound on the [B, V, 3, 3] blend-rotation intermediate
        # (compile-only — never executed), so the two keys quantify
        # exactly what chunking buys.
        def unchunked_interleaved(prm_pair, p, s):
            pl, pr = prm_pair
            vl = core.forward_batched(pl, p[:half], s[:half]).verts
            vr = core.forward_batched(pr, p[half:], s[half:]).verts
            return vl.sum() + vr.sum()

        analyze(
            "config3_unchunked",
            jax.jit(unchunked_interleaved),
            (left, right), pose3, beta3,
        )
        if args.pallas_sweep != "off":
            analyze(
                "config3_pallas_chunked",
                jax.jit(chunked_interleaved(use_pallas=True, **ikw)),
                (left, right), pose3, beta3,
            )
            analyze(
                "config3_fused_chunked",
                jax.jit(chunked_interleaved(use_pallas_fused=True, **ikw)),
                (left, right), pose3, beta3,
            )
            analyze(
                "config3_fused_full_chunked",
                jax.jit(chunked_interleaved(use_pallas_fused_full=True, **ikw)),
                (left, right), pose3, beta3,
            )

    section("memory_probe", memory_probe)

    # -- ordered execution: done-criteria first -----------------------------
    # Tunnel-up windows can last MINUTES, not hours (r5 live: a window
    # opened, delivered two configs, and died ~3 min in) — so a short
    # window's partial salvage must carry the round's DECIDING numbers,
    # not warm-up trivia. The headline sweep (config3d), the B=65536
    # route (criterion: >=0.85x headline), and the LM rate (criterion:
    # >=180 steps/s) run right after warm-up; everything else follows in
    # registration order. The readback tail (accuracy onward) keeps its
    # position: the first D2H permanently degrades later axon dispatches,
    # and accuracy can only probe kernels whose sections already ran.
    # config14 rides in the priority block (after the headline trio):
    # the fused GATHERED kernel + the lm_e2e sub-leg are exactly the
    # ROADMAP-item-2 numbers the next short tunnel-up window must
    # salvage first (r5 lesson: windows last minutes).
    priority = ["config1_warmup", "sync_probe", "config3d",
                "config3_fused_full_chunked", "config3",
                "config4", "config4b_lm", "config14_posed_kernel",
                "config3e_hands"]
    rank = {name: i for i, name in enumerate(priority)}
    for name, fn in sorted(_registered,
                           key=lambda nf: rank.get(nf[0], len(priority))):
        run_section(name, fn)

    global _FINAL_LINE
    _FINAL_LINE = assemble_line(results, errors, device_str, is_tpu)
    return _FINAL_LINE


def assemble_line(results: dict, errors: dict, device_str: str,
                  is_tpu: bool) -> dict:
    """Headline + roofline + the final JSON line from whatever configs
    completed. Top-level (not inline in run_benchmarks) so the signal
    guard can salvage a PARTIAL artifact from the registered live dicts
    when a kill lands mid-run — configs measured before the signal are a
    strictly better driver artifact than a bare null. Raises when no
    throughput config completed (callers fall back to the null line)."""
    candidates = [results.get("config2_b1024_evals_per_sec"),
                  results.get("config3_b65536_evals_per_sec"),
                  results.get("config3_pallas_chunked_evals_per_sec"),
                  results.get("config3_pallas_evals_per_sec"),
                  results.get("config3_fused_evals_per_sec"),
                  results.get("config3_fused_chunked_evals_per_sec"),
                  results.get("config3_fused_full_evals_per_sec"),
                  results.get("config3_fused_full_chunked_evals_per_sec"),
                  results.get("config3_fused_full_hands_evals_per_sec")]
    candidates = [c for c in candidates if c is not None and np.isfinite(c)]
    if not candidates:
        raise RuntimeError(f"no throughput config completed: {errors}")
    headline = max(candidates)

    fpe = flops_per_eval()
    results["flops_per_eval"] = fpe
    achieved = headline * fpe
    results["achieved_gflops"] = achieved / 1e9
    if is_tpu:
        results["pct_of_v5e_bf16_roofline"] = 100.0 * achieved / V5E_BF16_FLOPS
        # Per-eval HBM traffic floor: the [V,3] f32 output alone (inputs are
        # tiny, params cached in VMEM across the batch). At ~107 FLOP/byte
        # the workload is nominally compute-bound; this is the BW ceiling.
        out_bytes = 778 * 3 * 4
        results["hbm_bound_evals_per_sec"] = V5E_HBM_BYTES_PER_S / out_bytes
        results["pct_of_hbm_roofline"] = (
            100.0 * headline * out_bytes / V5E_HBM_BYTES_PER_S
        )

    line = {
        "metric": "mano_forward_evals_per_sec",
        "value": round(headline, 1),
        "unit": "evals/s",
        "vs_baseline": round(headline / BASELINE_EVALS_PER_SEC, 3),
        "max_err_vs_numpy": results.get("max_err_vs_numpy"),
        "device": device_str,
        "detail": {k: (float(f"{v:.5g}") if isinstance(v, float) else v)
                   for k, v in results.items()},
    }
    if errors:
        line["config_errors"] = errors
    return line


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--big-batch", type=int, default=65536)
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--fit-steps", type=int, default=100)
    ap.add_argument("--sil-size", type=int, default=64,
                    help="mask resolution for the silhouette config "
                         "(smaller for CPU correctness runs)")
    ap.add_argument("--skip-fit", action="store_true")
    ap.add_argument("--pallas-interpret", action="store_true",
                    help="run kernel configs through the Pallas "
                         "interpreter (CI coverage of the sweep logic "
                         "off-TPU; rates are meaningless)")
    ap.add_argument("--pallas-sweep", choices=["off", "quick", "full"],
                    default="full",
                    help="Pallas skinning block-size sweep breadth (full by "
                         "default so unattended driver runs capture the best "
                         "block; 'quick' pins the known-best block)")
    ap.add_argument("--mesh", default="",
                    help="e.g. 'data=8' — also bench a sharded forward over "
                         "an explicit mesh (virtual CPU meshes are "
                         "correctness-only)")
    ap.add_argument("--mesh-scaling", action="store_true",
                    help="emit a per-device-count scaling table (forward + "
                         "sharded fit step: per-shard shapes, collectives, "
                         "rate) over 1,2,4,... visible devices; pair with "
                         "--platform cpu + --virtual-devices N off-TPU")
    ap.add_argument("--mesh-scaling-batch", type=int, default=1024)
    ap.add_argument("--mesh-scaling-only", action="store_true",
                    help="run ONLY the scaling table (fast structural "
                         "artifact; `make mesh-scaling`)")
    ap.add_argument("--serving-requests", type=int, default=192,
                    help="requests per measured pass of the serving-"
                         "engine leg (config7)")
    ap.add_argument("--serving-max-rows", type=int, default=32,
                    help="serving leg request sizes are uniform in "
                         "[1, this]")
    ap.add_argument("--serving-max-bucket", type=int, default=64,
                    help="largest power-of-two serving bucket (bounds "
                         "the leg's warm-up compiles)")
    ap.add_argument("--serving-only", action="store_true",
                    help="run ONLY the serving-engine leg, the "
                         "fault-recovery drill, the mixed-subject "
                         "coalescing leg, the overload drill, the "
                         "cold-start drill and the tracing-overhead "
                         "leg (fast serving-layer artifact; "
                         "`make serve-smoke`)")
    ap.add_argument("--coalesce-subjects", type=int, default=12,
                    help="distinct baked subjects in the mixed-subject "
                         "coalescing leg (config9; >= 8 engages the "
                         "speed criterion, > 8 also exercises a table "
                         "capacity growth; 0 skips the leg)")
    ap.add_argument("--coalesce-requests", type=int, default=96,
                    help="requests per measured pass of the coalescing "
                         "leg (config9)")
    ap.add_argument("--coalesce-max-rows", type=int, default=4,
                    help="config9 request sizes are uniform in "
                         "[1, max-rows] — small on purpose: the "
                         "multi-tenant stream PR 4 targets is "
                         "few-rows-per-user")
    ap.add_argument("--coalesce-max-bucket", type=int, default=64,
                    help="largest power-of-two bucket of the config9 "
                         "engine")
    ap.add_argument("--recovery-requests", type=int, default=12,
                    help="requests per fault class in the recovery "
                         "drill (config7_recovery; faults are injected "
                         "in-process, no chip involved)")
    ap.add_argument("--overload-saturation", type=float, default=4.0,
                    help="offered-load multiple of the MEASURED service "
                         "rate in the overload drill (config10; the "
                         "done-criteria are judged at >= 4x achieved; "
                         "0 skips the leg)")
    ap.add_argument("--overload-bursts", type=int, default=40,
                    help="arrival bursts in the overload drill "
                         "(config10; one burst per 10 ms — saturation "
                         "is throttled in-process, no chip involved)")
    ap.add_argument("--coldstart-requests", type=int, default=32,
                    help="requests per stream of the cold-start drill "
                         "(config11: lattice bake, kill, zero-compile "
                         "restore, damage injections; restarts are "
                         "simulated in-process, no chip involved; "
                         "0 skips the leg)")
    ap.add_argument("--coldstart-subjects", type=int, default=6,
                    help="baked subjects the cold-start drill "
                         "checkpoints and restores (config11)")
    ap.add_argument("--coldstart-max-bucket", type=int, default=8,
                    help="largest power-of-two bucket of the config11 "
                         "engines (bounds the lattice size: every "
                         "bucket bakes full+gather+cpu entries)")
    ap.add_argument("--metrics-requests", type=int, default=160,
                    help="requests per stream repetition of the "
                         "metrics+sentinel leg (config13: observed — "
                         "tracer + metrics registry + numerics "
                         "sentinel — vs bare engine, paired "
                         "interleaved, plus the sentinel wrong-output "
                         "detection drill); 0 skips the leg")
    ap.add_argument("--tracing-requests", type=int, default=160,
                    help="requests per pass of the tracing-overhead "
                         "leg (config12: traced vs untraced engine, "
                         "interleaved; 0 skips the leg)")
    ap.add_argument("--coldstart-waves", type=int, default=6,
                    help="post-restore request waves used to call the "
                         "p99 settled (config11)")
    ap.add_argument("--posed-requests", type=int, default=96,
                    help="requests per slope pass of the fused-gathered-"
                         "kernel leg (config14: fused Pallas tier vs XLA "
                         "gathered program through two engines, slope-"
                         "timed; 0 skips the leg)")
    ap.add_argument("--posed-subjects", type=int, default=8,
                    help="distinct baked subjects in the config14 "
                         "mixed-subject stream")
    ap.add_argument("--posed-max-rows", type=int, default=4,
                    help="config14 request sizes are uniform in "
                         "[1, posed-max-rows]")
    ap.add_argument("--posed-max-bucket", type=int, default=64,
                    help="largest power-of-two bucket of the config14 "
                         "engines")
    ap.add_argument("--posed-lm-batch", type=int, default=32,
                    help="problem batch of config14's end-to-end "
                         "fit_lm steps/s sub-leg (ROADMAP 2b; the "
                         "batched-LU solve measured end to end); 0 "
                         "skips the sub-leg (its step-count programs "
                         "are cold compiles in plumbing-size lanes)")
    ap.add_argument("--stream-streams", type=int, default=208,
                    help="concurrent per-user tracking sessions in the "
                         "streaming-session drill (config15; the "
                         ">= 200-stream criterion is judged at >= 200 "
                         "— smaller runs record without judging; 0 "
                         "skips the leg)")
    ap.add_argument("--stream-frames", type=int, default=4,
                    help="frames per stream in config15 (>= 3: one "
                         "settle round, timed steady rounds, one "
                         "chaos round)")
    ap.add_argument("--stream-subjects", type=int, default=0,
                    help="distinct baked subjects across config15's "
                         "streams (0 = one subject per stream, the "
                         "true multi-tenant shape)")
    ap.add_argument("--stream-workers", type=int, default=16,
                    help="submitter-pool width of the config15 drill "
                         "(concurrent streams' frames coalesce through "
                         "the gathered dispatch)")
    ap.add_argument("--stream-max-bucket", type=int, default=64,
                    help="largest power-of-two bucket of the config15 "
                         "engine")
    ap.add_argument("--lane-lanes", type=int, default=4,
                    help="per-device dispatch lanes of the config16 "
                         "lane-loss drill (PR 13; 0 skips the leg). "
                         "Lanes oversubscribe round-robin when fewer "
                         "devices exist — the acceptance artifact "
                         "(`make serve-smoke`) forces >= 4 virtual CPU "
                         "devices via --virtual-devices")
    ap.add_argument("--lane-requests", type=int, default=96,
                    help="requests per config16 pass (pre-loss / loss "
                         "/ settle / post-failback)")
    ap.add_argument("--lane-subjects", type=int, default=6,
                    help="distinct baked subjects in the config16 "
                         "mixed-subject streams")
    ap.add_argument("--lane-workers", type=int, default=8,
                    help="concurrent submitters of the config16 drill "
                         "(the 'mid-stream' in mid-stream lane loss)")
    ap.add_argument("--lane-max-bucket", type=int, default=16,
                    help="largest power-of-two bucket of the config16 "
                         "engine (each of N lanes warms every bucket — "
                         "keep the product small)")
    ap.add_argument("--precision-requests", type=int, default=96,
                    help="mixed-subject tier-0 request stream of the "
                         "config17 precision-tier leg (PR 14: bf16 "
                         "policy engine vs f32 control, slope-timed; "
                         "0 skips the leg)")
    ap.add_argument("--precision-subjects", type=int, default=8,
                    help="distinct baked subjects in the config17 "
                         "stream (mixed coalesced batches on both "
                         "engines)")
    ap.add_argument("--precision-max-rows", type=int, default=4,
                    help="config17 request sizes are uniform in "
                         "[1, max-rows]")
    ap.add_argument("--precision-max-bucket", type=int, default=32,
                    help="largest power-of-two bucket of the config17 "
                         "engines")
    ap.add_argument("--precision-posed-kernel", default="xla",
                    choices=("xla", "fused"),
                    help="gathered-kernel tier of BOTH config17 "
                         "engines. Default xla — the family whose "
                         "explicit bf16 casts make the CPU-lane "
                         "envelope criterion real (the fused kernel's "
                         "single-pass bf16 form is invisible to the "
                         "interpreter — the documented dead-end). "
                         "bench-interpret sweeps the fused form for "
                         "plumbing coverage (drill + parity judge "
                         "branch must not debut on the chip)")
    ap.add_argument("--edge-bursts", type=int, default=24,
                    help="arrival bursts of the loopback edge drill "
                         "(config18, PR 15: the PR-5 overload criteria "
                         "through real sockets + the stream/disconnect/"
                         "drain wire legs; saturation is throttled "
                         "in-process, sockets are loopback — no chip "
                         "involved; 0 skips the leg)")
    ap.add_argument("--edge-workers", type=int, default=24,
                    help="wire-client worker pool of config18 (one "
                         "persistent connection each; must exceed the "
                         "drill's max_queued or overload can never "
                         "materialize through the blocking clients)")
    ap.add_argument("--edge-streams", type=int, default=3,
                    help="config18 stream-parity sessions (frames "
                         "through the upgrade protocol, judged "
                         "bit-identical to in-process submit_frame)")
    ap.add_argument("--edge-frames", type=int, default=3,
                    help="frames per config18 stream (>= 2: settle + "
                         "parity)")
    ap.add_argument("--edge-max-bucket", type=int, default=8,
                    help="largest power-of-two bucket of the config18 "
                         "engines")
    ap.add_argument("--edge-saturation", type=float, default=5.0,
                    help="offered-load multiple of the socket-"
                         "calibrated service rate in config18 (the "
                         "goodput criterion is judged at >= 3x "
                         "achieved; the wire's blocking clients "
                         "compress bursts, so the target carries "
                         "headroom over the floor)")
    ap.add_argument("--subject-store-subjects", type=int,
                    default=100_000,
                    help="registered-subject universe of the tiered "
                         "subject-store drill (config19, PR 16; "
                         "betas-only registration keeps O(100k) at "
                         "~40B/subject — density is the criterion, "
                         "not wall-clock)")
    ap.add_argument("--subject-store-requests", type=int, default=120,
                    help="requests per capacity-ladder leg of "
                         "config19 (hot-only / warm-spill / "
                         "cold-spill, paired sharded-vs-replicated "
                         "slices; 0 skips the leg)")
    ap.add_argument("--pipeline-requests", type=int, default=240,
                    help="steady-leg requests per trial of the "
                         "pipelined-dispatch drill (config20, PR 17; "
                         "paced at 0.9x the pipelined engine's "
                         "measured capacity; 0 skips the config)")
    ap.add_argument("--pipeline-calibrate", type=int, default=128,
                    help="requests per drain (capacity-calibration) "
                         "leg of config20 — the upfront-backlog legs "
                         "whose min-time sets each engine's measured "
                         "capacity and the steady leg's pace")
    ap.add_argument("--pipeline-trials", type=int, default=5,
                    help="interleaved serial/pipelined repeats of each "
                         "config20 leg (min-time capacities, pooled "
                         "queue-wait percentiles)")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="in-flight depth of config20's pipelined "
                         "engine (its serial twin is always depth 1)")
    ap.add_argument("--pipeline-max-bucket", type=int, default=16,
                    help="bucket ceiling of both config20 engines")
    ap.add_argument("--pipeline-rtt", type=float, default=0.0015,
                    help="config20's injected per-dispatch device "
                         "round-trip (chaos sat model, the documented "
                         "slow-device stand-in for the TPU tunnel)")
    ap.add_argument("--fleet-streams", type=int, default=208,
                    help="live streams of the fleet chaos drill "
                         "(config21, PR 18: 3 `mano serve` worker "
                         "processes behind the edge proxy, one "
                         "SIGKILLed mid-wave + one drained under "
                         "load; workers pin --platform cpu and "
                         "sockets are loopback — no chip involved; "
                         "0 skips the config, and the tiny-e2e bench "
                         "tests pass 0 to keep subprocess fan-out "
                         "out of that lane)")
    ap.add_argument("--fleet-workers", type=int, default=3,
                    help="config21 worker processes (>= 3: kill one, "
                         "drain one, serve on the rest)")
    ap.add_argument("--fleet-lanes", type=int, default=2,
                    help="dispatch lanes per config21 worker (each "
                         "worker gets xla_force_host_platform_device_"
                         "count=N virtual CPU devices; the per-lane "
                         "lattice must boot every lane with zero "
                         "re-traces)")
    ap.add_argument("--fleet-frames", type=int, default=4,
                    help="frames per config21 stream (>= 3: settle "
                         "wave + kill wave + drain tail)")
    ap.add_argument("--fleet-stream-workers", type=int, default=16,
                    help="client thread pool stepping config21's "
                         "streams (one persistent connection per "
                         "stream, one in-flight frame per stream)")
    ap.add_argument("--fleet-tracks", type=int, default=8,
                    help="distinct animation tracks of config21 "
                         "(streams sharing a track must stay "
                         "BIT-equal fleet-wide — the migration "
                         "warm-start judgment)")
    ap.add_argument("--fleet-max-bucket", type=int, default=8,
                    help="bucket ceiling of config21's workers and "
                         "reference engine")
    ap.add_argument("--fleet-max-subjects", type=int, default=32,
                    help="subject capacity of config21's workers "
                         "(keeps the sharded per-lane tables small; "
                         "the per-lane lattice bakes the shard "
                         "capacity)")
    ap.add_argument("--fleet-drain-budget", type=float, default=10.0,
                    help="seconds the config21 rolling-deploy drain "
                         "must finish within (judged)")
    ap.add_argument("--control-pairs", type=int, default=2,
                    help="(static, controlled) leg pairs of the "
                         "closed-loop control drill (config22, PR 19: "
                         "the adaptive controller vs its own static "
                         "defaults on one seeded flash-crowd trace "
                         "through a live loopback edge, plus a "
                         "controller-crash leg; 0 skips the config, "
                         "and the tiny-e2e bench tests pass 0 to keep "
                         "the seconds-long paced replays out of that "
                         "lane)")
    ap.add_argument("--control-trace-s", type=float, default=2.5,
                    help="seconds of the config22 flash-crowd trace "
                         "(every leg replays the same seeded "
                         "arrivals, paced to their offsets)")
    ap.add_argument("--control-workers", type=int, default=24,
                    help="wire-client worker pool of config22 (one "
                         "persistent connection each; must exceed "
                         "max-queued or overload never materializes "
                         "through blocking clients)")
    ap.add_argument("--control-max-bucket", type=int, default=8,
                    help="bucket ceiling of config22's engines")
    ap.add_argument("--control-max-queued", type=int, default=16,
                    help="admission bound of config22's engines (the "
                         "static default the controller steers "
                         "around and the crash leg must revert to)")
    ap.add_argument("--control-tier1-quota", type=int, default=4,
                    help="static tier-1 quota of config22 (the "
                         "baseline the controller must beat on "
                         "tier-1 served without losing tier-0 "
                         "goodput)")
    ap.add_argument("--selfheal-streams", type=int, default=12,
                    help="live streams of the self-healing chaos "
                         "campaign (config23, PR 20: a supervised "
                         "fleet behind an active/standby proxy pair "
                         "under a seeded kill/takeover/partition "
                         "campaign, plus the restart-storm and "
                         "in-process rebalance/damage legs; workers "
                         "pin --platform cpu and sockets are loopback "
                         "— no chip involved; 0 skips the config, and "
                         "the tiny-e2e bench tests pass 0 to keep "
                         "subprocess fan-out out of that lane)")
    ap.add_argument("--selfheal-workers", type=int, default=3,
                    help="config23 worker processes (>= 3: one "
                         "SIGKILLed, one SIGSTOPped, at least one "
                         "always serving)")
    ap.add_argument("--selfheal-lanes", type=int, default=2,
                    help="dispatch lanes per config23 worker (healed "
                         "replacements must boot every lane from the "
                         "per-lane lattice with zero jit compiles)")
    ap.add_argument("--selfheal-frames", type=int, default=7,
                    help="frames per config23 stream (>= 6: settle "
                         "wave + chaos waves + post-heal settle + "
                         "judged steady wave)")
    ap.add_argument("--selfheal-stream-workers", type=int, default=8,
                    help="client thread pool stepping config23's "
                         "resilient streams (one persistent "
                         "connection per stream; reconnect-and-resume "
                         "on transport death)")
    ap.add_argument("--selfheal-tracks", type=int, default=4,
                    help="distinct animation tracks of config23 "
                         "(every frame must stay BIT-equal to the "
                         "in-process reference across heals and the "
                         "proxy takeover)")
    ap.add_argument("--selfheal-max-bucket", type=int, default=8,
                    help="bucket ceiling of config23's workers and "
                         "reference engine")
    ap.add_argument("--selfheal-max-subjects", type=int, default=32,
                    help="subject capacity of config23's workers (the "
                         "per-lane lattice bakes the shard capacity)")
    ap.add_argument("--selfheal-mttr-budget-ms", type=float,
                    default=300000.0,
                    help="per-heal detect-to-ready budget judged at "
                         "p99 (config23; generous — a heal is a full "
                         "worker boot on a 1-core box, and the bar is "
                         "'bounded and honest', not 'fast')")
    ap.add_argument("--spec-batch", type=int, default=256,
                    help="batch for the specialization leg's full-vs-"
                         "pose-only forward comparison (config8); "
                         "0 skips the forward half")
    ap.add_argument("--spec-fit-batch", type=int, default=64,
                    help="problem batch for the specialization leg's "
                         "58-col vs frozen-betas LM comparison (the "
                         "done-criterion is judged at >= 64); 0 skips "
                         "the LM half (its scan compiles dominate "
                         "fresh-cache smoke lanes)")
    ap.add_argument("--profile", default="",
                    help="directory for an XLA profiler trace of the "
                         "winning full-fusion kernel (off by default)")
    ap.add_argument("--virtual-devices", type=int, default=0,
                    help="force N virtual host-platform devices (sets "
                         "XLA_FLAGS before jax loads; cpu only)")
    ap.add_argument("--platform", default="",
                    help="force a JAX platform (e.g. 'cpu'); empty = image "
                         "default (the axon TPU plugin when tunneled)")
    ap.add_argument("--init-retries", type=int, default=60,
                    help="backend bring-up probe attempts (backoff between)")
    ap.add_argument("--init-timeout", type=float, default=120.0,
                    help="seconds before a hung backend probe is killed")
    ap.add_argument("--init-budget", type=float, default=1200.0,
                    help="total seconds of bring-up probing before giving "
                         "up with the valid null line (the driver runs "
                         "with defaults AND kills at ~30 min, so the "
                         "default must fit inside that window; the builder "
                         "wrapper passes its own budget and retries for "
                         "hours)")
    ap.add_argument("--role", choices=["driver", "builder"],
                    default="driver",
                    help="device-lock role: 'driver' (default — the "
                         "authoritative run; claims priority, builder "
                         "loops stand down) or 'builder' (never waits: "
                         "exits immediately if the device is claimed)")
    ap.add_argument("--stall-timeout", type=float, default=600.0,
                    help="watchdog: emit the salvage artifact and exit if "
                         "no measurement progress for this many seconds "
                         "(hung tunnel RPCs defeat the SIGTERM guard — "
                         "the watchdog thread still runs). TPU runs only; "
                         "0 disables")
    ap.add_argument("--emit-by", type=float, default=-1.0,
                    help="watchdog: hard wall-clock seconds from start by "
                         "which the artifact line MUST be on stdout "
                         "(emit best-available and exit). Default: 1620 "
                         "(27 min, inside the driver harness's ~30-min "
                         "kill) for flagless TPU runs; 0 (off) when "
                         "--platform cpu; the builder wrapper passes its "
                         "own value under its attempt cap")
    ap.add_argument("--lock-wait", type=float, default=300.0,
                    help="driver-role seconds to wait for the device lock "
                         "before proceeding without it (advisory). Window "
                         "math: lock-wait + init-budget + configs must fit "
                         "the driver harness's ~30-min kill, so 5 min here "
                         "+ 20 min probing leaves margin for the run itself")
    args = ap.parse_args()
    install_signal_guard()
    if args.emit_by < 0:
        args.emit_by = 0.0 if args.platform == "cpu" else 1620.0
    start_watchdog(0.0 if args.platform == "cpu" else args.stall_timeout,
                   args.emit_by, time.time())

    if args.virtual_devices:
        # Must land in XLA_FLAGS before jaxlib initializes (the probe
        # subprocesses inherit it too). An explicit flag OVERRIDES any
        # inherited count (e.g. the test conftest's 8). Only meaningful
        # with --platform cpu; harmless otherwise.
        import re as _re
        flag = (f"--xla_force_host_platform_device_count="
                f"{args.virtual_devices}")
        prev = _re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = f"{prev} {flag}".strip()

    from mano_hand_tpu.utils.devicelock import DeviceBusy, DeviceLock

    # A CPU-forced run (bench-interpret lane, CI) can never touch the TPU:
    # taking the device lock would only preempt a real builder pipeline
    # (observed live: three interpret runs each cost the wrapper a 300 s
    # stand-down). Such runs skip the lock and use a per-pid compile
    # cache so they also can't co-write the shared one.
    use_lock = args.platform != "cpu"
    import contextlib

    global _ACTIVE_LOCK
    try:
        with (DeviceLock(args.role, wait_s=args.lock_wait, log=log)
              if use_lock else contextlib.nullcontext()) as lock:
            _ACTIVE_LOCK = lock if use_lock else None
            try:
                device_str = bring_up_backend(
                    args.init_retries, args.init_timeout, args.platform,
                    budget_s=args.init_budget)
            except Exception as e:
                emit(_null_line(f"backend bring-up failed: {e}",
                                outage=True))
                return 1

            if args.platform:
                import jax
                jax.config.update("jax_platforms", args.platform)

            _enable_compile_cache(
                locked=use_lock and lock.acquired)
            # Same predicate as run_benchmarks' is_tpu: the tunneled
            # plugin can surface as platform "axon", not "tpu" — a
            # startswith("tpu") gate would leave the stall watchdog
            # disarmed on the exact backend whose hangs it exists for.
            if device_str.split(":")[0] in ("tpu", "axon"):
                arm_watchdog_stall()

            try:
                line = run_benchmarks(args, device_str)
            except Exception as e:
                err = f"{type(e).__name__}: {str(e)[:600]}"
                # An exception escaping a non-isolated statement mid-run
                # (e.g. a device transfer when the tunnel drops) preserves
                # completed configs the same way a kill does.
                crash = _salvage(f"crashed mid-run ({err}); value covers "
                                 "only the configs completed before the "
                                 "crash")
                emit({**(crash or _null_line(err)), "device": device_str})
                return 1
    except DeviceBusy as e:
        emit(_null_line(f"device busy: {e}"))
        return 2
    finally:
        _ACTIVE_LOCK = None

    emit(line)
    return 0


if __name__ == "__main__":
    try:
        rc = main()
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — the one-line contract
        # Backstop for anything the inner handlers missed (found live:
        # a nonexistent MANO_DEVICE_LOCK_DIR made DeviceLock.__enter__
        # raise before any except clause — rc=1, EMPTY stdout).
        if not _EMITTED:
            emit(_null_line(f"unhandled {type(e).__name__}: "
                            f"{str(e)[:600]}"))
        rc = 1
    sys.exit(rc)
