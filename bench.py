"""Benchmark harness: MANO forward throughput on the attached accelerator.

Prints exactly ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "evals/s", "vs_baseline": N, ...}
Everything else goes to stderr.

Baseline: the reference publishes no numbers (BASELINE.md); the target is
the north-star >= 50,000 forward evals/sec on one v5e chip with max vertex
error < 1e-4 vs the float64 NumPy oracle (/root/repo/BASELINE.json).

Covers the BASELINE.json config suite:
  1. single zero-pose eval (vs oracle)        — accuracy anchor
  2. batch=1024 random pose+shape             — throughput
  3. batch=65536, left+right interleaved      — throughput (chunked)
  4. pose-fitting batch=256, 100 Adam steps   — fitting throughput
  5. 120-frame x 2-hand temporal sequence     — latency
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np

BASELINE_EVALS_PER_SEC = 50_000.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def timeit(fn, iters: int = 10, warmup: int = 2):
    """Median wall time of fn() (which must block until ready)."""
    from mano_hand_tpu.utils.profiling import time_jax_fn

    return time_jax_fn(fn, iters=iters, warmup=warmup)["median_s"]


def slope_time(run_m, m1: int, m2: int, iters: int = 5):
    """Per-iteration device time of ``run_m(m)`` via two-point slope.

    The axon TPU tunnel adds a fixed ~70 ms sync overhead per dispatch (and
    ``block_until_ready`` alone under-reports, returning at enqueue). So each
    measurement runs the workload m times INSIDE one jitted program, syncs on
    a scalar readback, and the (m2 - m1) slope cancels the fixed overhead —
    leaving honest sustained device time per workload pass.
    """
    t1 = timeit(run_m(m1), iters=iters, warmup=1)
    t2 = timeit(run_m(m2), iters=iters, warmup=1)
    slope = (t2 - t1) / (m2 - m1)
    if slope <= 0:
        log(f"WARNING: non-positive slope ({t1 * 1e3:.2f} ms @ m={m1}, "
            f"{t2 * 1e3:.2f} ms @ m={m2}) — measurement too noisy, "
            "reporting NaN")
        return float("nan")
    return slope


def looped(jit_fn, m: int, *args):
    """Thunk running jit_fn(*args, m) and truly syncing via scalar D2H."""
    return lambda: float(jit_fn(*args, m))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--big-batch", type=int, default=65536)
    ap.add_argument("--chunk", type=int, default=8192)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--fit-steps", type=int, default=100)
    ap.add_argument("--skip-fit", action="store_true")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.assets import synthetic_pair
    from mano_hand_tpu.fitting import fit
    from mano_hand_tpu.models import core, oracle

    dev = jax.devices()[0]
    log(f"device: {dev.platform}:{dev.device_kind}")

    left64, right64 = synthetic_pair(seed=0)
    right = right64.astype(np.float32).device_put()
    left = left64.astype(np.float32).device_put()
    rng = np.random.default_rng(0)

    results = {}

    # -- config 1: single zero-pose eval + random-pose accuracy --------------
    # Outputs stay ON DEVICE here; the np.asarray readbacks happen only
    # after every timed section. On the axon TPU tunnel the first
    # device->host readback permanently degrades all later dispatches in
    # the process to ~70 ms, so timing must complete before any D2H.
    out1 = core.jit_forward(
        right, jnp.zeros((16, 3), jnp.float32), jnp.zeros(10, jnp.float32)
    )
    poses = rng.normal(scale=0.6, size=(8, 16, 3)).astype(np.float32)
    betas = rng.normal(size=(8, 10)).astype(np.float32)
    outs = core.jit_forward_batched(right, jnp.asarray(poses), jnp.asarray(betas))
    jax.block_until_ready((out1.verts, outs.verts))

    # Enter the tunnel's synchronous mode deterministically (the first D2H
    # readback flips it process-wide) and record the fixed sync overhead
    # that slope_time cancels out of every reported number.
    tiny_sum = jax.jit(lambda x: x.sum())
    float(tiny_sum(jnp.zeros(4)))
    t_sync = timeit(lambda: float(tiny_sum(jnp.zeros(4))), iters=5, warmup=1)
    results["tunnel_sync_ms"] = t_sync * 1e3
    log(f"tunnel fixed sync overhead: {t_sync * 1e3:.1f} ms (cancelled by slope)")

    def loop_scalar(forward_sum):
        """m passes of forward_sum inside one program. forward_sum must
        return a FULL reduction (.sum()) of the output verts: the loop carry
        then depends on every batch element and vertex, so XLA can neither
        elide a pass, hoist it (input varies with i), nor slice-sink the
        batch away (a [0,0,0] probe would let the simplifier compute just
        one batch element)."""

        def run(prm_args, pose, shape, m):
            def body(i, acc):
                p = pose + i.astype(pose.dtype) * 1e-6
                return acc + forward_sum(prm_args, p, shape)

            return jax.lax.fori_loop(0, m, body, jnp.zeros((), pose.dtype))

        return jax.jit(run, static_argnums=3)

    # -- config 2: batch=1024 ----------------------------------------------
    b2 = 1024
    pose2 = jnp.asarray(rng.normal(scale=0.6, size=(b2, 16, 3)), jnp.float32)
    beta2 = jnp.asarray(rng.normal(size=(b2, 10)), jnp.float32)
    fwd2 = loop_scalar(
        lambda prm, p, s: core.forward_batched(prm, p, s).verts.sum()
    )
    t2 = slope_time(lambda m: looped(fwd2, m, right, pose2, beta2), 1, 9,
                    iters=max(1, args.iters // 2))
    results["config2_b1024_evals_per_sec"] = b2 / t2
    log(f"config2 batch=1024: {b2 / t2:,.0f} evals/s ({t2 * 1e3:.2f} ms)")

    # -- config 3: batch=65536, left+right interleaved (chunked) ------------
    b3 = max(2, args.big_batch - (args.big_batch % 2))
    half = b3 // 2
    chunk = max(1, min(args.chunk, half))
    while half % chunk:  # clamp to a divisor so odd CLI args can't crash
        chunk -= 1
    pose3 = jnp.asarray(rng.normal(scale=0.6, size=(b3, 16, 3)), jnp.float32)
    beta3 = jnp.asarray(rng.normal(size=(b3, 10)), jnp.float32)

    def interleaved(prm_pair, p, s):
        # alternate hands by halves of each chunk: two param sets, one graph
        pl, pr = prm_pair
        vl = core.forward_chunked(pl, p[:half], s[:half], chunk)
        vr = core.forward_chunked(pr, p[half:], s[half:], chunk)
        return vl.sum() + vr.sum()

    fwd3 = loop_scalar(interleaved)
    t3 = slope_time(lambda m: looped(fwd3, m, (left, right), pose3, beta3),
                    1, 3, iters=max(3, args.iters // 3))
    results["config3_b65536_evals_per_sec"] = b3 / t3
    log(f"config3 batch={b3} L+R: {b3 / t3:,.0f} evals/s ({t3 * 1e3:.1f} ms)")

    # -- config 3b: same workload through the Pallas fused-skinning kernel --
    def interleaved_pallas(prm_pair, p, s):
        pl_, pr_ = prm_pair
        vl = core.forward_batched_pallas(pl_, p[:half], s[:half])
        vr = core.forward_batched_pallas(pr_, p[half:], s[half:])
        return vl.sum() + vr.sum()

    try:
        fwd3p = loop_scalar(interleaved_pallas)
        t3p = slope_time(
            lambda m: looped(fwd3p, m, (left, right), pose3, beta3),
            1, 3, iters=max(3, args.iters // 3),
        )
        results["config3_pallas_evals_per_sec"] = b3 / t3p
        log(f"config3 pallas: {b3 / t3p:,.0f} evals/s ({t3p * 1e3:.1f} ms)")
    except Exception as e:  # no TPU (CPU run) or kernel regression
        log(f"config3 pallas path skipped: {type(e).__name__}: {e}")

    # -- config 4: pose fitting batch=256 -----------------------------------
    if not args.skip_fit:
        b4 = 256
        pose4 = rng.normal(scale=0.3, size=(b4, 16, 3)).astype(np.float32)
        beta4 = rng.normal(scale=0.5, size=(b4, 10)).astype(np.float32)
        targets = core.jit_forward_batched(
            right, jnp.asarray(pose4), jnp.asarray(beta4)
        ).verts

        def run_fit(steps):
            # fit is jitted with static n_steps; the whole Adam loop is one
            # lax.scan program, so the steps-count slope cancels sync cost.
            return lambda: float(
                fit(right, targets, n_steps=steps, lr=0.05).final_loss.sum()
            )

        s1, s2 = args.fit_steps // 2, args.fit_steps + args.fit_steps // 2
        t_step = slope_time(run_fit, s1, s2, iters=max(2, args.iters // 3))
        t4 = t_step * args.fit_steps
        fit_evals = b4 * args.fit_steps  # fwd+bwd per step
        results["config4_fit_steps_per_sec"] = 1.0 / t_step
        results["config4_fit_evals_per_sec"] = fit_evals / t4
        log(f"config4 fit b=256 x {args.fit_steps} steps: {t4 * 1e3:.1f} ms "
            f"({fit_evals / t4:,.0f} fwd+bwd evals/s)")

    # -- config 5: 120-frame two-hand temporal sequence ---------------------
    t_frames, hands = 120, 2
    pose5 = jnp.asarray(
        rng.normal(scale=0.4, size=(t_frames * hands, 16, 3)), jnp.float32
    )
    beta5 = jnp.zeros((t_frames * hands, 10), jnp.float32)

    def seq(prm_pair, p, s):
        pl, pr = prm_pair
        vl = core.forward_batched(pl, p[:t_frames], s[:t_frames]).verts
        vr = core.forward_batched(pr, p[t_frames:], s[t_frames:]).verts
        return vl.sum() + vr.sum()

    fwd5 = loop_scalar(seq)
    t5 = slope_time(lambda m: looped(fwd5, m, (left, right), pose5, beta5),
                    1, 9, iters=max(1, args.iters // 2))
    results["config5_seq240_ms"] = t5 * 1e3
    log(f"config5 120f x 2 hands: {t5 * 1e3:.2f} ms "
        f"({t_frames * hands / t5:,.0f} evals/s)")

    # -- accuracy readbacks (after ALL timing; D2H poisons axon dispatch) ----
    want = oracle.forward(right64)
    err0 = float(np.abs(np.asarray(out1.verts) - want.verts).max())
    results["config1_zero_pose_max_err"] = err0
    log(f"config1 zero-pose max err vs oracle: {err0:.3e}")
    max_err = 0.0
    for i in range(8):
        w = oracle.forward(right64, pose=poses[i], shape=betas[i]).verts
        max_err = max(max_err, float(np.abs(np.asarray(outs.verts[i]) - w).max()))
    results["max_err_vs_numpy"] = max_err
    log(f"random-pose max err vs oracle: {max_err:.3e}")

    # -- headline ------------------------------------------------------------
    headline = max(
        results["config2_b1024_evals_per_sec"],
        results["config3_b65536_evals_per_sec"],
    )
    line = {
        "metric": "mano_forward_evals_per_sec",
        "value": round(headline, 1),
        "unit": "evals/s",
        "vs_baseline": round(headline / BASELINE_EVALS_PER_SEC, 3),
        "max_err_vs_numpy": max_err,
        "device": f"{dev.platform}:{dev.device_kind}",
        "detail": {k: (float(f"{v:.5g}") if isinstance(v, float) else v)
                   for k, v in results.items()},
    }

    def _finite(x):
        # NaN/inf (noisy slope sentinel) would make the line invalid JSON.
        if isinstance(x, float) and not np.isfinite(x):
            return None
        if isinstance(x, dict):
            return {k: _finite(v) for k, v in x.items()}
        return x

    print(json.dumps(_finite(line)), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
