"""On-chip probe: fit_lm ms/step at the bench's config-4 shape.

Used to attribute LM-step time while optimizing the solver (roadmap
round-3 close-out #1). Current subjects: the batched-LU normal-equation
solve (landed; isolated probe bench_results/probe_solve.py measured 8x
the vmapped Cholesky) and JtJ/Jtr contraction precision.

Run: JAX_PLATFORMS=axon python bench_results/probe_lm_solve.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_compile_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import jax.numpy as jnp

from mano_hand_tpu.assets import synthetic
from mano_hand_tpu.fitting import lm
from mano_hand_tpu.models import core

B, STEPS = 256, 30


def run(label, **kw):
    params = synthetic.synthetic_params(seed=0, dtype="float32")
    key = jax.random.PRNGKey(7)
    pose = 0.3 * jax.random.normal(key, (B, 16, 3), jnp.float32)
    shape = 0.5 * jax.random.normal(
        jax.random.fold_in(key, 1), (B, 10), jnp.float32
    )
    target = jax.vmap(lambda p, s: core.forward(params, p, s).verts)(
        pose, shape
    )
    jax.block_until_ready(target)
    fit = lambda: lm.fit_lm(params, target, n_steps=STEPS, **kw)  # noqa: E731
    out = fit()
    jax.block_until_ready(out)
    n = 10
    t0 = time.perf_counter()
    for _ in range(n):
        out = fit()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    per_step = dt / STEPS
    print(
        f"{label:16s} {per_step*1e3:7.3f} ms/step "
        f"({1/per_step:6.1f} steps/s)  final_loss="
        f"{float(out.final_loss.mean()):.3e}"
    )


def main():
    print("devices:", jax.devices())
    run("analytic+LU")
    run("ad+LU", jacobian="ad")


if __name__ == "__main__":
    main()
