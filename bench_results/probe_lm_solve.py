"""On-chip probe: fit_lm ms/step at the bench's config-4 shape.

Used to attribute LM-step time while optimizing the solver (roadmap
round-3 close-out #1). Current subjects: the batched-LU normal-equation
solve (landed; isolated probe bench_results/probe_solve.py measured 8x
the vmapped Cholesky) and JtJ/Jtr contraction precision.

METHODOLOGY NOTE (the first version of this probe was wrong): timing a
loop of enqueued fits and blocking only once at the end measured
0.049 ms/step for the analytic path — physically impossible (the
[B, V, 3, P] Jacobian slab alone costs more HBM traffic than that per
step). On the axon tunnel, back-to-back dispatches of the SAME program
with the SAME operands do not reliably serialize into device-time sums
the way local backends do. Always block per call, and difference two
n_steps variants (slope method) so the ~70 ms tunnel dispatch cost and
any fixed per-call overhead cancel — the same discipline bench.py uses
for the forward configs.

Run: JAX_PLATFORMS=axon python bench_results/probe_lm_solve.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update(
    "jax_compilation_cache_dir",
    # Overridable so a probe retry loop never shares the test suite's
    # cache (concurrent access to one cache dir has produced segfaults
    # in jax's cache reader — see the Makefile note).
    os.environ.get(
        "MANO_PROBE_CACHE_DIR",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_compile_cache",
        ),
    ),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import jax.numpy as jnp

from mano_hand_tpu.assets import synthetic
from mano_hand_tpu.fitting import lm
from mano_hand_tpu.models import core

B = 256
STEPS_LO, STEPS_HI = 30, 90
REPEATS = 6


def run(label, **kw):
    params = synthetic.synthetic_params(seed=0, dtype="float32")
    key = jax.random.PRNGKey(7)
    pose = 0.3 * jax.random.normal(key, (B, 16, 3), jnp.float32)
    shape = 0.5 * jax.random.normal(
        jax.random.fold_in(key, 1), (B, 10), jnp.float32
    )
    target = jax.vmap(lambda p, s: core.forward(params, p, s).verts)(
        pose, shape
    )
    jax.block_until_ready(target)

    def timed(n_steps):
        out = lm.fit_lm(params, target, n_steps=n_steps, **kw)
        jax.block_until_ready(out)          # warm/compile
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = lm.fit_lm(params, target, n_steps=n_steps, **kw)
            jax.block_until_ready(out)      # block EVERY call
            best = min(best, time.perf_counter() - t0)
        return best, out

    t_lo, _ = timed(STEPS_LO)
    t_hi, out = timed(STEPS_HI)
    per_step = (t_hi - t_lo) / (STEPS_HI - STEPS_LO)
    print(
        f"{label:16s} slope {per_step*1e3:7.3f} ms/step "
        f"({1/per_step:6.1f} steps/s)  "
        f"[t{STEPS_LO}={t_lo*1e3:.1f}ms t{STEPS_HI}={t_hi*1e3:.1f}ms]  "
        f"final_loss={float(out.final_loss.mean()):.3e}"
    )


def main():
    print("devices:", jax.devices())
    run("analytic+LU")
    run("ad+LU", jacobian="ad")


if __name__ == "__main__":
    main()
