"""On-chip probe: batched [B, 58, 58] SPD solve variants for the LM step.

Roadmap round-3 close-out #1: the batched Cholesky is ~1.5-2 ms of the
5.5 ms LM step at b=256. Probe the candidate replacements in isolation
before wiring anything into fitting/lm.py.

Run: JAX_PLATFORMS=axon python bench_results/probe_solve.py
"""

import time

import jax
import jax.numpy as jnp

B, P = 256, 58


def make_spd(key):
    j = jax.random.normal(key, (B, 2400, P), jnp.float32)
    a = jnp.einsum("brp,brq->bpq", j, j) + 1e-3 * jnp.eye(P)
    b = jax.random.normal(jax.random.fold_in(key, 1), (B, P), jnp.float32)
    return a, b


def time_fn(fn, *args, iters=30):
    out = fn(*args)
    jax.block_until_ready(out)
    # slope method: time 1x and (1+iters)x, difference removes dispatch
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    t1 = time.perf_counter()
    return (t1 - t0) / iters


def v_cho(a, b):
    return jax.vmap(
        lambda ai, bi: jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(ai), bi
        )
    )(a, b)


def v_lu(a, b):
    return jnp.linalg.solve(a, b[..., None])[..., 0]


def v_pos(a, b):
    return jax.vmap(
        lambda ai, bi: jax.scipy.linalg.solve(ai, bi, assume_a="pos")
    )(a, b)


def v_inv(a, b):
    return jnp.einsum("bpq,bq->bp", jnp.linalg.inv(a), b)


def v_cg(a, b):
    # 58-dim SPD, damped: Jacobi-preconditioned CG, fixed 12 iters.
    d = jnp.reciprocal(jnp.diagonal(a, axis1=-2, axis2=-1))

    def mv(x):
        return jnp.einsum("bpq,bq->bp", a, x)

    x = jnp.zeros_like(b)
    r = b - mv(x)
    z = d * r
    p = z
    rz = jnp.sum(r * z, -1)
    for _ in range(12):
        ap = mv(p)
        alpha = rz / jnp.sum(p * ap, -1)
        x = x + alpha[:, None] * p
        r = r - alpha[:, None] * ap
        z = d * r
        rz_new = jnp.sum(r * z, -1)
        p = z + (rz_new / rz)[:, None] * p
        rz = rz_new
    return x


def main():
    print("devices:", jax.devices())
    key = jax.random.PRNGKey(0)
    a, b = jax.jit(make_spd)(key)
    jax.block_until_ready((a, b))
    ref = None
    for name, fn in [
        ("cho_factor/cho_solve (current)", v_cho),
        ("jnp.linalg.solve (LU)", v_lu),
        ("scipy solve assume_a=pos", v_pos),
        ("inv + matmul", v_inv),
        ("Jacobi-PCG 12 iters", v_cg),
    ]:
        try:
            jfn = jax.jit(fn)
            t = time_fn(jfn, a, b)
            x = jfn(a, b)
            if ref is None:
                ref = x
                err = 0.0
            else:
                err = float(
                    jnp.max(jnp.abs(x - ref) / (jnp.abs(ref) + 1e-6))
                )
            print(f"{name:35s} {t*1e3:8.3f} ms  rel_err={err:.2e}")
        except Exception as e:  # noqa: BLE001
            print(f"{name:35s} FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
