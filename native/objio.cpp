// Native OBJ serializer for mano_hand_tpu.
//
// The OBJ text format ("v %f %f %f" / "f %d %d %d", 1-indexed faces —
// matching /root/reference/mano_np.py:190-194) is trivially CPU-bound in
// Python at animation scale (hundreds of 778-vertex frames). This writer
// formats into a growable buffer with snprintf (same printf semantics as
// Python's '%' operator, so output is byte-identical) and writes once.
//
// C ABI, loaded via ctypes (no pybind11 in this image). Thread-safe: no
// globals; each call owns its buffer.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

extern "C" {

// Format one mesh as OBJ text into an internal buffer and write it to
// `path`. Returns 0 on success, negative errno-style codes on failure.
int mano_write_obj(const char* path,
                   const double* verts, int64_t n_verts,
                   const int32_t* faces, int64_t n_faces) {
  if (!path || (n_verts > 0 && !verts) || (n_faces > 0 && !faces)) {
    return -1;
  }
  std::string buf;
  buf.reserve(static_cast<size_t>(n_verts) * 40 +
              static_cast<size_t>(n_faces) * 24);
  // %f of a double can exceed 300 chars (e.g. 1e308), so the line buffer
  // must fit three of them; truncation (n >= sizeof line) is still checked.
  char line[1024];
  for (int64_t i = 0; i < n_verts; ++i) {
    int n = snprintf(line, sizeof line, "v %f %f %f\n",
                     verts[3 * i], verts[3 * i + 1], verts[3 * i + 2]);
    if (n < 0 || n >= static_cast<int>(sizeof line)) return -2;
    buf.append(line, static_cast<size_t>(n));
  }
  for (int64_t i = 0; i < n_faces; ++i) {
    int n = snprintf(line, sizeof line, "f %d %d %d\n",
                     faces[3 * i] + 1, faces[3 * i + 1] + 1,
                     faces[3 * i + 2] + 1);
    if (n < 0 || n >= static_cast<int>(sizeof line)) return -2;
    buf.append(line, static_cast<size_t>(n));
  }
  FILE* fp = fopen(path, "w");
  if (!fp) return -3;
  size_t written = fwrite(buf.data(), 1, buf.size(), fp);
  int rc = (written == buf.size()) ? 0 : -4;
  if (fclose(fp) != 0) rc = rc ? rc : -5;
  return rc;
}

// Batch variant: write an animation sequence frame_%05d.obj under `dir`.
// verts is [T, V, 3] contiguous. Returns number of frames written, or a
// negative error code.
int mano_write_obj_sequence(const char* dir, const char* stem,
                            const double* verts, int64_t t_frames,
                            int64_t n_verts,
                            const int32_t* faces, int64_t n_faces) {
  if (!dir || !stem) return -1;
  char path[4096];
  for (int64_t t = 0; t < t_frames; ++t) {
    int n = snprintf(path, sizeof path, "%s/%s_%05lld.obj", dir, stem,
                     static_cast<long long>(t));
    if (n < 0 || n >= static_cast<int>(sizeof path)) return -2;
    int rc = mano_write_obj(path, verts + t * n_verts * 3, n_verts,
                            faces, n_faces);
    if (rc != 0) return rc;
  }
  return static_cast<int>(t_frames);
}

}  // extern "C"
