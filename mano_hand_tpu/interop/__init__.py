"""Interop bridges to neighboring ecosystems.

The reference is pure NumPy, but most downstream MANO users come from
torch-based stacks (manopth/smplx); ``interop.torch_bridge`` gives them a
zero-copy-where-possible on-ramp. ``interop.flax_bridge`` embeds the
forward core in flax networks as a Module.

Bridges import lazily so a torch-only environment never needs flax and
vice versa.
"""

from mano_hand_tpu.interop.torch_bridge import (
    TorchManoLayer,
    forward_from_torch,
    make_torch_layer,
    params_from_torch,
    to_torch,
)

__all__ = [
    "TorchManoLayer",
    "forward_from_torch",
    "make_torch_layer",
    "params_from_torch",
    "to_torch",
    "ManoLayer",
]


def __getattr__(name):
    if name == "ManoLayer":
        from mano_hand_tpu.interop.flax_bridge import ManoLayer

        return ManoLayer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
