"""PyTorch bridge: run the TPU forward from/to torch tensors.

For users migrating from torch MANO stacks (manopth, smplx): keep their
torch data pipeline, swap the model evaluation. Two tiers:

* ``forward_from_torch`` — inference: convert, evaluate, convert back.
* ``TorchManoLayer`` / ``make_torch_layer`` — training: a
  ``torch.autograd.Function`` wraps the JAX forward via ``jax.vjp``, so
  pose/shape/trans gradients flow from a torch loss back into a torch
  optimizer — a drop-in differentiable replacement for manopth/smplx
  layers. Tensor hand-off is zero-copy where the runtimes allow it
  (DLPack for CPU torch -> JAX; NumPy views for JAX -> torch).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core


def _torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in this image
        raise ImportError("interop.torch_bridge requires torch") from e
    return torch


def _to_np(x) -> np.ndarray:
    torch = _torch()
    if isinstance(x, torch.Tensor):
        return x.detach().cpu().numpy()
    if hasattr(x, "toarray"):  # scipy sparse (official-pickle J_regressor)
        return np.asarray(x.toarray())
    return np.asarray(x)


def to_torch(tree: Any):
    """jax/numpy array, ManoOutput, or any NamedTuple/dataclass -> torch.

    Leaves become CPU torch tensors (sharing memory when the source is a
    NumPy-backed array).
    """
    torch = _torch()
    if hasattr(tree, "_asdict"):  # NamedTuple (e.g. ManoOutput)
        return type(tree)(*(to_torch(v) for v in tree))
    if dataclasses.is_dataclass(tree):
        return {
            f.name: to_torch(getattr(tree, f.name))
            for f in dataclasses.fields(tree)
        }
    if isinstance(tree, (list, tuple)):
        return type(tree)(to_torch(v) for v in tree)
    if isinstance(tree, dict):
        return {k: to_torch(v) for k, v in tree.items()}
    if isinstance(tree, (str, type(None), int, float)):
        return tree
    arr = np.ascontiguousarray(np.asarray(tree))
    if not arr.flags.writeable:
        # jax.Array views are read-only; torch.from_numpy would warn about
        # (and allow) writes into them. Copy for a clean owning tensor.
        arr = arr.copy()
    return torch.from_numpy(arr)


def params_from_torch(
    tensors: dict,
    side: str = "right",
    dtype=np.float32,
) -> ManoParams:
    """Build ManoParams from a dict of torch tensors / arrays.

    Accepts this package's key names (schema.py) and the common torch-stack
    aliases (smplx/manopth naming): v_template, shapedirs->shape_basis,
    posedirs->pose_basis ([V,3,135] or transposed [135, V*3]),
    J_regressor->j_regressor, lbs_weights/weights, faces, parents
    (kintree_table's parent row also accepted), hands_components/
    hands_mean -> pca basis/mean.
    """
    t = {k: _to_np(v) for k, v in tensors.items()}

    def pick(*names):
        for n in names:
            if n in t:
                return t[n]
        return None

    required = {
        "v_template": ("v_template", "mesh_template"),
        "shape_basis": ("shape_basis", "shapedirs", "mesh_shape_basis"),
        "pose_basis": ("pose_basis", "posedirs", "mesh_pose_basis"),
        "j_regressor": ("j_regressor", "J_regressor"),
        "lbs_weights": ("lbs_weights", "weights", "skinning_weights"),
        "faces": ("faces", "f"),
        "parents": ("parents", "kintree_table"),
    }
    missing = [
        canonical for canonical, aliases in required.items()
        if pick(*aliases) is None
    ]
    if missing:
        raise ValueError(
            f"params dict is missing required keys: {missing} "
            f"(accepted aliases: "
            f"{ {k: v for k, v in required.items() if k in missing} })"
        )

    v_template = pick("v_template", "mesh_template")
    n_verts = v_template.shape[0]

    pose_basis = pick("pose_basis", "posedirs", "mesh_pose_basis")
    if pose_basis is not None and pose_basis.ndim == 2:
        # torch-stack layout: [P, V*3] (flattened, transposed)
        pose_basis = pose_basis.T.reshape(n_verts, 3, -1)

    parents = pick("parents")
    if parents is None and "kintree_table" in t:
        parents = t["kintree_table"][0]
    # Root encodings seen in the wild: None, -1, or uint32(-1); schema wants
    # -1 and a hashable tuple (parents are static aux data under jit).
    parents = tuple(
        -1 if (p is None or int(p) < 0 or int(p) >= 2**31 - 1) else int(p)
        for p in np.asarray(parents, dtype=object).reshape(-1)
    )

    j_regressor = pick("j_regressor", "J_regressor")

    shape_basis = pick("shape_basis", "shapedirs", "mesh_shape_basis")
    # PCA space covers the articulated joints' axis-angles: 3*(J-1) dims.
    n_pca = 3 * (j_regressor.shape[0] - 1)
    pca_basis = pick("pca_basis", "hands_components", "pose_pca_basis")
    if pca_basis is None:
        pca_basis = np.eye(n_pca)
    pca_mean = pick("pca_mean", "hands_mean", "pose_pca_mean")
    if pca_mean is None:
        pca_mean = np.zeros(pca_basis.shape[1])

    from mano_hand_tpu.assets.schema import validate

    return validate(ManoParams(
        v_template=np.asarray(v_template, dtype),
        shape_basis=np.asarray(shape_basis, dtype),
        pose_basis=np.asarray(pose_basis, dtype),
        j_regressor=np.asarray(j_regressor, dtype),
        lbs_weights=np.asarray(pick("lbs_weights", "weights",
                                    "skinning_weights"), dtype),
        pca_basis=np.asarray(pca_basis, dtype),
        pca_mean=np.asarray(pca_mean, dtype),
        faces=np.asarray(pick("faces", "f"), np.int32),
        parents=parents,
        side=side,
    ))


def _torch_to_jax(t):
    """Detached CPU torch tensor -> JAX array, zero-copy via DLPack when
    the runtimes allow it (contiguous CPU tensors), NumPy otherwise."""
    import jax.numpy as jnp

    torch = _torch()
    if isinstance(t, torch.Tensor):
        t = t.detach()
        if t.device.type == "cpu":
            t = t.contiguous()
            try:
                return jnp.from_dlpack(t)
            except Exception:
                pass  # dtype/layout DLPack won't carry — NumPy fallback
        return jnp.asarray(_to_np(t))
    return jnp.asarray(np.asarray(t))


def _jax_to_torch(x):
    """JAX array -> owning CPU torch tensor (one device_get; the NumPy ->
    torch step is a view, copied only when the buffer is read-only)."""
    torch = _torch()
    arr = np.asarray(x)
    if not arr.flags.writeable:
        arr = arr.copy()
    return torch.from_numpy(arr)


def make_torch_layer(params: ManoParams, pose2rot: bool = True):
    """Differentiable torch -> JAX -> torch MANO layer (the training tier).

    Returns ``layer(pose, shape=None, trans=None) -> (verts, joints)``
    where all tensors are torch and **gradients flow**: the forward runs
    the jitted JAX core, the backward runs one jitted ``jax.vjp`` pull
    (forward recomputed inside the compiled program — cheaper than
    holding JAX residuals hostage across the torch autograd boundary,
    and both directions hit the jit cache after the first call).

    Inputs may be unbatched ([16, 3] / [48]) or batched ([B, 16, 3] /
    [B, 48]); with ``pose2rot=False`` pose is rotation matrices
    ([B?, 16, 3, 3]), the smplx contract. ``trans`` is a global
    translation added to verts and joints (the manopth/smplx layer DOF
    the core model itself doesn't carry). Everything is float32.

    The reference has no autodiff at all (/root/reference/mano_np.py);
    this is parity with the torch MANO layers users migrate from.
    """
    import jax
    import jax.numpy as jnp

    torch = _torch()
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]

    def _core_fwd(pose, shape, trans):
        if pose2rot:
            out = core.forward_batched(params, pose, shape)
        else:
            out = core.forward_batched_rotmats(params, pose, shape)
        return (out.verts + trans[:, None, :],
                out.posed_joints + trans[:, None, :])

    fwd_jit = jax.jit(_core_fwd)

    def _core_bwd(pose, shape, trans, g_verts, g_joints):
        _, vjp_fn = jax.vjp(_core_fwd, pose, shape, trans)
        return vjp_fn((g_verts, g_joints))

    bwd_jit = jax.jit(_core_bwd)

    class _ManoFunction(torch.autograd.Function):
        @staticmethod
        def forward(ctx, pose_t, shape_t, trans_t):
            ctx.save_for_backward(pose_t, shape_t, trans_t)
            verts, joints = fwd_jit(
                _torch_to_jax(pose_t), _torch_to_jax(shape_t),
                _torch_to_jax(trans_t),
            )
            return _jax_to_torch(verts), _jax_to_torch(joints)

        @staticmethod
        def backward(ctx, g_verts, g_joints):
            pose_t, shape_t, trans_t = ctx.saved_tensors
            gp, gs, gt = bwd_jit(
                _torch_to_jax(pose_t), _torch_to_jax(shape_t),
                _torch_to_jax(trans_t),
                _torch_to_jax(g_verts), _torch_to_jax(g_joints),
            )
            return (_jax_to_torch(gp), _jax_to_torch(gs),
                    _jax_to_torch(gt))

    row = (n_joints, 3, 3) if not pose2rot else (n_joints, 3)

    def layer(pose, shape=None, trans=None):
        pose = torch.as_tensor(pose).float()
        if pose2rot:
            batched = pose.dim() == 3 or (
                pose.dim() == 2 and pose.shape[-1] != 3
            )
        else:
            batched = pose.dim() == 4
        lead = (pose.shape[0],) if batched else (1,)
        # torch-side reshapes keep the autograd graph connected to the
        # caller's tensors; the Function itself always sees batched input.
        pose_b = pose.reshape(*lead, *row)
        if shape is None:
            shape_b = torch.zeros((*lead, n_shape))
        else:
            shape_b = torch.as_tensor(shape).float().reshape(*lead, n_shape)
        if trans is None:
            trans_b = torch.zeros((*lead, 3))
        else:
            trans_b = torch.as_tensor(trans).float().reshape(*lead, 3)
        verts, joints = _ManoFunction.apply(pose_b, shape_b, trans_b)
        if not batched:
            return verts[0], joints[0]
        return verts, joints

    return layer


def TorchManoLayer(params: ManoParams, pose2rot: bool = True):
    """``torch.nn.Module`` wrapping ``make_torch_layer`` — registrable in
    ``torch.nn.Sequential``/module trees like the manopth/smplx layers it
    replaces. (A factory, not a class: torch imports stay lazy.)"""
    torch = _torch()
    layer_fn = make_torch_layer(params, pose2rot)

    class _TorchManoModule(torch.nn.Module):
        def forward(self, pose, shape=None, trans=None):
            return layer_fn(pose, shape, trans)

    return _TorchManoModule()


def forward_from_torch(
    params: ManoParams,
    pose,                      # torch [B?, 16, 3] / [B?, 48]; with
                               # pose2rot=False: [B?, 16, 3, 3] matrices
    shape: Optional[Any] = None,  # torch [B?, S]
    pose2rot: bool = True,
):
    """Evaluate the JAX core on torch inputs; outputs as torch tensors.

    Unbatched or batched; ManoOutput fields come back as CPU torch tensors.
    ``pose2rot=False`` takes per-joint rotation MATRICES instead of
    axis-angle — the smplx keyword and contract (rotation-space pipelines
    skip Rodrigues).
    """
    import jax.numpy as jnp

    pose_np = _to_np(pose).astype(np.float32)
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]
    # Select representation-specific pieces ONCE; both paths use the jitted
    # wrappers (per-frame torch pipelines would otherwise re-trace the
    # whole graph eagerly on every call).
    if pose2rot:
        batched = pose_np.ndim == 3 or (
            pose_np.ndim == 2 and pose_np.shape[-1] != 3
        )
        row_shape = (n_joints, 3)
        fwd = core.jit_forward_batched if batched else core.jit_forward
    else:
        batched = pose_np.ndim == 4
        row_shape = (n_joints, 3, 3)
        fwd = (core.jit_forward_batched_rotmats if batched
               else core.jit_forward_rotmats)
    if shape is None:
        shape_np = np.zeros(
            (pose_np.shape[0], n_shape) if batched else (n_shape,),
            np.float32,
        )
    else:
        shape_np = _to_np(shape).astype(np.float32)
    lead = (pose_np.shape[0],) if batched else ()
    pose_j = jnp.asarray(pose_np.reshape(*lead, *row_shape))
    out = fwd(params, pose_j, jnp.asarray(shape_np))
    return to_torch(out)
