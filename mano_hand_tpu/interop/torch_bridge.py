"""PyTorch bridge: run the TPU forward from/to torch tensors.

For users migrating from torch MANO stacks (manopth, smplx): keep their
torch data pipeline, swap the model evaluation. Conversion goes through
NumPy (zero-copy for CPU torch tensors via ``.numpy()`` /
``torch.from_numpy``); gradients do NOT flow across the bridge — use the
JAX core end-to-end (fitting/) when optimizing.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core


def _torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover - torch is in this image
        raise ImportError("interop.torch_bridge requires torch") from e
    return torch


def _to_np(x) -> np.ndarray:
    torch = _torch()
    if isinstance(x, torch.Tensor):
        return x.detach().cpu().numpy()
    if hasattr(x, "toarray"):  # scipy sparse (official-pickle J_regressor)
        return np.asarray(x.toarray())
    return np.asarray(x)


def to_torch(tree: Any):
    """jax/numpy array, ManoOutput, or any NamedTuple/dataclass -> torch.

    Leaves become CPU torch tensors (sharing memory when the source is a
    NumPy-backed array).
    """
    torch = _torch()
    if hasattr(tree, "_asdict"):  # NamedTuple (e.g. ManoOutput)
        return type(tree)(*(to_torch(v) for v in tree))
    if dataclasses.is_dataclass(tree):
        return {
            f.name: to_torch(getattr(tree, f.name))
            for f in dataclasses.fields(tree)
        }
    if isinstance(tree, (list, tuple)):
        return type(tree)(to_torch(v) for v in tree)
    if isinstance(tree, dict):
        return {k: to_torch(v) for k, v in tree.items()}
    if isinstance(tree, (str, type(None), int, float)):
        return tree
    arr = np.ascontiguousarray(np.asarray(tree))
    if not arr.flags.writeable:
        # jax.Array views are read-only; torch.from_numpy would warn about
        # (and allow) writes into them. Copy for a clean owning tensor.
        arr = arr.copy()
    return torch.from_numpy(arr)


def params_from_torch(
    tensors: dict,
    side: str = "right",
    dtype=np.float32,
) -> ManoParams:
    """Build ManoParams from a dict of torch tensors / arrays.

    Accepts this package's key names (schema.py) and the common torch-stack
    aliases (smplx/manopth naming): v_template, shapedirs->shape_basis,
    posedirs->pose_basis ([V,3,135] or transposed [135, V*3]),
    J_regressor->j_regressor, lbs_weights/weights, faces, parents
    (kintree_table's parent row also accepted), hands_components/
    hands_mean -> pca basis/mean.
    """
    t = {k: _to_np(v) for k, v in tensors.items()}

    def pick(*names):
        for n in names:
            if n in t:
                return t[n]
        return None

    required = {
        "v_template": ("v_template", "mesh_template"),
        "shape_basis": ("shape_basis", "shapedirs", "mesh_shape_basis"),
        "pose_basis": ("pose_basis", "posedirs", "mesh_pose_basis"),
        "j_regressor": ("j_regressor", "J_regressor"),
        "lbs_weights": ("lbs_weights", "weights", "skinning_weights"),
        "faces": ("faces", "f"),
        "parents": ("parents", "kintree_table"),
    }
    missing = [
        canonical for canonical, aliases in required.items()
        if pick(*aliases) is None
    ]
    if missing:
        raise ValueError(
            f"params dict is missing required keys: {missing} "
            f"(accepted aliases: "
            f"{ {k: v for k, v in required.items() if k in missing} })"
        )

    v_template = pick("v_template", "mesh_template")
    n_verts = v_template.shape[0]

    pose_basis = pick("pose_basis", "posedirs", "mesh_pose_basis")
    if pose_basis is not None and pose_basis.ndim == 2:
        # torch-stack layout: [P, V*3] (flattened, transposed)
        pose_basis = pose_basis.T.reshape(n_verts, 3, -1)

    parents = pick("parents")
    if parents is None and "kintree_table" in t:
        parents = t["kintree_table"][0]
    # Root encodings seen in the wild: None, -1, or uint32(-1); schema wants
    # -1 and a hashable tuple (parents are static aux data under jit).
    parents = tuple(
        -1 if (p is None or int(p) < 0 or int(p) >= 2**31 - 1) else int(p)
        for p in np.asarray(parents, dtype=object).reshape(-1)
    )

    j_regressor = pick("j_regressor", "J_regressor")

    shape_basis = pick("shape_basis", "shapedirs", "mesh_shape_basis")
    # PCA space covers the articulated joints' axis-angles: 3*(J-1) dims.
    n_pca = 3 * (j_regressor.shape[0] - 1)
    pca_basis = pick("pca_basis", "hands_components", "pose_pca_basis")
    if pca_basis is None:
        pca_basis = np.eye(n_pca)
    pca_mean = pick("pca_mean", "hands_mean", "pose_pca_mean")
    if pca_mean is None:
        pca_mean = np.zeros(pca_basis.shape[1])

    from mano_hand_tpu.assets.schema import validate

    return validate(ManoParams(
        v_template=np.asarray(v_template, dtype),
        shape_basis=np.asarray(shape_basis, dtype),
        pose_basis=np.asarray(pose_basis, dtype),
        j_regressor=np.asarray(j_regressor, dtype),
        lbs_weights=np.asarray(pick("lbs_weights", "weights",
                                    "skinning_weights"), dtype),
        pca_basis=np.asarray(pca_basis, dtype),
        pca_mean=np.asarray(pca_mean, dtype),
        faces=np.asarray(pick("faces", "f"), np.int32),
        parents=parents,
        side=side,
    ))


def forward_from_torch(
    params: ManoParams,
    pose,                      # torch [B?, 16, 3] / [B?, 48]; with
                               # pose2rot=False: [B?, 16, 3, 3] matrices
    shape: Optional[Any] = None,  # torch [B?, S]
    pose2rot: bool = True,
):
    """Evaluate the JAX core on torch inputs; outputs as torch tensors.

    Unbatched or batched; ManoOutput fields come back as CPU torch tensors.
    ``pose2rot=False`` takes per-joint rotation MATRICES instead of
    axis-angle — the smplx keyword and contract (rotation-space pipelines
    skip Rodrigues).
    """
    import jax.numpy as jnp

    pose_np = _to_np(pose).astype(np.float32)
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]
    # Select representation-specific pieces ONCE; both paths use the jitted
    # wrappers (per-frame torch pipelines would otherwise re-trace the
    # whole graph eagerly on every call).
    if pose2rot:
        batched = pose_np.ndim == 3 or (
            pose_np.ndim == 2 and pose_np.shape[-1] != 3
        )
        row_shape = (n_joints, 3)
        fwd = core.jit_forward_batched if batched else core.jit_forward
    else:
        batched = pose_np.ndim == 4
        row_shape = (n_joints, 3, 3)
        fwd = (core.jit_forward_batched_rotmats if batched
               else core.jit_forward_rotmats)
    if shape is None:
        shape_np = np.zeros(
            (pose_np.shape[0], n_shape) if batched else (n_shape,),
            np.float32,
        )
    else:
        shape_np = _to_np(shape).astype(np.float32)
    lead = (pose_np.shape[0],) if batched else ()
    pose_j = jnp.asarray(pose_np.reshape(*lead, *row_shape))
    out = fwd(params, pose_j, jnp.asarray(shape_np))
    return to_torch(out)
