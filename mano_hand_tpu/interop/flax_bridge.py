"""Flax bridge: the MANO forward as a ``flax.linen`` Module.

Embeds the hand model inside flax networks (e.g. an image encoder
regressing (pose, shape) with a differentiable mesh head). The asset
params ride as module constants — not trainable variables — so
``Module.init`` carries no 10 MB of "weights"; optionally the shape
coefficients can be learned as a variable (calibration use case).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from mano_hand_tpu import ops
from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core

POSE_FORMATS = ("aa", "pca", "6d", "rotmat", "quat")


class ManoLayer(nn.Module):
    """Differentiable MANO mesh head.

    Attributes:
      params: the (float32) ManoParams asset, a module constant.
      pose_format: what ``__call__``'s pose argument means —
        ``"aa"`` axis-angle [B, 16, 3] (default); ``"pca"`` PCA
        coefficients [B, n<=45] (+ optional global_rot [B, 3]); ``"6d"``
        the continuous rotation representation [B, 16, 6] (the standard
        regression target for neural pose estimators — continuous, no
        wrap; COLUMN convention — pytorch3d-trained regressors emit the
        ROW convention and decode here to transposed rotations, see
        ``ops.matrix_from_6d``); ``"rotmat"`` rotation matrices
        [B, 16, 3, 3]; ``"quat"``
        quaternions [B, 16, 4] (scalar-first w,x,y,z; normalized
        internally — mocap interchange).
      use_pca: legacy alias for ``pose_format="pca"``.
      learn_shape: if True, beta is a trainable variable of the module
        (shared across the batch — per-subject calibration); else it is an
        input.

    Returns verts [B, V, 3]; the full ManoOutput is available via
    ``forward_full``.
    """

    params: ManoParams
    pose_format: str = "aa"
    use_pca: bool = False
    learn_shape: bool = False

    @nn.compact
    def __call__(
        self,
        pose: jnp.ndarray,
        shape: Optional[jnp.ndarray] = None,
        global_rot: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        return self.forward_full(pose, shape, global_rot).verts

    @nn.compact
    def forward_full(
        self,
        pose: jnp.ndarray,
        shape: Optional[jnp.ndarray] = None,
        global_rot: Optional[jnp.ndarray] = None,
    ):
        if self.use_pca and self.pose_format not in ("aa", "pca"):
            # Contradictory config: silently letting use_pca win would send
            # a 6d/rotmat-shaped input into the PCA decode and fail deep in
            # the core with an opaque reshape error.
            raise ValueError(
                f"use_pca=True conflicts with pose_format="
                f"{self.pose_format!r}; drop use_pca (legacy alias for "
                f"pose_format='pca')"
            )
        fmt = "pca" if self.use_pca else self.pose_format
        if fmt not in POSE_FORMATS:
            raise ValueError(
                f"pose_format must be one of {POSE_FORMATS}, got {fmt!r}"
            )
        n_shape = self.params.shape_basis.shape[-1]
        batch = pose.shape[0]
        if self.learn_shape:
            beta = self.param(
                "beta", nn.initializers.zeros, (n_shape,), jnp.float32
            )
            shape = jnp.broadcast_to(beta, (batch, n_shape))
        elif shape is None:
            shape = jnp.zeros((batch, n_shape), jnp.float32)
        if fmt == "6d":
            return core.forward_batched_rotmats(
                self.params, ops.matrix_from_6d(pose), shape
            )
        if fmt == "quat":
            return core.forward_batched_rotmats(
                self.params, ops.matrix_from_quaternion(pose), shape
            )
        if fmt == "rotmat":
            return core.forward_batched_rotmats(self.params, pose, shape)
        if fmt == "pca":
            full_pose = core.decode_pca(self.params, pose, global_rot)
        else:
            full_pose = pose
        return core.forward_batched(self.params, full_pose, shape)
