"""Flax bridge: the MANO forward as a ``flax.linen`` Module.

Embeds the hand model inside flax networks (e.g. an image encoder
regressing (pose, shape) with a differentiable mesh head). The asset
params ride as module constants — not trainable variables — so
``Module.init`` carries no 10 MB of "weights"; optionally the shape
coefficients can be learned as a variable (calibration use case).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core


class ManoLayer(nn.Module):
    """Differentiable MANO mesh head.

    Attributes:
      params: the (float32) ManoParams asset, a module constant.
      use_pca: if True, ``__call__`` takes PCA coefficients [B, n<=45]
        (+ optional global_rot [B, 3]); else absolute pose [B, 16, 3].
      learn_shape: if True, beta is a trainable variable of the module
        (shared across the batch — per-subject calibration); else it is an
        input.

    Returns verts [B, V, 3]; the full ManoOutput is available via
    ``forward_full``.
    """

    params: ManoParams
    use_pca: bool = False
    learn_shape: bool = False

    @nn.compact
    def __call__(
        self,
        pose: jnp.ndarray,
        shape: Optional[jnp.ndarray] = None,
        global_rot: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        return self.forward_full(pose, shape, global_rot).verts

    @nn.compact
    def forward_full(
        self,
        pose: jnp.ndarray,
        shape: Optional[jnp.ndarray] = None,
        global_rot: Optional[jnp.ndarray] = None,
    ):
        n_shape = self.params.shape_basis.shape[-1]
        batch = pose.shape[0]
        if self.learn_shape:
            beta = self.param(
                "beta", nn.initializers.zeros, (n_shape,), jnp.float32
            )
            shape = jnp.broadcast_to(beta, (batch, n_shape))
        elif shape is None:
            shape = jnp.zeros((batch, n_shape), jnp.float32)
        if self.use_pca:
            full_pose = core.decode_pca(self.params, pose, global_rot)
        else:
            full_pose = pose
        return core.forward_batched(self.params, full_pose, shape)
