"""Exportable metrics registry + SLO accounting (PR 9 tentpole).

Eight PRs of serving machinery report themselves through ad-hoc
``ServingCounters``/``load()`` snapshots with no export format and no
objective to judge against: an operator (or the driver) can ask "what
happened" but not "are we meeting the SLO", and nothing external can
scrape either answer. This module is the aggregate health surface:

* **Instruments.** ``Counter`` (monotone), ``Gauge`` (set-point), and
  ``Quantile`` (bounded-reservoir summary — the ServingCounters
  ``_LATENCY_RESERVOIR`` reasoning) registered on a ``MetricsRegistry``.
* **Collectors.** The existing telemetry sources register as pull
  collectors: ``engine_registry(engine)`` absorbs
  ``ServingCounters.snapshot()``, ``ServingEngine.load()``, the tracer
  accounting, and the per-tier SLO report — each source is read in ITS
  one lock hold (the PR-5 torn-telemetry rule), and the registry's own
  instruments are copied in one registry-lock hold. A collector that
  raises degrades to an ``errors`` entry in the snapshot — telemetry
  must never take the dispatch path down.
* **Export.** ``snapshot()`` is the JSON form; ``prometheus_text``
  renders any snapshot (live or re-loaded from disk) as
  Prometheus-text exposition — `mano status --metrics-dir`/`mano
  serve-bench --metrics DIR` are the entry points.
* **SLOs.** ``slo_report`` turns one counters snapshot into per-tier
  objective accounting: goodput (served/offered), deadline hit rate
  (served/(served+expired)), shed fraction — each with an error-budget
  BURN RATE (actual badness / budgeted badness; > 1.0 means the tier is
  spending budget faster than the objective allows). bench.py config13
  carries the report and ``scripts/bench_report.py`` judges it.

Naming: every exported metric is ``<namespace>_<name>`` (default
namespace ``mano``); counters get no ``_total`` suffix magic — the
``# TYPE`` line is the contract, and the JSON snapshot carries the type
explicitly.

Counter-drift guard (satellite): ``serving_samples`` derives its
metrics GENERICALLY from the snapshot dict — a new ``ServingCounters``
field appears in the export automatically, and a field of a shape this
mapper does not understand is surfaced as a non-zero
``serving_unexported_keys`` gauge instead of vanishing
(tests/test_metrics.py pins both directions).

Clock discipline: ages and uptimes stamp ``time.monotonic()`` (the
analysis wallclock rule); wall-clock appears only as a human-readable
export label, never in arithmetic.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

METRICS_SCHEMA = 1

#: Bounded per-instrument sample reservoir (the ServingCounters
#: _LATENCY_RESERVOIR reasoning at registry scale).
_RESERVOIR = 2048

_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: ServingCounters snapshot keys that are high-water marks or ratios —
#: exported as gauges; every other scalar is a monotone counter.
_SERVING_GAUGE_KEYS = frozenset({
    "queue_depth_peak", "backlog_peak", "padding_waste",
    "coalesce_width_mean",
    # Dispatch-pipeline occupancy high-water (PR 17): how much of
    # ``inflight_depth`` the completion stage actually used.
    "pipeline_inflight_peak",
})


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} must match {_NAME_RE.pattern} "
            "(prometheus-compatible, namespace added at export)")
    return name


class Counter:
    """Monotone event count. ``inc`` only — a counter that can go down
    is a gauge wearing the wrong ``# TYPE`` line."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(
                f"counter {self.name}: inc({n}) would decrease a "
                "monotone counter (use a Gauge)")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self) -> List[Tuple[Optional[dict], float]]:
        return [(None, self.value)]


class Gauge:
    """A set-point that moves both ways (backlog, table capacity, …)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = _check_name(name)
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self) -> List[Tuple[Optional[dict], float]]:
        return [(None, self.value)]


class Quantile:
    """Bounded-reservoir summary: ``observe`` samples, export p50/p99
    (+ count). Ring overwrite on a per-instrument cursor so a long-lived
    server cannot grow memory with traffic (the ServingCounters
    ``record_latency`` pattern)."""

    kind = "quantile"

    def __init__(self, name: str, help: str = "",
                 capacity: int = _RESERVOIR):
        self.name = _check_name(name)
        self.help = help
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._samples_buf: List[float] = []
        self._writes = 0

    def observe(self, v: float) -> None:
        with self._lock:
            if len(self._samples_buf) >= self.capacity:
                self._samples_buf[self._writes % self.capacity] = float(v)
            else:
                self._samples_buf.append(float(v))
            self._writes += 1

    def _samples(self) -> List[Tuple[Optional[dict], float]]:
        with self._lock:
            buf = list(self._samples_buf)
            n = self._writes
        out: List[Tuple[Optional[dict], float]] = []
        if buf:
            arr = np.asarray(buf)
            out.append(({"quantile": "0.5"},
                        float(np.percentile(arr, 50))))
            out.append(({"quantile": "0.99"},
                        float(np.percentile(arr, 99))))
        out.append(({"stat": "count"}, float(n)))
        return out


def sample(value: float, labels: Optional[dict] = None) -> list:
    """One normalized sample: ``[labels-or-None, value]`` — the shape
    collectors return and the exporters consume."""
    return [dict(labels) if labels else None, float(value)]


def metric(kind: str, value=None, *, help: str = "",
           samples: Optional[list] = None) -> dict:
    """One normalized metric struct for a collector's return dict."""
    if samples is None:
        samples = [sample(value)]
    return {"type": kind, "help": help, "samples": samples}


class MetricsRegistry:
    """Lock-light instrument registry with atomic snapshots.

    Thread-safe: submitters/dispatchers tick instruments under each
    instrument's own lock; ``snapshot()`` copies the instrument TABLE
    in one registry-lock hold, then reads each instrument and collector
    OUTSIDE it (each source is internally atomic — its own one lock
    hold), so a scrape never blocks a writer for longer than one copy
    and never publishes a torn view of any single source. Cross-source
    skew (the serving block an instant older than a gauge beside it) is
    inherent to multi-source scraping and documented, not hidden.
    """

    def __init__(self, namespace: str = "mano"):
        self.namespace = _check_name(namespace)
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}
        self._collectors: List[Tuple[str, Callable[[], dict]]] = []

    # ------------------------------------------------------- registration
    def _register(self, inst):
        with self._lock:
            cur = self._instruments.get(inst.name)
            if cur is not None:
                if type(cur) is not type(inst):
                    raise ValueError(
                        f"metric {inst.name!r} already registered as "
                        f"{type(cur).__name__}")
                return cur
            self._instruments[inst.name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._register(Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._register(Gauge(name, help))

    def quantile(self, name: str, help: str = "",
                 capacity: int = _RESERVOIR) -> Quantile:
        return self._register(Quantile(name, help, capacity=capacity))

    def register_collector(self, name: str,
                           fn: Callable[[], dict]) -> None:
        """``fn() -> {metric_name: metric-struct}`` pulled per snapshot.
        The callable owns its atomicity (one lock hold per source)."""
        with self._lock:
            self._collectors.append((_check_name(name), fn))

    # ------------------------------------------------------------ readers
    def snapshot(self) -> dict:
        """The JSON export: every instrument + every collector, each
        read atomically; a failing collector degrades to an ``errors``
        entry (telemetry never crashes the path it observes)."""
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = list(self._collectors)
        metrics: Dict[str, dict] = {}
        for inst in instruments:
            metrics[inst.name] = {
                "type": inst.kind,
                "help": inst.help,
                "samples": [[labels, value]
                            for labels, value in inst._samples()],
            }
        errors: Dict[str, str] = {}
        for name, fn in collectors:
            try:
                got = fn()
            except Exception as e:  # noqa: BLE001 — degrade, never raise
                errors[name] = f"{type(e).__name__}: {e}"
                continue
            for mname, struct in got.items():
                metrics[_check_name(mname)] = struct
        out = {
            "schema": METRICS_SCHEMA,
            "namespace": self.namespace,
            "t_monotonic": time.monotonic(),
            "wall_time_utc": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "metrics": metrics,
        }
        if errors:
            out["errors"] = errors
        return out

    def prometheus(self) -> str:
        return prometheus_text(self.snapshot())


#: The persisted-scrape filename contract — ONE definition shared by
#: every writer (`serve-bench --metrics`, config13's metrics_dir) and
#: the reader (`mano status --metrics-dir`): a rename applied to one
#: side cannot silently break the other.
METRICS_JSON = "metrics.json"
METRICS_PROM = "metrics.prom"
SLO_JSON = "slo.json"


def export_metrics_dir(snapshot: dict, out_dir, slo: Optional[dict]
                       = None) -> dict:
    """Persist one registry snapshot into ``out_dir`` as the JSON +
    Prometheus-text pair (+ the SLO report when given); returns the
    written paths. Raises OSError on an unwritable dir — callers own
    the degrade-vs-crash decision (the --trace export rule)."""
    import json
    from pathlib import Path

    d = Path(out_dir)
    d.mkdir(parents=True, exist_ok=True)
    (d / METRICS_JSON).write_text(json.dumps(snapshot))
    (d / METRICS_PROM).write_text(prometheus_text(snapshot))
    out = {"metrics_json": str(d / METRICS_JSON),
           "metrics_prom": str(d / METRICS_PROM)}
    if slo is not None:
        (d / SLO_JSON).write_text(json.dumps(slo))
        out["slo_json"] = str(d / SLO_JSON)
    return out


def _prom_name(name: str) -> str:
    """Exposition-safe metric/label NAME: once requests arrive over
    the wire (PR 15), bucket/kind/subject strings are user-influenced
    and may reach a label key or a reloaded snapshot's metric name —
    anything outside ``[a-zA-Z_][a-zA-Z0-9_]*`` is folded to ``_`` so
    the text format stays parseable (values are escaped, names cannot
    be)."""
    name = re.sub(r"[^a-zA-Z0-9_]", "_", str(name))
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _prom_escape(value: str) -> str:
    """Exposition-format label-value escaping: ``\\`` first (so the
    escapes below cannot be double-escaped), then ``"`` and newlines;
    a bare CR is folded into the newline escape — the format is
    line-delimited and an unescaped CR would tear a sample line in
    CRLF-aware parsers."""
    value = str(value).replace("\\", "\\\\").replace('"', '\\"')
    value = value.replace("\r\n", "\n").replace("\r", "\n")
    return value.replace("\n", "\\n")


def _prom_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    parts = [f'{_prom_name(k)}="{_prom_escape(v)}"'
             for k, v in sorted(labels.items())]
    return "{" + ",".join(parts) + "}"


def prometheus_text(snapshot: dict) -> str:
    """Render a registry snapshot (live, or re-loaded from the JSON a
    ``serve-bench --metrics DIR`` run persisted) as Prometheus text
    exposition. Pure function of the snapshot, so `mano status` can
    serve the text form without the process that owned the registry."""
    ns = _prom_name(snapshot.get("namespace", "mano"))
    lines: List[str] = []
    # "quantile" summaries render as untyped gauges per-quantile —
    # prometheus's native summary type requires _sum/_count pairs this
    # registry deliberately does not fake.
    type_map = {"counter": "counter", "gauge": "gauge",
                "quantile": "gauge"}
    for name in sorted(snapshot.get("metrics", {})):
        m = snapshot["metrics"][name]
        # Re-sanitize here, not just at registration: this renderer
        # also serves snapshots RE-LOADED from disk (`mano status
        # --prom`) whose names never passed _check_name.
        full = f"{ns}_{_prom_name(name)}"
        if m.get("help"):
            esc = str(m["help"]).replace("\\", "\\\\")
            esc = esc.replace("\r\n", " ").replace("\r", " ")
            esc = esc.replace("\n", " ")
            lines.append(f"# HELP {full} {esc}")
        lines.append(f"# TYPE {full} {type_map.get(m.get('type'), 'gauge')}")
        for labels, value in m.get("samples", []):
            v = float(value)
            txt = ("NaN" if np.isnan(v)
                   else ("+Inf" if v == np.inf
                         else ("-Inf" if v == -np.inf else repr(v))))
            lines.append(f"{full}{_prom_labels(labels)} {txt}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------- collectors
def serving_samples(snap: dict) -> dict:
    """``ServingCounters.snapshot()`` -> metric structs, derived
    GENERICALLY so a newly added counter field cannot silently skip the
    export (the counter-drift satellite): scalars become
    ``serving_<key>`` counters/gauges, the per-tier ledgers become
    tier-labeled counters, the latency table becomes bucket-labeled
    gauges — and any key of a shape this mapper does not understand is
    counted in ``serving_unexported_keys`` (a non-zero value IS the
    drift alarm; the introspection test pins it at zero)."""
    out: Dict[str, dict] = {}
    unexported = 0
    for key, val in snap.items():
        if key == "tiers" and isinstance(val, dict):
            fields: Dict[str, list] = {}
            for tier, ledger in val.items():
                if not isinstance(ledger, dict):
                    unexported += 1
                    continue
                for f, v in ledger.items():
                    fields.setdefault(f, []).append(
                        sample(v, {"tier": tier}))
            for f, samples in fields.items():
                out[f"serving_tier_{f}"] = metric(
                    "counter", help=f"per-tier {f} ledger",
                    samples=samples)
        elif key == "latency_by_bucket" and isinstance(val, dict):
            p50, p99, counts = [], [], []
            for bucket, q in val.items():
                lb = {"bucket": str(bucket)}
                p50.append(sample(q.get("p50_ms", 0.0), lb))
                p99.append(sample(q.get("p99_ms", 0.0), lb))
                counts.append(sample(q.get("n", 0), lb))
            if p50:
                out["serving_latency_p50_ms"] = metric(
                    "gauge", help="per-bucket request latency p50",
                    samples=p50)
                out["serving_latency_p99_ms"] = metric(
                    "gauge", help="per-bucket request latency p99",
                    samples=p99)
                out["serving_latency_samples"] = metric(
                    "gauge", help="per-bucket latency sample count",
                    samples=counts)
        elif key == "subject_store_promotion_ms" and isinstance(val, dict):
            # The tiered-store promotion-stall summary (PR 16): one
            # {p50_ms, p99_ms, n} dict — the quantiles export as
            # gauges, the sample count as its own gauge (the
            # latency_by_bucket convention without the bucket label).
            out["serving_subject_store_promotion_p50_ms"] = metric(
                "gauge", val.get("p50_ms", 0.0),
                help="subject-store promotion stall p50 (install-path "
                     "wait for a warm/cold row to be device-ready)")
            out["serving_subject_store_promotion_p99_ms"] = metric(
                "gauge", val.get("p99_ms", 0.0),
                help="subject-store promotion stall p99")
            out["serving_subject_store_promotion_samples"] = metric(
                "gauge", val.get("n", 0),
                help="promotion stall sample count")
        elif isinstance(val, bool) or not isinstance(val, (int, float)):
            unexported += 1
        else:
            kind = ("gauge" if key in _SERVING_GAUGE_KEYS
                    or isinstance(val, float) else "counter")
            out[f"serving_{key}"] = metric(
                kind, val, help=f"ServingCounters.{key}")
    out["serving_unexported_keys"] = metric(
        "gauge", unexported,
        help="snapshot keys the metrics mapper could not classify "
             "(non-zero = counter drift; see obs/metrics.py)")
    return out


def load_samples(load: dict) -> dict:
    """``ServingEngine.load()`` -> metric structs: the backpressure
    snapshot as scrapeable gauges (admission state encoded
    ok=0/busy=1/shed=2 per tier)."""
    out: Dict[str, dict] = {}
    for key in ("outstanding", "queued", "backlog_peak"):
        if load.get(key) is not None:
            out[f"load_{key}"] = metric(
                "gauge", load[key], help=f"load().{key}")
    if load.get("max_queued") is not None:
        out["load_max_queued"] = metric(
            "gauge", load["max_queued"], help="bounded-admission cap")
    states = {"ok": 0, "busy": 1, "shed": 2}
    admission = [
        sample(states.get(state, -1), {"tier": tier})
        for tier, state in (load.get("admission") or {}).items()
    ]
    if admission:
        out["load_admission_state"] = metric(
            "gauge", help="per-tier admission state (0=ok 1=busy 2=shed)",
            samples=admission)
    lat = load.get("latency_by_tier") or {}
    p50 = [sample(q.get("p50_ms", 0.0), {"tier": t})
           for t, q in lat.items()]
    p99 = [sample(q.get("p99_ms", 0.0), {"tier": t})
           for t, q in lat.items()]
    if p50:
        out["load_latency_p50_ms"] = metric(
            "gauge", help="per-tier served-request latency p50",
            samples=p50)
        out["load_latency_p99_ms"] = metric(
            "gauge", help="per-tier served-request latency p99",
            samples=p99)
    if load.get("backlog_age_s") is not None:
        out["load_backlog_age_s"] = metric(
            "gauge", load["backlog_age_s"],
            help="age of the oldest still-open request span")
    streams = load.get("streams") or {}
    for key, help_txt in (
            ("active", "open stream sessions"),
            ("frames_in_flight", "stream frames submitted, unresolved"),
            ("backlog_age_s", "age of the oldest in-flight stream "
                              "frame across sessions"),
            ("opened", "stream sessions ever opened"),
            ("frames_submitted", "stream frames ever submitted"),
            ("frames_resolved", "stream frames resolved (any kind)")):
        if streams.get(key) is not None:
            out[f"load_streams_{key}"] = metric(
                "gauge" if key in ("active", "frames_in_flight",
                                   "backlog_age_s") else "counter",
                streams[key], help=help_txt)
    closed = [sample(v, {"kind": k})
              for k, v in (streams.get("closed_by_kind") or {}).items()]
    if closed:
        out["load_streams_closed_by_kind"] = metric(
            "counter", help="stream-session terminals by kind",
            samples=closed)
    # Precision tiers (PR 14): which tier serves which precision
    # family (0=f32, 1=bf16 per tier label) and the policy's stated
    # vertex-error envelope — the scrape-side record an operator (or
    # an alert) reads beside the sentinel's bf16 drift gauges.
    prec = load.get("precision") or {}
    tiers = [
        sample(1.0 if dtype == "bf16" else 0.0, {"tier": t})
        for t, dtype in sorted((prec.get("tiers") or {}).items())
    ]
    if tiers:
        out["load_precision_tier_bf16"] = metric(
            "gauge", help="per-tier precision family "
                          "(1=bf16 pose path, 0=f32)",
            samples=tiers)
    if prec.get("envelope_m") is not None:
        out["load_precision_envelope_m"] = metric(
            "gauge", prec["envelope_m"],
            help="stated bf16-tier max vertex error envelope (m)")
    # Dispatch lanes (PR 13): fleet-level gauges plus the per-lane
    # backlog/state/ladder counters, labelled by lane index.
    lanes = load.get("lanes") or {}
    for key, help_txt in (
            ("n_lanes", "configured per-device dispatch lanes"),
            ("n_devices", "distinct devices behind the lanes"),
            ("healthy", "lanes whose breaker is not DOWN"),
            ("backlog_rows_total", "queued+in-flight rows fleet-wide")):
        if lanes.get(key) is not None:
            out[f"load_lanes_{key}"] = metric(
                "gauge", lanes[key], help=help_txt)
    per = lanes.get("per_lane") or []
    if per:
        states = {"healthy": 0, "degraded": 1, "down": 2}
        for key, kind, help_txt in (
                ("table_capacity", "gauge", "allocated device table "
                                            "rows"),
                ("resident_rows", "gauge", "device rows actually "
                                           "holding a subject"),
                ("backlog_rows", "gauge", "queued+in-flight rows"),
                ("inflight", "gauge", "batches executing now"),
                ("assigned", "counter", "batches ever placed here"),
                ("dispatched", "counter", "batches that reached a "
                                          "device"),
                ("served_requests", "counter", "requests resolved ok"),
                ("failovers_out", "counter", "batches handed "
                                             "up-ladder"),
                ("failovers_in", "counter", "sibling batches absorbed"),
                ("cpu_failovers", "counter", "batches that fell "
                                             "through to CPU"),
                ("errors", "counter", "batches resolved as "
                                      "ServingError")):
            out[f"load_lane_{key}"] = metric(
                kind, help=f"per-lane {help_txt}",
                samples=[sample(p.get(key, 0),
                                {"lane": str(p.get("lane"))})
                         for p in per])
        out["load_lane_state"] = metric(
            "gauge", help="per-lane breaker state "
                          "(0=healthy 1=degraded 2=down)",
            samples=[sample(states.get(p.get("state"), -1),
                            {"lane": str(p.get("lane"))})
                     for p in per])
    # Tiered subject store (PR 16): warm/cold occupancy — the hit/miss
    # COUNTERS ride the generic serving_samples mapper; these are the
    # set-point gauges only load() knows.
    store = load.get("subject_store") or {}
    for key, help_txt in (
            ("warm_rows", "host-RAM warm-tier rows resident"),
            ("warm_capacity", "warm-tier LRU bound"),
            ("promotions_pending", "async host->device promotions "
                                   "in flight"),
            ("cold_pages", "cold-tier row pages on disk")):
        if store.get(key) is not None:
            out[f"load_subject_store_{key}"] = metric(
                "gauge", store[key], help=help_txt)
    # Closed-loop control (PR 19): controller liveness + the actuated
    # set points — an operator reads THESE beside the burn-rate gauges
    # to see what the controller decided and whether it is alive. The
    # tick/actuation/revert counters ride serving_samples; these are
    # the states and values only the control block knows.
    ctl = load.get("control") or {}
    for key, help_txt in (
            ("attached", "a controller is attached (0/1)"),
            ("running", "controller tick thread alive (0/1)"),
            ("crashed", "controller crashed; engine reverted to "
                        "static defaults (0/1)"),
            ("version", "controller actuation version (torn-snapshot "
                        "anchor)")):
        if ctl.get(key) is not None:
            out[f"load_control_{key}"] = metric(
                "gauge", int(ctl[key]), help=help_txt)
    values = ctl.get("values") or {}
    for key, help_txt in (
            ("coalesce_base_s", "actuated coalesce window base"),
            ("max_queued", "actuated bounded-admission cap"),
            ("bucket_bias", "actuated bucket-ladder selection bias")):
        if values.get(key) is not None:
            out[f"load_control_{key}"] = metric(
                "gauge", values[key], help=help_txt)
    retry = [sample(v, {"tier": t})
             for t, v in sorted((values.get("retry_after_s")
                                 or {}).items())]
    if retry:
        out["load_control_retry_after_s"] = metric(
            "gauge", help="actuated per-tier Retry-After (seconds)",
            samples=retry)
    return out


def tracer_samples(acc: dict) -> dict:
    """``Tracer.accounting()`` -> metric structs (the closed-exactly-
    once criterion as scrapeable numbers)."""
    out = {
        "trace_spans_started": metric("counter",
                                      acc.get("spans_started", 0)),
        "trace_spans_closed": metric("counter",
                                     acc.get("spans_closed", 0)),
        "trace_spans_open": metric("gauge", acc.get("spans_open", 0)),
        "trace_events_dropped": metric("counter",
                                       acc.get("events_dropped", 0)),
        "trace_incidents": metric("counter", acc.get("incidents", 0)),
    }
    by_kind = [sample(v, {"kind": k})
               for k, v in (acc.get("closed_by_kind") or {}).items()]
    if by_kind:
        out["trace_closed_by_kind"] = metric(
            "counter", help="span terminal resolutions by kind",
            samples=by_kind)
    return out


# ---------------------------------------------------------------- SLO layer
#: Default per-tier objectives. Tier 0 is the interactive class (the
#: PR-5 goodput criterion's 95% floor restated as a 99% target with a
#: burn-rate denominator); tiers >= 1 are batch work whose shed budget
#: IS the overload design (they absorb sheds so tier 0 doesn't).
DEFAULT_SLO_OBJECTIVES = {
    "0": {"goodput_target": 0.99, "deadline_hit_target": 0.999,
          "shed_budget": 0.01},
    "default": {"goodput_target": 0.50, "deadline_hit_target": 0.99,
                "shed_budget": 0.75},
}


def _burn(actual_good: float, target_good: float) -> float:
    """Error-budget burn rate: observed badness / budgeted badness.
    1.0 = exactly on budget; > 1.0 = burning faster than the objective
    allows; a zero budget (target 1.0) burns infinitely on any miss."""
    budget = 1.0 - target_good
    bad = 1.0 - actual_good
    if budget <= 0.0:
        return 0.0 if bad <= 0.0 else float("inf")
    return bad / budget


def slo_report(counters_snapshot: dict,
               objectives: Optional[dict] = None,
               latency_by_tier: Optional[dict] = None) -> dict:
    """Per-tier SLO accounting from ONE counters snapshot (pass the
    same dict the serving export used — two snapshot() calls would tear
    the two views apart). Returns per tier: the observed rates, each
    objective, and its error-budget burn rate; ``ok`` iff every burn
    rate <= 1.0. Requests still in flight (offered but not yet
    resolved) are excluded from the deadline-hit denominator but kept
    in goodput's offered denominator — goodput is a statement about
    offered load, not about resolved outcomes only.

    ``latency_by_tier`` (PR 12 — the ``load()``/tracer quantile dict,
    ``{tier: {"p50_ms", "p99_ms", ...}}``) adds a LATENCY objective for
    any tier whose objectives carry ``p99_target_ms``: the burn rate is
    observed p99 over the target (> 1.0 = frames are landing slower
    than the stream SLO allows). Absent either side, the report is
    byte-identical to the PR-9 shape — existing consumers see no new
    keys they did not opt into."""
    objectives = objectives or DEFAULT_SLO_OBJECTIVES
    tiers_out: Dict[str, dict] = {}
    for tier, ledger in (counters_snapshot.get("tiers") or {}).items():
        obj = objectives.get(tier, objectives.get(
            "default", DEFAULT_SLO_OBJECTIVES["default"]))
        submitted = int(ledger.get("submitted", 0))
        served = int(ledger.get("served", 0))
        shed = int(ledger.get("shed", 0))
        expired = int(ledger.get("expired", 0))
        # Caller-cancelled requests (PR 13) leave the offered load: the
        # caller withdrew the work, so neither goodput nor the shed
        # fraction should charge the engine for not serving it.
        cancelled = int(ledger.get("cancelled", 0))
        offered = max(0, submitted - cancelled)
        goodput = served / offered if offered else 1.0
        decided = served + expired
        deadline_hit = served / decided if decided else 1.0
        shed_fraction = shed / offered if offered else 0.0
        burns = {
            "goodput": _burn(goodput, obj["goodput_target"]),
            "deadline_hit": _burn(deadline_hit,
                                  obj["deadline_hit_target"]),
            "shed": (0.0 if obj["shed_budget"] <= 0 and shed_fraction <= 0
                     else (float("inf") if obj["shed_budget"] <= 0
                           else shed_fraction / obj["shed_budget"])),
        }
        p99_target = obj.get("p99_target_ms")
        lat = (latency_by_tier or {}).get(tier) or {}
        if p99_target and lat.get("p99_ms") is not None:
            # Latency burn: observed badness IS the quantile itself, so
            # the rate is the direct quotient (1.0 = exactly at target).
            burns["latency_p99"] = lat["p99_ms"] / p99_target
        tiers_out[tier] = {
            "submitted": submitted,
            "served": served,
            "shed": shed,
            "expired": expired,
            # Shape-stable for pre-PR-13 consumers: the key appears
            # only once a caller actually cancelled something.
            **({"cancelled": cancelled} if cancelled else {}),
            **({"latency_p99_ms": round(float(lat["p99_ms"]), 4)}
               if p99_target and lat.get("p99_ms") is not None else {}),
            "goodput": round(goodput, 6),
            "deadline_hit_rate": round(deadline_hit, 6),
            "shed_fraction": round(shed_fraction, 6),
            "objectives": dict(obj),
            "burn_rates": {k: (v if v == float("inf")
                               else round(v, 4))
                           for k, v in burns.items()},
            "ok": all(v <= 1.0 for v in burns.values()),
        }
    return {
        "schema": METRICS_SCHEMA,
        "tiers": tiers_out,
        "ok": all(t["ok"] for t in tiers_out.values()) if tiers_out
              else True,
    }


def slo_samples(report: dict) -> dict:
    """An ``slo_report`` -> metric structs (burn rates as the scrape-
    and-alert surface)."""
    goodput, burns, ok = [], [], []
    for tier, t in (report.get("tiers") or {}).items():
        goodput.append(sample(t["goodput"], {"tier": tier}))
        ok.append(sample(1.0 if t["ok"] else 0.0, {"tier": tier}))
        for objective, v in t["burn_rates"].items():
            burns.append(sample(v, {"tier": tier,
                                    "objective": objective}))
    out: Dict[str, dict] = {}
    if goodput:
        out["slo_goodput"] = metric(
            "gauge", help="served / offered per tier", samples=goodput)
        out["slo_burn_rate"] = metric(
            "gauge",
            help="error-budget burn rate per (tier, objective); "
                 "> 1 = over budget",
            samples=burns)
        out["slo_ok"] = metric(
            "gauge", help="1 iff every burn rate <= 1", samples=ok)
    return out


def register_engine_collectors(reg: MetricsRegistry, engine,
                               tracer=None, sentinel=None,
                               objectives: Optional[dict] = None,
                               ) -> MetricsRegistry:
    """Absorb one engine's telemetry sources into an EXISTING registry
    — ``ServingCounters`` (+ the SLO report derived from the SAME
    snapshot, one lock hold), ``load()``, the tracer accounting, and
    (when given) the numerics sentinel's probe/drift counters."""

    def _serving() -> dict:
        snap = engine.counters.snapshot()   # ONE lock-held copy
        out = serving_samples(snap)
        out.update(slo_samples(slo_report(snap, objectives)))
        return out

    reg.register_collector("serving", _serving)
    reg.register_collector("load", lambda: load_samples(engine.load()))
    tr = tracer if tracer is not None else engine.tracer
    if tr is not None:
        reg.register_collector(
            "tracer", lambda: tracer_samples(tr.accounting()))
    if sentinel is not None:
        reg.register_collector("sentinel", sentinel.samples)
    return reg


def engine_registry(engine, tracer=None, sentinel=None,
                    objectives: Optional[dict] = None,
                    namespace: str = "mano") -> MetricsRegistry:
    """THE engine wiring: one fresh registry absorbing every telemetry
    source the serving stack already maintains (see
    ``register_engine_collectors``)."""
    return register_engine_collectors(
        MetricsRegistry(namespace), engine, tracer=tracer,
        sentinel=sentinel, objectives=objectives)
