"""Leveled stderr logger for the serving/runtime stack (PR 8 satellite).

Two channels, chosen by what the message IS — not by where it happens
to print:

* ``debug``/``info`` — progress and diagnostics. Written straight to
  **stderr**, gated by the logger level (``MANO_LOG`` env var, default
  ``warning`` so library callers stay silent unless they opt in).
  NEVER stdout: ``bench.py`` and `mano serve-bench` own stdout as a
  one-JSON-line artifact channel, and a stray progress print there
  corrupts the driver's parse (the contract tests/test_cli.py pins
  under ``--trace``).
* ``warning`` — structured degradation (a damaged AOT artifact, a
  checkpoint that would serve another asset's subjects). Routed through
  Python's ``warnings`` machinery, NOT a bare stderr write: callers can
  catch, filter, or assert on degradation (``pytest.warns`` pins these
  contracts in tests/test_serving.py and tests/test_coldstart.py), and
  the default warning printer already lands on stderr.
* ``error`` — always written to stderr, level-independent.

No handlers, no formatters, no config files: one process-wide level, a
per-logger name prefix, and nothing imported beyond the stdlib — the
logger must stay importable from the engine's hot path without
touching jax.
"""

from __future__ import annotations

import os
import sys
import threading
import warnings
from typing import Dict, Optional, TextIO

#: Level names -> numeric rank (stdlib-logging-compatible ordering).
LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}

#: Environment switch: ``MANO_LOG=info`` (or debug/warning/error) raises
#: or lowers the process default for loggers that don't pin their own
#: level. Unknown values fall back to "warning" (quiet).
ENV_VAR = "MANO_LOG"

_DEFAULT_LEVEL = "warning"


def _resolve(level: Optional[str]) -> int:
    if level is None:
        level = os.environ.get(ENV_VAR, _DEFAULT_LEVEL)
    return LEVELS.get(str(level).lower(), LEVELS[_DEFAULT_LEVEL])


class Logger:
    """One named, leveled stderr logger (see the module docstring for
    the channel split). ``level=None`` follows the ``MANO_LOG`` env var
    at construction time; an explicit level pins it (the CLI pins
    ``info`` so `serve-bench` progress is visible by default)."""

    def __init__(self, name: str, level: Optional[str] = None,
                 stream: Optional[TextIO] = None):
        self.name = name
        self._rank = _resolve(level)
        self._stream = stream   # None = sys.stderr resolved per write
                                # (capsys/redirect-friendly)

    def enabled(self, level: str) -> bool:
        return LEVELS.get(level, LEVELS["error"]) >= self._rank

    def _write(self, level: str, msg: str) -> None:
        stream = self._stream if self._stream is not None else sys.stderr
        print(f"[{level}] {self.name}: {msg}", file=stream, flush=True)

    def debug(self, msg: str) -> None:
        if self.enabled("debug"):
            self._write("debug", msg)

    def info(self, msg: str) -> None:
        if self.enabled("info"):
            self._write("info", msg)

    def warning(self, msg: str, category=UserWarning,
                stacklevel: int = 2) -> None:
        """Degradation channel: a real ``warnings.warn`` so callers can
        catch/filter/assert (the engine's damaged-artifact contracts),
        prefixed with the logger name for grep-ability. The warnings
        printer writes stderr; stdout stays pure. The default
        ``stacklevel=2`` (passed through verbatim: level 1 is the
        ``warn()`` call in this method, level 2 its caller) attributes
        the warning to the actual degradation site, not this shim."""
        warnings.warn(f"{self.name}: {msg}", category,
                      stacklevel=stacklevel)

    def error(self, msg: str) -> None:
        self._write("error", msg)


_REGISTRY: Dict[str, Logger] = {}
_REGISTRY_LOCK = threading.Lock()


def get_logger(name: str, level: Optional[str] = None) -> Logger:
    """Process-cached logger per name. An explicit ``level`` re-pins an
    existing logger (the CLI forcing ``info`` on a library logger)."""
    with _REGISTRY_LOCK:
        lg = _REGISTRY.get(name)
        if lg is None:
            lg = _REGISTRY[name] = Logger(name, level=level)
        elif level is not None:
            lg._rank = _resolve(level)
        return lg
