"""Continuous numerics monitoring for the serving stack (PR 9).

Two silent precision collapses in this repo's history were only ever
caught by probing the compiled path ON the device, in the same
compilation context as the timed path (docs/roadmap.md process notes;
the CLAUDE.md numerics rule). Serving has the same exposure
continuously: a lattice entry deserialized wrong, a driver regression,
a tunnel-level corruption (chaos kind ``wrong``) all return
plausible-looking floats that no retry, breaker, or deadline will ever
flag. The ``NumericsSentinel`` turns the one-shot probe into a
standing guarantee:

* **Golden input.** A committed deterministic input
  (``golden_inputs``: fixed seed, fixed rows — the same arrays every
  process, every round).
* **Every live program family, in the serving context.** Each probe
  runs the golden input through the engine's OWN cached executables —
  the chaos-wrapped, possibly lattice-loaded objects real dispatches
  use (``ServingEngine.numerics_probe_targets``) — for every family
  currently live: ``full``, gathered pose-only, and the CPU-failover
  tier. Only already-warm families are probed: the sentinel never
  compiles, so steady-state stays zero-recompile.
* **f32 digests against clean references.** Each served output's
  digest is compared against a clean reference executable built from
  the SAME trace (the bit-identity policy: params/table as runtime
  arguments ⇒ f32 ``==``). A mismatch raises a ``numerics_drift``
  incident on the PR-8 tracer timeline — the flight recorder captures
  the moment — and each probe rides a span closed EXACTLY once
  (terminal kind ``probe``/``drift``/``error``), the engine's
  span-accounting criterion extended to the sentinel itself.
* **Committed goldens.** ``arm()`` additionally digests the clean
  reference at the committed fixed shape and compares it against
  ``obs/goldens.json`` (committed for the synthetic asset on the CPU
  backend; regenerate with ``python -m mano_hand_tpu.obs.sentinel``
  after an INTENTIONAL numerics change, the analysis-baseline
  workflow). A mismatch there means the ENVIRONMENT drifted (new
  XLA/jax float folding) — reported as ``numerics_golden_mismatch``,
  distinct from a live serving-path drift.

Proven by drill, not hoped: bench config13's sentinel drill
(serving/measure.py:metrics_overhead_run) injects the chaos
``wrong``-output fault into a live engine and the sentinel MUST detect
it — judged by scripts/bench_report.py.

Threading: ``start()`` arms a low-rate background daemon probe
(bounded ``Event.wait`` loop — never a bare retry loop); every stamp
is ``time.monotonic()`` (the analysis wallclock rule). On a tunneled
backend a probe can hang in a device RPC like any dispatch — the
thread is daemon (abandonable) and ``status()`` exposes the last-probe
age so a wedged sentinel is itself observable.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from pathlib import Path
from typing import Dict, Optional

import numpy as np

from mano_hand_tpu.obs import log as obs_log
from mano_hand_tpu.obs.metrics import metric

GOLDENS_SCHEMA = 1
#: Committed golden-input identity: rows and seed are part of the
#: golden contract — change either and every committed digest is void.
GOLDEN_SEED = 20260804
GOLDEN_ROWS = 4

_LOG = obs_log.get_logger("obs.sentinel")


def default_goldens_path() -> Path:
    return Path(__file__).resolve().parent / "goldens.json"


def default_bf16_goldens_path() -> Path:
    """The committed bf16-tier goldens (PR 14), beside goldens.json.

    A SEPARATE file with a different comparator: the bf16 family is a
    reduced-precision program, so its committed record is a digest of
    its own deterministic output PLUS its measured error against the
    f32 truth — judged against the PrecisionPolicy ENVELOPE, never by
    f32-digest equality (which a bf16 program can never satisfy)."""
    return Path(__file__).resolve().parent / "goldens_bf16.json"


def golden_inputs(n_joints: int, n_shape: int, rows: int = GOLDEN_ROWS,
                  seed: int = GOLDEN_SEED):
    """THE committed golden input: deterministic (fixed seed) pose and
    shape arrays — identical bytes every process, every asset with the
    same dims."""
    rng = np.random.default_rng(seed)
    pose = rng.normal(scale=0.4, size=(rows, n_joints, 3)).astype(
        np.float32)
    shape = rng.normal(size=(rows, n_shape)).astype(np.float32)
    return pose, shape


def f32_digest(arr) -> str:
    """Content digest of an array's f32 bytes (the bit-identity
    comparator: two digests equal iff the outputs are f32 ``==``)."""
    a = np.ascontiguousarray(np.asarray(arr, np.float32))
    return hashlib.sha256(a.tobytes()).hexdigest()[:16]


def reference_digests(params, rows: int = GOLDEN_ROWS,
                      seed: int = GOLDEN_SEED) -> dict:
    """Clean-path golden digests on the CURRENT backend at the
    committed fixed shape — what ``commit_goldens`` persists and
    ``arm()`` re-derives for comparison."""
    import jax

    from mano_hand_tpu.models import core

    pose, shape = golden_inputs(params.n_joints, params.n_shape,
                                rows=rows, seed=seed)
    prm = params.astype(np.float32).device_put()
    full = np.asarray(jax.jit(
        lambda q, p, s: core.forward_batched(q, p, s).verts)(
            prm, pose, shape))
    cpu_dev = jax.devices("cpu")[0]
    prm_cpu = jax.device_put(params.astype(np.float32), cpu_dev)
    cpu = np.asarray(jax.jit(
        lambda q, p, s: core.forward_batched(q, p, s).verts)(
            prm_cpu, jax.device_put(pose, cpu_dev),
            jax.device_put(shape, cpu_dev)))
    return {"full": f32_digest(full), "cpu": f32_digest(cpu)}


def _golden_table(params, rows: int = GOLDEN_ROWS, seed: int = GOLDEN_SEED):
    """A deterministic SubjectTable of the golden subjects: row ``i``
    bakes golden shape row ``i`` — the committed fixed table the bf16
    gathered references run over (identical bytes every process)."""
    from mano_hand_tpu.models import core

    _, shape = golden_inputs(params.n_joints, params.n_shape,
                             rows=rows, seed=seed)
    prm = params.astype(np.float32).device_put()
    shaped = [core.jit_specialize(prm, shape[i]) for i in range(rows)]
    return core.stack_shaped(shaped)


def reference_digests_bf16(params, rows: int = GOLDEN_ROWS,
                           seed: int = GOLDEN_SEED) -> dict:
    """Clean bf16-tier golden record on the CURRENT backend at the
    committed fixed shape: the bf16 gathered family's output digest
    plus its measured max abs error vs the f32 gathered truth — what
    ``commit_goldens_bf16`` persists and ``arm()`` re-derives."""
    import jax
    import jax.numpy as jnp

    from mano_hand_tpu.models import core

    pose, _ = golden_inputs(params.n_joints, params.n_shape,
                            rows=rows, seed=seed)
    table = _golden_table(params, rows=rows, seed=seed)
    idx = np.arange(rows, dtype=np.int32)
    bf = np.asarray(jax.jit(
        lambda t, i, p: core.forward_posed_gather(
            t, i, p, compute_dtype=jnp.bfloat16).verts)(table, idx, pose))
    f32 = np.asarray(jax.jit(
        lambda t, i, p: core.forward_posed_gather(t, i, p).verts)(
            table, idx, pose))
    return {"gather_bf16": {
        "digest": f32_digest(bf),
        "max_abs_err_vs_f32": float(np.abs(
            bf.astype(np.float32) - f32.astype(np.float32)).max()),
    }}


def _commit_golden_file(params, path, derive, rows: int,
                        seed: int) -> dict:
    """Shared body of ``commit_goldens``/``commit_goldens_bf16``:
    merge-with-existing (one file carries every (params_digest,
    backend) pair ever committed; a damaged or schema/shape-mismatched
    file is rewritten whole), derive the entry, write sorted JSON."""
    import jax

    from mano_hand_tpu.io.export_aot import params_digest

    # Key on the f32-cast params: that is what a ServingEngine holds
    # (engine __init__ casts to its dtype), so ``arm()``'s lookup key
    # matches regardless of the asset file's storage dtype.
    params = params.astype(np.float32)
    data = {"schema": GOLDENS_SCHEMA, "rows": rows, "seed": seed,
            "entries": {}}
    if path.exists():
        try:
            old = json.loads(path.read_text())
            if (old.get("schema") == GOLDENS_SCHEMA
                    and old.get("rows") == rows
                    and old.get("seed") == seed):
                data["entries"] = dict(old.get("entries") or {})
        except (OSError, ValueError):
            pass   # damaged file: rewrite whole
    key = f"{params_digest(params)}:{jax.default_backend()}"
    data["entries"][key] = derive(params, rows=rows, seed=seed)
    path.write_text(json.dumps(data, indent=2, sort_keys=True) + "\n")
    return data


def commit_goldens_bf16(params, path=None, rows: int = GOLDEN_ROWS,
                        seed: int = GOLDEN_SEED) -> dict:
    """Write the committed bf16-tier goldens for ``params`` on the
    current backend (same merge/keying rules as ``commit_goldens``)."""
    path = Path(path) if path is not None else default_bf16_goldens_path()
    return _commit_golden_file(params, path, reference_digests_bf16,
                               rows, seed)


def commit_goldens(params, path=None, rows: int = GOLDEN_ROWS,
                   seed: int = GOLDEN_SEED) -> dict:
    """Write the committed-goldens file for ``params`` on the current
    backend (merging with existing entries — one file carries every
    (params_digest, backend) pair ever committed)."""
    path = Path(path) if path is not None else default_goldens_path()
    return _commit_golden_file(params, path, reference_digests,
                               rows, seed)


def load_goldens(path=None) -> Optional[dict]:
    path = Path(path) if path is not None else default_goldens_path()
    if not path.exists():
        return None
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if data.get("schema") != GOLDENS_SCHEMA:
        return None
    return data


class NumericsSentinel:
    """Low-rate background numerics probe over one ``ServingEngine``.

    One instance per engine; ``probe()`` for a manual pass, ``start()``
    for the background loop. Thread-safe: one private lock guards the
    result/counter state, never held across device work or tracer
    calls (the obs/ lock rule)."""

    def __init__(self, engine, tracer=None, interval_s: float = 60.0,
                 goldens_path=None, bf16_goldens_path=None,
                 clock=time.monotonic):
        if interval_s <= 0:
            raise ValueError(
                f"interval_s must be > 0, got {interval_s}")
        self._engine = engine
        self._tracer = (tracer if tracer is not None
                        else getattr(engine, "tracer", None))
        self.interval_s = float(interval_s)
        self._goldens_path = goldens_path
        self._bf16_goldens_path = bf16_goldens_path
        self._clock = clock
        self._lock = threading.Lock()
        self._refs: Dict[str, object] = {}
        self._params_cpu = None
        self._cpu_dev = None
        self.probes = 0
        self.drifts = 0
        self.probe_errors = 0
        self.golden_status = "unchecked"   # unchecked|match|mismatch|absent
        # The bf16 tier's committed-golden anchor (PR 14); stays
        # "unchecked" on a policy-less engine (nothing to anchor).
        self.golden_bf16_status = "unchecked"
        self._last: Optional[dict] = None
        self._last_t: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- clean refs
    # Each reference is the SAME trace as the engine's builder
    # (serving/engine.py:build_*_executable) re-jitted without chaos
    # wrapping: identical jaxpr -> identical XLA program -> f32
    # bit-identical output (the params-as-runtime-args policy every
    # bit-identity test in this repo pins). Compiles happen at the
    # sentinel's FIRST probe of a (family, shape) — arm-time work,
    # cached by jax thereafter; engine counters never tick.
    def _ref_full(self):
        ref = self._refs.get("full")
        if ref is None:
            import jax

            from mano_hand_tpu.models import core

            ref = jax.jit(
                lambda q, p, s: core.forward_batched(q, p, s).verts)
            self._refs["full"] = ref
        return ref

    def _ref_gather(self, targets):
        """Tier-aware (PR 10): the engine's gather callables are either
        the XLA gathered program or the fused Pallas kernel
        (``targets["gather_fused"]``) — the two families are ~1e-5
        apart, not bit-identical, so the clean reference MUST re-jit
        the SAME family (an XLA reference under the fused tier would
        read as permanent drift; the same-trace rule every other
        reference here follows)."""
        fused = bool(targets.get("gather_fused"))
        key = "gather_fused" if fused else "gather"
        ref = self._refs.get(key)
        if ref is None:
            import jax

            from mano_hand_tpu.models import core

            if fused:
                interp = bool(targets.get("gather_fused_interpret"))
                ref = jax.jit(
                    lambda t, i, p: core.forward_posed_gather_fused(
                        t, i, p, interpret=interp))
            else:
                ref = jax.jit(
                    lambda t, i, p: core.forward_posed_gather(t, i, p).verts)
            self._refs[key] = ref
        return ref

    def _ref_gather_bf16(self, targets):
        """The bf16 tier's same-trace reference (PR 14): the engine's
        bf16 gather callables are either the XLA bf16-compute family
        or the fused kernel's single-pass bf16 form — the reference
        re-jits the SAME family (the same-trace rule), so its digest
        pins the served path exactly while the ENVELOPE judgment runs
        against the f32 reference."""
        fused = bool(targets.get("gather_fused"))
        key = "gather_bf16_fused" if fused else "gather_bf16"
        ref = self._refs.get(key)
        if ref is None:
            import jax
            import jax.numpy as jnp

            from mano_hand_tpu.models import core

            if fused:
                interp = bool(targets.get("gather_fused_interpret"))
                ref = jax.jit(
                    lambda t, i, p: core.forward_posed_gather_fused(
                        t, i, p, interpret=interp,
                        compute_dtype=jnp.bfloat16))
            else:
                ref = jax.jit(
                    lambda t, i, p: core.forward_posed_gather(
                        t, i, p, compute_dtype=jnp.bfloat16).verts)
            self._refs[key] = ref
        return ref

    def _ref_gather_truth(self):
        """The f32 XLA gathered program — the TRUTH the bf16 tier's
        envelope is measured against, independent of which kernel
        family serves (fused or XLA, bf16 or f32). On a non-fused
        engine ``_ref_gather`` already holds this exact program under
        ``"gather"`` — alias it rather than compiling a twin (the
        probe path should pay at most one reference compile per
        family)."""
        ref = self._refs.get("gather_truth")
        if ref is None:
            ref = self._refs.get("gather")
            if ref is None:
                import jax

                from mano_hand_tpu.models import core

                ref = jax.jit(
                    lambda t, i, p: core.forward_posed_gather(
                        t, i, p).verts)
            self._refs["gather_truth"] = ref
        return ref

    def _cpu_inputs(self, params_host):
        import jax

        if self._params_cpu is None:
            self._cpu_dev = jax.devices("cpu")[0]
            self._params_cpu = jax.device_put(
                params_host, self._cpu_dev)
        return self._params_cpu, self._cpu_dev

    # ---------------------------------------------------------------- probing
    def arm(self) -> dict:
        """One-time baseline: derive the clean golden digests at the
        committed fixed shape and check them against the committed
        goldens file (match / mismatch / absent for this
        (params_digest, backend)). A mismatch is ENVIRONMENT drift —
        incident ``numerics_golden_mismatch`` — not a serving-path
        fault; per-probe serving checks are independent of it."""
        import jax

        from mano_hand_tpu.io.export_aot import params_digest

        t = self._engine.numerics_probe_targets()
        got = reference_digests(t["params"])
        committed = load_goldens(self._goldens_path)
        key = f"{params_digest(t['params'])}:{jax.default_backend()}"
        entry = (committed or {}).get("entries", {}).get(key)
        if entry is None:
            status = "absent"
        elif entry == got:
            status = "match"
        else:
            status = "mismatch"
            _LOG.warning(
                f"committed golden digests for {key} do not match this "
                f"environment (committed {entry}, derived {got}) — "
                "XLA/jax numerics drifted since the goldens were "
                "committed; regenerate with `python -m "
                "mano_hand_tpu.obs.sentinel` if intentional")
            if self._tracer is not None:
                self._tracer.incident("numerics_golden_mismatch",
                                      key=key)
        out = {"golden_status": status, "key": key, "derived": got,
               "committed": entry}
        bf16_status = "unchecked"
        envelope = t.get("precision_envelope")
        if envelope is not None:
            # The bf16-tier anchor (PR 14): the derived record must
            # reproduce the committed DIGEST (environment determinism,
            # same rule as the f32 goldens) AND its measured error vs
            # the f32 truth must sit inside the policy's stated
            # ENVELOPE — the comparator a reduced-precision family
            # actually admits. Either failure is environment-level
            # numerics drift, reported distinctly from a live
            # serving-path drift.
            got_bf16 = reference_digests_bf16(t["params"])
            committed_bf16 = load_goldens(
                self._bf16_goldens_path
                if self._bf16_goldens_path is not None
                else default_bf16_goldens_path())
            entry_bf16 = (committed_bf16 or {}).get(
                "entries", {}).get(key)
            derived_err = got_bf16["gather_bf16"]["max_abs_err_vs_f32"]
            if entry_bf16 is None:
                bf16_status = "absent"
            elif entry_bf16 == got_bf16:
                # committed record == the full derived record — the
                # {"gather_bf16": {...}} wrapper commit_goldens_bf16
                # persists.
                bf16_status = "match"
            else:
                bf16_status = "mismatch"
            if derived_err > envelope:
                bf16_status = "mismatch"
            if bf16_status == "mismatch":
                _LOG.warning(
                    f"bf16-tier goldens for {key}: derived "
                    f"{got_bf16['gather_bf16']} vs committed "
                    f"{entry_bf16} at envelope {envelope} — "
                    "environment bf16 numerics drifted; regenerate "
                    "with `python -m mano_hand_tpu.obs.sentinel` if "
                    "intentional")
                if self._tracer is not None:
                    self._tracer.incident("numerics_golden_mismatch",
                                          key=f"{key}:bf16")
            out.update({"golden_bf16_status": bf16_status,
                        "derived_bf16": got_bf16["gather_bf16"],
                        "committed_bf16": entry_bf16,
                        "envelope_m": envelope})
        with self._lock:
            self.golden_status = status
            self.golden_bf16_status = bf16_status
        return out

    def _probe_family(self, exe, want_fn, *args) -> dict:
        served = np.asarray(exe(*args))
        want = np.asarray(want_fn(*args))
        rec = {
            "served_digest": f32_digest(served),
            "want_digest": f32_digest(want),
            "max_abs_err": float(np.abs(
                served.astype(np.float32)
                - want.astype(np.float32)).max()),
        }
        rec["drift"] = rec["served_digest"] != rec["want_digest"]
        return rec

    def probe(self) -> dict:
        """One probe pass NOW over every live family. Returns the
        result dict ({family: {served_digest, want_digest, drift,
        max_abs_err}}, ...); a drift raises the ``numerics_drift``
        incident. The probe's span closes exactly once whatever
        happens (terminal kind probe/drift/error)."""
        tr = self._tracer
        sid = tr.start("sentinel", tier=0, rows=GOLDEN_ROWS) \
            if tr is not None else None
        kind = "error"
        families: Dict[str, dict] = {}
        drifted: list = []
        try:
            t = self._engine.numerics_probe_targets()
            pose, shape = golden_inputs(t["n_joints"], t["n_shape"])
            if t["full"]:
                b = min(t["full"])
                pp, ss = _pad_rows(pose, b), _pad_rows(shape, b)
                families["full"] = dict(
                    bucket=b,
                    **self._probe_family(
                        t["full"][b],
                        lambda p, s: self._ref_full()(
                            t["params_dev"], p, s),
                        pp, ss))
            if t["cpu"]:
                import jax

                b = min(t["cpu"])
                prm_cpu, cpu_dev = self._cpu_inputs(t["params"])
                pp, ss = _pad_rows(pose, b), _pad_rows(shape, b)
                families["cpu"] = dict(
                    bucket=b,
                    **self._probe_family(
                        t["cpu"][b],
                        lambda p, s: self._ref_full()(
                            prm_cpu, jax.device_put(p, cpu_dev),
                            jax.device_put(s, cpu_dev)),
                        pp, ss))
            if t["gather"] and t["table"] is not None:
                b = min(t["gather"])
                idx = np.zeros((b,), np.int32)   # row 0 always baked
                pp = _pad_rows(pose, b)
                families["gather"] = dict(
                    bucket=b, capacity=t["table"].capacity,
                    family=("gather_fused" if t.get("gather_fused")
                            else "gather"),
                    **self._probe_family(
                        t["gather"][b],
                        self._ref_gather(t), t["table"], idx, pp))
            if t.get("gather_bf16") and t["table"] is not None:
                # The bf16 tier (PR 14): judged against the policy's
                # ERROR ENVELOPE relative to the f32 XLA truth — a
                # reduced-precision family can never satisfy f32-digest
                # equality, so the envelope IS its drift criterion
                # (the same-trace bf16 digest rides along as the exact
                # comparator: a chaos/driver corruption flips both).
                b = min(t["gather_bf16"])
                idx = np.zeros((b,), np.int32)
                pp = _pad_rows(pose, b)
                served = np.asarray(t["gather_bf16"][b](
                    t["table"], idx, pp))
                same = np.asarray(self._ref_gather_bf16(t)(
                    t["table"], idx, pp))
                truth = np.asarray(self._ref_gather_truth()(
                    t["table"], idx, pp))
                env = t.get("precision_envelope")
                err = float(np.abs(
                    served.astype(np.float32)
                    - truth.astype(np.float32)).max())
                rec = {
                    "bucket": b, "capacity": t["table"].capacity,
                    "family": ("gather_fused_bf16"
                               if t.get("gather_fused")
                               else "gather_bf16"),
                    "served_digest": f32_digest(served),
                    "want_digest": f32_digest(same),
                    "max_abs_err": err,
                    "envelope": env,
                }
                rec["drift"] = bool(
                    (env is not None and err > env)
                    or rec["served_digest"] != rec["want_digest"])
                families["gather_bf16"] = rec
            drifted = [f for f, rec in families.items()
                       if rec["drift"]]
            kind = "drift" if drifted else "probe"
        except Exception as e:  # noqa: BLE001 — a broken probe must
            # not take down the path it observes; counted + logged.
            with self._lock:
                self.probe_errors += 1
            _LOG.warning(
                f"numerics probe failed: {type(e).__name__}: {e}")
            families["probe_error"] = {"error":
                                       f"{type(e).__name__}: {e}"}
        finally:
            if tr is not None:
                tr.close(sid, kind,
                         families=",".join(sorted(families)))
        result = {
            "families": families,
            "drift": bool(drifted),
            "drifted_families": drifted,
            "t_monotonic": self._clock(),
        }
        with self._lock:
            self.probes += 1
            if drifted:
                self.drifts += 1
            self._last = result
            self._last_t = result["t_monotonic"]
        if drifted and tr is not None:
            # Outside self._lock (the tracer runs incident hooks —
            # the flight recorder — and no lock of ours may wrap a
            # call out).
            tr.incident("numerics_drift",
                        families=",".join(drifted),
                        err=max(families[f]["max_abs_err"]
                                for f in drifted))
        return result

    # --------------------------------------------------- background loop
    def start(self) -> "NumericsSentinel":
        """Arm the background probe: one daemon thread, one probe per
        ``interval_s``, BOUNDED wait (Event.wait — stops promptly,
        never a bare retry loop)."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="mano-numerics-sentinel",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.probe()
            except Exception:  # noqa: BLE001 — probe() already records
                pass

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # Bounded join: a probe wedged in a device RPC is abandoned
            # (daemon), exactly the engine's stop() reasoning — but the
            # handle is cleared ONLY when the thread actually exited:
            # a wedged probe must keep reading armed=True (observable)
            # and a later start() must not spawn a second loop beside
            # it (start()'s is_alive() guard needs the handle).
            t.join(timeout_s)
            if not t.is_alive():
                self._thread = None

    def __enter__(self) -> "NumericsSentinel":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- reporting
    def status(self) -> dict:
        """One lock-held copy of the sentinel's own accounting (the
        torn-telemetry rule)."""
        now = self._clock()
        with self._lock:
            return {
                "probes": self.probes,
                "drifts": self.drifts,
                "probe_errors": self.probe_errors,
                "golden_status": self.golden_status,
                "golden_bf16_status": self.golden_bf16_status,
                "armed": (self._thread is not None
                          and self._thread.is_alive()),
                "last_probe_age_s": (None if self._last_t is None
                                     else max(0.0, now - self._last_t)),
                "last": self._last,
            }

    def samples(self) -> dict:
        """Registry-collector form of ``status()`` (obs/metrics.py)."""
        st = self.status()
        golden_code = {"unchecked": -1, "match": 0, "absent": 1,
                       "mismatch": 2}.get(st["golden_status"], -1)
        out = {
            "sentinel_probes": metric(
                "counter", st["probes"], help="numerics probes run"),
            "sentinel_drifts": metric(
                "counter", st["drifts"],
                help="probes that detected numerics drift"),
            "sentinel_probe_errors": metric(
                "counter", st["probe_errors"],
                help="probes that failed to complete"),
            "sentinel_golden_status": metric(
                "gauge", golden_code,
                help="-1 unchecked, 0 match, 1 absent, 2 mismatch"),
            "sentinel_golden_bf16_status": metric(
                "gauge",
                {"unchecked": -1, "match": 0, "absent": 1,
                 "mismatch": 2}.get(st["golden_bf16_status"], -1),
                help="bf16-tier golden anchor: -1 unchecked, 0 match, "
                     "1 absent, 2 mismatch (envelope-judged)"),
            "sentinel_armed": metric(
                "gauge", 1.0 if st["armed"] else 0.0),
        }
        if st["last_probe_age_s"] is not None:
            out["sentinel_last_probe_age_s"] = metric(
                "gauge", st["last_probe_age_s"],
                help="seconds since the last completed probe")
        return out


def _pad_rows(arr: np.ndarray, rows: int) -> np.ndarray:
    """Golden rows padded (row-0 repeat) or truncated to the probe
    bucket — self-contained so the sentinel never imports the bucket
    policy it is auditing."""
    if arr.shape[0] >= rows:
        return np.ascontiguousarray(arr[:rows])
    pad = np.broadcast_to(arr[:1],
                          (rows - arr.shape[0],) + arr.shape[1:])
    return np.ascontiguousarray(np.concatenate([arr, pad]))


def main(argv=None) -> int:
    """Regenerate the committed goldens for the synthetic asset on the
    host CPU backend: ``python -m mano_hand_tpu.obs.sentinel``. Run it
    after an INTENTIONAL numerics change and justify the diff in the
    PR (the `mano analyze --update-baseline` workflow)."""
    import jax

    # The site-hook rule: only the config API reliably pins cpu.
    jax.config.update("jax_platforms", "cpu")
    from mano_hand_tpu.assets import synthetic_params

    params = synthetic_params()
    data = commit_goldens(params)
    print(f"goldens committed to {default_goldens_path()}: "
          f"{sorted(data['entries'])}")
    data16 = commit_goldens_bf16(params)
    print(f"bf16 goldens committed to {default_bf16_goldens_path()}: "
          f"{sorted(data16['entries'])}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
