"""Incident flight recorder + timeline export (PR 8 tentpole).

The black-box half of the observability story: when something goes
wrong — a deadline kill, a failover, a shed burst, the poison path, a
watchdog fire — the numbers that explain it are the ones from JUST
BEFORE the incident, and by the time a human looks, the ring has moved
on. The ``FlightRecorder`` subscribes to the tracer's incident stream
and captures a bounded, schema-versioned artifact per trigger (recent
spans + runtime events + a counters snapshot); drills attach a trimmed
capture to their bench artifacts so ``scripts/bench_report.py`` can
judge span accounting, and ``write_trace_dir`` exports the full
Chrome-trace timeline for ``scripts/trace_report.py`` to merge with an
XLA ``--profile`` device capture.

Artifact versioning follows the lattice-manifest rule
(io/export_aot.py): ``schema`` bumps on any shape change; consumers
judge only artifacts whose schema they know.

Clock note: captures carry BOTH the monotonic stamp (comparable with
span timestamps) and a wall-clock ISO label (for humans correlating
with external logs) — wall-clock is never used in any arithmetic (the
analysis wallclock-deadline rule).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import List, Optional

FLIGHT_SCHEMA = 1

#: Default bound on in-memory captures a recorder retains (oldest
#: evicted): incidents during a long outage must not grow memory.
DEFAULT_KEEP = 8


def flight_record(tracer, counters=None, *, reason: str = "on_demand",
                  max_spans: int = 16, max_events: int = 64) -> dict:
    """One bounded flight-record artifact: tracer accounting, the most
    recent ``max_spans`` spans and ``max_events`` runtime events, and a
    counters snapshot when given. Small enough to ride inside a bench
    JSON line (the drills attach one each); the full-ring export is
    ``write_trace_dir``'s job.

    The tracer half derives from ONE ``snapshot()`` (a single lock
    hold), so a capture taken mid-incident is internally consistent —
    its accounting, spans, and runtime events all describe the same
    instant (the ServingCounters torn-telemetry rule)."""
    from mano_hand_tpu.obs.trace import ACCOUNTING_KEYS, spans_from_events

    snap = tracer.snapshot()
    spans = spans_from_events(snap["events"], set(snap["open_spans"]))
    runtime = [[ts, name, fields]
               for ts, sid, name, fields in snap["events"] if sid == 0]
    return {
        "schema": FLIGHT_SCHEMA,
        "reason": reason,
        "t_monotonic": time.monotonic(),
        "wall_time_utc": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "accounting": {k: snap[k] for k in ACCOUNTING_KEYS},
        "recent_spans": spans[-max_spans:],
        "recent_runtime_events": runtime[-max_events:],
        "counters": (counters.snapshot()
                     if counters is not None else None),
    }


class FlightRecorder:
    """Auto-capture on tracer incidents; bounded in-memory history,
    optional on-disk dumps.

    >>> tracer = Tracer()
    >>> rec = FlightRecorder(tracer, counters, out_dir="traces/")
    >>> # ... incidents (deadline_kill / failover / shed_burst /
    >>> # watchdog) now each leave a flight_<seq>_<reason>.json ...
    >>> rec.captures[-1]["reason"]
    """

    def __init__(self, tracer, counters=None,
                 out_dir: Optional[str] = None,
                 keep: int = DEFAULT_KEEP):
        self.tracer = tracer
        self.counters = counters
        self.out_dir = Path(out_dir) if out_dir else None
        self.keep = max(1, int(keep))
        self.captures: List[dict] = []
        self._seq = 0
        tracer.on_incident(self._on_incident)

    def _on_incident(self, reason: str, fields: dict) -> None:
        self.capture(reason=reason)

    def capture(self, reason: str = "on_demand") -> dict:
        """One capture now (also the on-demand entry point)."""
        art = flight_record(self.tracer, self.counters, reason=reason)
        self._seq += 1
        art["seq"] = self._seq
        self.captures.append(art)
        del self.captures[:-self.keep]
        if self.out_dir is not None:
            try:
                self.out_dir.mkdir(parents=True, exist_ok=True)
                safe = "".join(c if c.isalnum() or c in "-_" else "_"
                               for c in reason)[:40]
                path = self.out_dir / f"flight_{self._seq:04d}_{safe}.json"
                path.write_text(json.dumps(art))
            except OSError:
                # A full/readonly disk must not take the dispatch path
                # down with it — the in-memory capture stands.
                pass
        return art


def write_trace_dir(tracer, out_dir, counters=None,
                    reason: str = "final") -> dict:
    """Export the full timeline into ``out_dir``: the Chrome-trace
    engine span file (``engine.trace.json`` — the ``*.trace.json``
    suffix is what ``scripts/trace_report.py`` globs) plus a final
    flight record. Returns ``{"engine_trace": path, "flight": path}``.
    """
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    trace_path = out / "engine.trace.json"
    trace_path.write_text(json.dumps(tracer.chrome_trace()))
    flight_path = out / "flight_final.json"
    flight_path.write_text(json.dumps(
        flight_record(tracer, counters, reason=reason)))
    return {"engine_trace": str(trace_path), "flight": str(flight_path)}
