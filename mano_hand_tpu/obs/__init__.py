"""Observability for the serving stack (PR 8): request-lifecycle
tracing, an incident flight recorder, a unified host+device timeline,
and the leveled stderr logger.

Deliberately jax-free at import time: the tracer rides the engine's hot
path and the logger is imported by everything — neither may pull a
backend in.

* ``obs.trace.Tracer`` — bounded lock-light span/event ring; threaded
  through ``ServingEngine(tracer=...)``.
* ``obs.recorder`` — ``FlightRecorder`` (auto-capture on incidents),
  ``flight_record`` (one bounded artifact), ``write_trace_dir``
  (Chrome-trace export for ``scripts/trace_report.py``).
* ``obs.log`` — ``get_logger``: info/debug to leveled stderr,
  warning through the ``warnings`` machinery, stdout never.
"""

from mano_hand_tpu.obs.log import Logger, get_logger
from mano_hand_tpu.obs.recorder import (
    FlightRecorder,
    flight_record,
    write_trace_dir,
)
from mano_hand_tpu.obs.trace import TERMINAL_KINDS, Tracer

__all__ = [
    "FlightRecorder",
    "Logger",
    "TERMINAL_KINDS",
    "Tracer",
    "flight_record",
    "get_logger",
    "write_trace_dir",
]
