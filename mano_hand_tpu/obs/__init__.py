"""Observability for the serving stack (PR 8 + PR 9): request-lifecycle
tracing, an incident flight recorder, a unified host+device timeline,
the leveled stderr logger, an exportable metrics registry with SLO
accounting, and the numerics sentinel.

Deliberately jax-free at import time: the tracer rides the engine's hot
path and the logger is imported by everything — neither may pull a
backend in (the sentinel imports jax lazily, at probe time only).

* ``obs.trace.Tracer`` — bounded lock-light span/event ring; threaded
  through ``ServingEngine(tracer=...)``.
* ``obs.recorder`` — ``FlightRecorder`` (auto-capture on incidents),
  ``flight_record`` (one bounded artifact), ``write_trace_dir``
  (Chrome-trace export for ``scripts/trace_report.py``).
* ``obs.log`` — ``get_logger``: info/debug to leveled stderr,
  warning through the ``warnings`` machinery, stdout never.
* ``obs.metrics`` — ``MetricsRegistry`` (counter/gauge/quantile
  instruments, one-lock-hold snapshots), ``engine_registry`` (absorbs
  ``ServingCounters``/``load()``/tracer/SLO as collectors),
  ``prometheus_text`` + JSON export, ``slo_report`` burn rates.
* ``obs.sentinel`` — ``NumericsSentinel``: low-rate golden-input
  probe of every live program family in the serving compilation
  context, f32-digest drift detection onto the incident timeline.
"""

from mano_hand_tpu.obs.log import Logger, get_logger
from mano_hand_tpu.obs.metrics import (
    MetricsRegistry,
    engine_registry,
    prometheus_text,
    slo_report,
)
from mano_hand_tpu.obs.recorder import (
    FlightRecorder,
    flight_record,
    write_trace_dir,
)
from mano_hand_tpu.obs.sentinel import NumericsSentinel
from mano_hand_tpu.obs.trace import TERMINAL_KINDS, Tracer

__all__ = [
    "FlightRecorder",
    "Logger",
    "MetricsRegistry",
    "NumericsSentinel",
    "TERMINAL_KINDS",
    "Tracer",
    "engine_registry",
    "flight_record",
    "get_logger",
    "prometheus_text",
    "slo_report",
    "write_trace_dir",
]
