"""Request-lifecycle tracing for the serving stack (PR 8 tentpole).

Seven PRs of serving machinery (batching, coalescing, failover,
overload, cold start) report only aggregate ``ServingCounters`` — when
a drill misses a criterion or the roofline gap needs attacking there is
no way to see WHERE one request's time went or what the engine was
doing at the moment of an incident. The ``Tracer`` answers both with
one bounded structure:

* **Per-request spans.** ``ServingEngine.submit`` opens a span; the
  engine stamps an event at every boundary it already sweeps deadlines
  at — submit -> coalesce/park -> launch -> dispatched -> readback ->
  resolve(kind) — and closes the span exactly once at the future's
  terminal resolution (ok / shed / expired / error / shutdown, the
  ``ServingError.kind`` vocabulary). The accounting
  (``spans_started`` / ``spans_closed`` / ``spans_open``) turns "every
  future resolves" into "every span closes", a number bench criteria
  judge (scripts/bench_report.py, config12).
* **Runtime events on the same timeline.** Chaos injections, breaker
  transitions, deadline kills, failovers, evictions, lattice loads,
  compiles, watchdog fires — span-less events interleaved with the
  request timeline, so an incident reads in context.
* **A bounded, lock-light ring.** Events are small tuples appended to a
  ``deque(maxlen=capacity)`` under one private lock that is never held
  across device work and never nested inside engine locks (the tracer
  calls nothing back). A long-lived server cannot grow memory with
  traffic; overwritten history is counted (``events_dropped``), never
  silently absent. The disabled path is ``tracer is None`` in the
  engine — zero calls, zero cost; the enabled path is measured at
  <= 3% end-to-end (bench config12's paired interleaved criterion).

Clock discipline (the analysis wallclock-deadline rule): every stamp is
``time.monotonic()`` — the same domain as the engine's deadlines, so
span timings and deadline sweeps compare directly and an NTP step
cannot tear a timeline. Wall-clock appears only in flight-recorder
artifacts as a human-readable label (obs/recorder.py).

Export: ``chrome_trace()`` renders spans as Chrome-trace complete
events (one slice per request plus per-stage sub-slices, one thread
per priority tier) so ``scripts/trace_report.py`` can merge the engine
host timeline with an XLA ``--profile`` device capture into one
report; ``stage_breakdown()`` answers "queue wait vs device vs
readback" per (bucket, tier) — the roofline work's first question.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

#: Terminal span kinds — the engine's future-resolution vocabulary
#: (serving/engine.py:ServingError.kind plus "ok"), extended by the
#: stream-session lifecycle (PR 12): "closed" is a client-initiated
#: clean close of a serving/streams.py session span; stream expiry /
#: shed / shutdown reuse the request kinds with the same meaning.
#: "cancelled" (PR 13) is the caller withdrawing a request via
#: ``future.cancel()`` — the admission slot frees and the span closes
#: before the deadline sweep would have fired.
TERMINAL_KINDS = ("ok", "shed", "expired", "error", "shutdown",
                  "closed", "cancelled")

#: Default ring capacity: ~6 events/request keeps the last ~1300
#: requests of history — plenty for an incident dump, bounded forever.
DEFAULT_CAPACITY = 8192

#: Per-tier latency reservoir bound (the ServingCounters
#: _LATENCY_RESERVOIR reasoning at backpressure-snapshot scale).
_TIER_RESERVOIR = 2048

#: Accounting keys inside a ``Tracer.snapshot()`` (everything but the
#: raw ``events``/``open_spans`` payloads).
ACCOUNTING_KEYS = (
    "spans_started", "spans_closed", "spans_open", "spans_double_closed",
    "closed_by_kind", "events_total", "events_dropped", "ring_len",
    "ring_capacity", "incidents")


def spans_from_events(events, open_ids) -> List[dict]:
    """Group one consistent ``snapshot()["events"]`` copy per span —
    the shared derivation for ``Tracer.spans``, the chrome export, and
    the flight recorder, so every view of one capture describes the
    SAME instant instead of re-reading the live ring."""
    grouped: Dict[int, dict] = {}
    for ts, sid, name, fields in events:
        if sid == 0:
            continue
        g = grouped.setdefault(
            sid, {"id": sid, "events": [], "closed_kind": None})
        g["events"].append([ts, name, fields])
        if name == "resolve" and fields:
            g["closed_kind"] = fields.get("kind")
    for g in grouped.values():
        g["open"] = g["id"] in open_ids
    return [grouped[k] for k in sorted(grouped)]


class Tracer:
    """Bounded request-span + runtime-event recorder (module docstring).

    Thread-safe: submitters, the dispatcher, supervision worker
    threads, and watchdogs all write here. One private lock guards the
    span table and counters; it is never held while calling out
    (incident hooks run OUTSIDE the lock so a hook may snapshot the
    tracer) and the engine never calls tracer methods while holding a
    lock the tracer could want — the tracer wants none of the
    engine's.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 clock: Callable[[], float] = time.monotonic,
                 shed_burst_threshold: int = 32):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = threading.Lock()
        # Ring entries: (ts, span_id, name, fields|None); span_id 0 =
        # runtime (span-less) event.
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity)
        self._next_id = 1
        self._open: Dict[int, dict] = {}   # span_id -> start record
        self.spans_started = 0
        self.spans_closed = 0
        self.spans_double_closed = 0       # close() on an already-closed
        #   span: forensics for the documented resolve-vs-sweep race
        #   window, NOT part of the closed-exactly-once criterion (the
        #   pop guard means spans_closed never double-counts).
        self.closed_by_kind: Dict[str, int] = {}
        self.events_total = 0
        self.incidents = 0
        self.shed_burst_threshold = int(shed_burst_threshold)
        self._shed_streak = 0
        self._incident_hooks: List[Callable[[str, dict], None]] = []
        # Per-tier closed-span latency reservoirs for the backpressure
        # snapshot (ServingEngine.load()).
        self._tier_lat: Dict[int, list] = {}
        self._tier_writes: Dict[int, int] = {}

    # ------------------------------------------------------------- writers
    def start(self, kind: str, tier: int = 0, rows: int = 1) -> int:
        """Open one request span; returns its id. ``kind`` is the
        request path ("full" / "posed"), not the terminal kind."""
        ts = self._clock()
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            self._open[sid] = {"t0": ts, "kind": kind, "tier": int(tier),
                               "rows": int(rows)}
            self.spans_started += 1
            self._append(ts, sid, "submit",
                         {"kind": kind, "tier": int(tier),
                          "rows": int(rows)})
            return sid

    def event(self, span_id: Optional[int], name: str, **fields) -> None:
        """Stamp one boundary event onto a span (or the runtime
        timeline when ``span_id`` is None/0)."""
        ts = self._clock()
        with self._lock:
            self._append(ts, span_id or 0, name, fields or None)

    def close(self, span_id: Optional[int], kind: str, **fields) -> bool:
        """Terminal resolution of one span — exactly once: the first
        close wins (pops the open record, counts ``spans_closed``);
        a repeat only bumps ``spans_double_closed``."""
        if span_id is None:
            return False
        ts = self._clock()
        with self._lock:
            rec = self._open.pop(span_id, None)
            if rec is None:
                self.spans_double_closed += 1
                return False
            self.spans_closed += 1
            self.closed_by_kind[kind] = self.closed_by_kind.get(kind, 0) + 1
            f = {"kind": kind, **fields} if fields else {"kind": kind}
            self._append(ts, span_id, "resolve", f)
            if kind == "ok":
                # Only SERVED requests feed the backpressure quantiles:
                # a shed resolves in O(µs), so counting it would make
                # load()'s latency signal read FASTER exactly when the
                # engine is drowning — the inverse of backpressure.
                tier = rec["tier"]
                lat = ts - rec["t0"]
                samples = self._tier_lat.setdefault(tier, [])
                if len(samples) >= _TIER_RESERVOIR:
                    cursor = self._tier_writes.get(tier, 0)
                    samples[cursor % _TIER_RESERVOIR] = lat
                else:
                    samples.append(lat)
                self._tier_writes[tier] = \
                    self._tier_writes.get(tier, 0) + 1
            return True

    def runtime_event(self, name: str, **fields) -> None:
        """A span-less engine/runtime event on the shared timeline."""
        ts = self._clock()
        with self._lock:
            self._append(ts, 0, name, fields or None)

    def incident(self, reason: str, **fields) -> None:
        """A runtime event that ALSO notifies incident hooks (the
        flight recorder's trigger). Hooks run outside the lock so they
        may snapshot this tracer."""
        self.runtime_event(f"incident:{reason}", **fields)
        with self._lock:
            self.incidents += 1
            hooks = list(self._incident_hooks)
        for h in hooks:
            try:
                h(reason, fields)
            except Exception:  # noqa: BLE001 — a broken hook must not
                pass           # poison the dispatch path it rides on

    def on_incident(self, hook: Callable[[str, dict], None]) -> None:
        with self._lock:
            self._incident_hooks.append(hook)

    def note_shed(self) -> None:
        """One admission shed. Cheap streak bookkeeping; crossing
        ``shed_burst_threshold`` consecutive sheds fires ONE
        ``shed_burst`` incident per crossing (reset by any admit) —
        overload becomes a flight-recorder trigger without paying an
        incident per shed on the O(µs) admission path."""
        with self._lock:
            self._shed_streak += 1
            fire = self._shed_streak == self.shed_burst_threshold
        if fire:
            self.incident("shed_burst", streak=self.shed_burst_threshold)

    def note_admit(self) -> None:
        with self._lock:
            self._shed_streak = 0

    def _append(self, ts, span_id, name, fields) -> None:
        # Callers hold self._lock.
        self._ring.append((ts, span_id, name, fields))
        self.events_total += 1

    # ------------------------------------------------------------- readers
    def _accounting_locked(self) -> dict:
        # Caller holds self._lock.
        return {
            "spans_started": self.spans_started,
            "spans_closed": self.spans_closed,
            "spans_open": len(self._open),
            "spans_double_closed": self.spans_double_closed,
            "closed_by_kind": dict(self.closed_by_kind),
            "events_total": self.events_total,
            "events_dropped": max(
                0, self.events_total - len(self._ring)),
            "ring_len": len(self._ring),
            "ring_capacity": self.capacity,
            "incidents": self.incidents,
        }

    def accounting(self) -> dict:
        """The closed-exactly-once criterion's numbers, one lock hold."""
        with self._lock:
            return self._accounting_locked()

    def load_snapshot(self) -> dict:
        """The backpressure-signal extension (``ServingEngine.load``):
        per-tier SERVED-request latency quantiles (kind="ok" closes
        only — shed/expired resolutions are O(µs) bookkeeping and
        would read as the tier speeding up mid-overload) and the
        backlog age (oldest still-open span). Samples and open-span
        starts are copied in ONE lock hold — the same torn-telemetry
        rule as ``ServingCounters.snapshot`` — and the percentile math
        runs on the copies outside the lock."""
        now = self._clock()
        with self._lock:
            items = {t: list(s) for t, s in self._tier_lat.items()}
            # REQUEST spans only: a stream-session span (PR 12) stays
            # open for the session's whole lifetime by design, and
            # counting it would pin backlog_age_s to "oldest open
            # stream" — a healthy engine reading as permanently wedged.
            # The per-frame backlog signal lives in load()["streams"].
            oldest = min((r["t0"] for r in self._open.values()
                          if r.get("kind") != "stream"),
                         default=None)
        out = {}
        for t, s in sorted(items.items()):
            if not s:
                continue
            arr = np.asarray(s)
            out[str(t)] = {
                "p50_ms": float(np.percentile(arr, 50) * 1e3),
                "p99_ms": float(np.percentile(arr, 99) * 1e3),
                "n": int(arr.size),
            }
        return {
            "latency_by_tier": out,
            "backlog_age_s": (0.0 if oldest is None
                              else max(0.0, now - oldest)),
        }

    def snapshot(self) -> dict:
        """Accounting + the full event ring + the open-span table, ALL
        copied in ONE lock hold (the flight recorder's raw material —
        a capture taken mid-incident must be internally consistent,
        never accounting from one instant beside events from another).
        Events serialize as ``[ts, span_id, name, fields]``."""
        with self._lock:
            snap = self._accounting_locked()
            snap["events"] = [[ts, sid, name, fields]
                              for ts, sid, name, fields in self._ring]
            snap["open_spans"] = {sid: dict(rec)
                                  for sid, rec in self._open.items()}
        return snap

    def spans(self) -> List[dict]:
        """Events grouped per span (ring-bounded history): a list of
        ``{"id", "events": [[ts, name, fields], ...], "closed_kind"}``.
        Spans whose early events were overwritten by the ring are
        returned with what remains — partial history beats none."""
        snap = self.snapshot()
        return spans_from_events(snap["events"], set(snap["open_spans"]))

    # ----------------------------------------------------------- analysis
    @staticmethod
    def _span_stages(span: dict) -> Optional[dict]:
        """(bucket, tier, kind, queue_s, device_s, readback_s, total_s)
        for one complete span, or None when the ring lost a boundary.

        Stage semantics (honest about what the engine can see):
        ``queue`` = submit -> launch (admission + queue + coalesce
        wait); ``dispatch`` = launch -> dispatched (batch assembly,
        executable fetch — a cold compile lands HERE, which is how a
        recompile shows up on the timeline — and the dispatch call;
        on the supervised path the device round-trip too); ``device``
        = dispatched -> readback (device execution + transfer — on
        the unsupervised double-buffered path this includes pipeline
        wait); ``readback`` = readback -> resolve (host-side slice +
        future delivery). The four stages partition submit->resolve
        exactly.

        Pipelined spans (PR 17, ``inflight_depth > 1``) carry one
        extra OPTIONAL event, ``staged`` — the moment the dispatcher
        handed the assembled batch to the completion stage. When
        present, ``dispatch`` narrows to launch -> staged (assembly +
        executable fetch only) and a fifth stage ``pipeline`` =
        staged -> dispatched (completion-stage queue wait: how long
        the batch sat behind earlier in-flight batches) joins the
        partition. Serial spans (depth 1) never emit ``staged``, so
        their rows — and the whole report — are byte-identical to the
        pre-pipeline engine's.
        """
        at = {}
        meta = {}
        for ts, name, fields in span["events"]:
            at.setdefault(name, ts)
            if fields:
                for k, v in fields.items():
                    # First write wins: "kind" must stay the submit
                    # event's path kind (full/posed), not the resolve
                    # event's terminal kind (that one is
                    # span["closed_kind"]).
                    meta.setdefault(k, v)
        needed = ("submit", "launch", "dispatched", "readback", "resolve")
        if any(k not in at for k in needed):
            return None
        st = {
            "bucket": meta.get("bucket"),
            "tier": meta.get("tier", 0),
            "kind": meta.get("kind"),
            "queue_s": at["launch"] - at["submit"],
            "dispatch_s": at["dispatched"] - at["launch"],
            "device_s": at["readback"] - at["dispatched"],
            "readback_s": at["resolve"] - at["readback"],
            "total_s": at["resolve"] - at["submit"],
        }
        if "staged" in at:
            st["dispatch_s"] = at["staged"] - at["launch"]
            st["pipeline_s"] = at["dispatched"] - at["staged"]
            st["_staged_at"] = at["staged"]
        return st

    def stage_breakdown(self, spans: Optional[List[dict]] = None) -> dict:
        """Queue-wait vs device vs readback per (bucket, tier) over the
        ring's complete spans — the unified-timeline report's host-side
        half (scripts/trace_report.py prints it next to the XLA device
        tracks). ``spans`` lets a caller holding one consistent
        snapshot derive the table from it (chrome_trace does)."""
        rows: Dict[str, Dict[str, list]] = {}
        complete = 0
        for span in (self.spans() if spans is None else spans):
            st = self._span_stages(span)
            if st is None:
                continue
            complete += 1
            key = f"b{st['bucket']}/tier{st['tier']}"
            cell = rows.setdefault(
                key, {"queue_s": [], "dispatch_s": [], "device_s": [],
                      "readback_s": [], "total_s": []})
            for k, v in st.items():
                # "pipeline_s" rides only on pipelined spans (PR 17):
                # rows that never saw one keep the four-stage shape.
                if k.endswith("_s"):
                    cell.setdefault(k, []).append(v)
        out = {}
        for key, cell in sorted(rows.items()):
            out[key] = {"n": len(cell["total_s"])}
            for k, samples in cell.items():
                arr = np.asarray(samples)
                stage = k[:-2]  # strip _s
                out[key][f"{stage}_p50_ms"] = float(
                    np.percentile(arr, 50) * 1e3)
                out[key][f"{stage}_p99_ms"] = float(
                    np.percentile(arr, 99) * 1e3)
                out[key][f"{stage}_mean_ms"] = float(arr.mean() * 1e3)
        return {"complete_spans": complete, "by_bucket_tier": out}

    # ------------------------------------------------------------- export
    #: Chrome-trace pid for the engine host timeline. Deliberately NOT
    #: the XLA captures' pid space — trace_report summarizes per
    #: capture file, and the metadata names the track.
    CHROME_PID = 9001

    def chrome_trace(self) -> dict:
        """The span ring as Chrome-trace JSON (``traceEvents`` with
        ``ph: X`` complete events, µs timestamps): one ``request/...``
        slice per complete span plus per-stage sub-slices, one thread
        per priority tier, runtime events as instants. Alongside rides
        ``manoEngineTrace`` — schema-versioned accounting + stage
        breakdown — which is what marks the file as an engine span
        export to ``scripts/trace_report.py``. The whole export
        derives from ONE snapshot, so its traceEvents, accounting, and
        stage table all describe the same instant."""
        snap = self.snapshot()
        spans = spans_from_events(snap["events"], set(snap["open_spans"]))
        pid = self.CHROME_PID
        ev: List[dict] = [{
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": "mano-serving-engine"},
        }]
        tiers_seen = set()

        def tid_for(tier: int) -> int:
            if tier not in tiers_seen:
                tiers_seen.add(tier)
                ev.append({"ph": "M", "pid": pid, "tid": tier,
                           "name": "thread_name",
                           "args": {"name": f"tier {tier}"}})
            return tier

        for span in spans:
            st = self._span_stages(span)
            at = {name: ts for ts, name, _ in reversed(span["events"])}
            if st is None:
                continue
            tid = tid_for(st["tier"])
            t0 = at["submit"]
            label = (f"request/{st['kind'] or '?'}"
                     f"/b{st['bucket']}")
            ev.append({"ph": "X", "pid": pid, "tid": tid, "name": label,
                       "ts": t0 * 1e6, "dur": st["total_s"] * 1e6,
                       "args": {"terminal": span["closed_kind"]}})
            slices = [
                ("queue", at["submit"], st["queue_s"]),
                ("dispatch", at["launch"], st["dispatch_s"]),
                ("device", at["dispatched"], st["device_s"]),
                ("readback", at["readback"], st["readback_s"])]
            if "pipeline_s" in st:
                slices.insert(
                    2, ("pipeline", st["_staged_at"], st["pipeline_s"]))
            for stage, start, dur in slices:
                ev.append({"ph": "X", "pid": pid, "tid": tid,
                           "name": f"stage/{stage}",
                           "ts": start * 1e6, "dur": dur * 1e6})
        for ts, sid, name, fields in snap["events"]:
            if sid != 0:
                continue
            ev.append({"ph": "i", "pid": pid, "tid": tid_for(-1),
                       "name": name, "ts": ts * 1e6, "s": "p",
                       **({"args": fields} if fields else {})})
        return {
            "displayTimeUnit": "ms",
            "traceEvents": ev,
            "manoEngineTrace": {
                "schema": 1,
                "accounting": {k: snap[k] for k in ACCOUNTING_KEYS},
                "stages": self.stage_breakdown(spans),
            },
        }
