"""Gradient-based pose/shape recovery (inverse MANO).

TPU-first structure: one jitted ``lax.scan`` over optimizer steps, ``vmap``
over a batch of independent fitting problems — B x n_steps forward+backward
passes compile to a single XLA program with zero host round-trips. The
optimizer is any optax GradientTransformation (Adam by default).

Pose can be parameterized as full axis-angle ([16, 3], well-suited to
tracking), PCA coefficients + global rotation (the reference's native
parameterization, better conditioned for sparse data), or the 6D
continuous rotation representation (Zhou et al. — no 2*pi wrap in the
landscape; results decode back to axis-angle via the SO(3) log map).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from mano_hand_tpu import ops
from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.fitting import objectives
from mano_hand_tpu.models import core

# Identity rotation in the 6D representation (first two columns of I).
# Plain tuple: materializing a device array at import time would initialize
# the backend before the caller can pick a platform.
_ID6D = (1.0, 0.0, 0.0, 0.0, 1.0, 0.0)


# --- pose-space machinery shared by _fit_single and fit_sequence ---------
# One definition each of: parameter init, decode-to-rotation input, prior
# deviation, and final decode-to-axis-angle, keyed on pose_space. `prefix`
# prepends leading dims (() for one problem, (T,) for a clip).

def _pose_shapes(pose_space, n_joints, n_pca, allowed):
    """Per-problem pose-parameter shapes — THE shape source of truth that
    both ``_pose_init`` (array construction) and the batched warm-start
    validation consume, so the two can't drift."""
    if pose_space not in allowed:
        raise ValueError(
            f"pose_space must be one of {sorted(allowed)}, "
            f"got {pose_space!r}"
        )
    if pose_space == "aa":
        return {"pose": (n_joints, 3)}
    if pose_space == "pca":
        return {"pca": (n_pca,), "global_rot": (3,)}
    # "6d": the continuous rotation representation (ops.matrix_from_6d) —
    # no 2*pi wrap in the optimization landscape.
    return {"rot6d": (n_joints, 6)}


def _pose_init(pose_space, prefix, n_joints, n_pca, dtype, allowed):
    shapes = _pose_shapes(pose_space, n_joints, n_pca, allowed)
    if pose_space == "6d":
        # Init = identity rotation, not zeros (a zero 6D vector is
        # degenerate under Gram-Schmidt).
        return {
            "rot6d": jnp.broadcast_to(
                jnp.asarray(_ID6D, dtype), (*prefix, *shapes["rot6d"])
            )
        }
    return {k: jnp.zeros((*prefix, *s), dtype) for k, s in shapes.items()}


def _batched_init_shapes(pose_space, n_joints, n_pca, n_shape, fit_trans,
                         allowed=frozenset({"aa", "pca", "6d"}),
                         freeze_shape=False):
    """Full per-problem parameter shapes for the active parameterization —
    plain tuples (no array materialization; this runs on every batched
    warm-started call). Pose shapes come from ``_pose_shapes``, the same
    source ``_pose_init`` builds from. ``freeze_shape`` drops the beta
    entry (frozen-betas mode: beta is a constant, not a parameter, so a
    seeded ``init["shape"]`` must fail the key check by name)."""
    shapes = dict(_pose_shapes(pose_space, n_joints, n_pca, allowed))
    if not freeze_shape:
        shapes["shape"] = (n_shape,)
    if fit_trans:
        shapes["trans"] = (3,)
    return shapes


def validate_batched_init(init, b, expected, target_shape, fn_name):
    """One up-front check for every batched warm-start path (Adam and LM).

    Full-shape validation: a single-problem seed — even one whose own
    leading dim coincidentally equals B — or a typo'd key must fail here
    with a descriptive message, not as a raw vmap axis-size error deep in
    the trace. ``expected`` maps key -> per-problem shape tuple.
    """
    unknown = set(init) - set(expected)
    if unknown:
        raise ValueError(
            f"init keys {sorted(unknown)} not in this parameterization "
            f"{sorted(expected)}"
        )
    for k, v in init.items():
        v = jnp.asarray(v)
        want = (b, *expected[k])
        if v.shape != want:
            raise ValueError(
                f"batched {fn_name} needs one seed per problem: "
                f"init[{k!r}] has shape {v.shape}, expected {want} for "
                f"target batch {target_shape}"
            )


def _pose_deviation(pose_space, p, dtype):
    """What the pose prior penalizes: distance from the rest pose in the
    active parameterization (identity representation for 6d)."""
    if pose_space == "pca":
        return p["pca"]
    if pose_space == "6d":
        return p["rot6d"] - jnp.asarray(_ID6D, dtype)
    return p["pose"]


def _check_pose_prior(pose_prior: str, pose_space: str,
                      joint_limits=None) -> None:
    if pose_prior not in ("l2", "mahalanobis"):
        raise ValueError(
            f"pose_prior must be 'l2' or 'mahalanobis', got {pose_prior!r}"
        )
    if pose_prior == "mahalanobis" and pose_space not in ("aa", "pca"):
        # 6d would need the SO(3) log map inside the loss (the exact thing
        # the 6d path exists to avoid); refuse rather than degrade.
        raise ValueError(
            "pose_prior='mahalanobis' needs the axis-angle statistics, so "
            f"pose_space must be 'aa' or 'pca'; got {pose_space!r}"
        )
    if joint_limits is not None:
        if pose_space not in ("aa", "pca"):
            # Same constraint as the Mahalanobis prior: the bounds live in
            # axis-angle coordinates.
            raise ValueError(
                "joint_limits are per-axis-angle-DOF bounds, so pose_space "
                f"must be 'aa' or 'pca'; got {pose_space!r}"
            )
        if len(joint_limits) != 2:
            raise ValueError(
                "joint_limits must be a (lo, hi) pair (e.g. from "
                "objectives.pose_limits_from_corpus); got "
                f"{len(joint_limits)} elements"
            )


def _fingers_flat(pose_space, params, p, precision=None):
    """The articulated (non-root) pose as flat axis-angle [..., 3*(J-1)] —
    the coordinates the Mahalanobis prior's statistics live in."""
    if pose_space == "aa":
        pose = p["pose"]
        return pose[..., 1:, :].reshape(*pose.shape[:-2], -1)
    # "pca": decode to the flat finger pose (decode_pca minus the root row).
    pca = p["pca"]
    n = pca.shape[-1]
    return (
        jnp.einsum("...n,nf->...f", pca, params.pca_basis[:n])
        + params.pca_mean
    )


def _pose_reg(pose_space, pose_prior, pose_prior_vars, params, p, dtype,
              pose_prior_weight, joint_limits=None,
              joint_limit_weight=0.0):
    """The pose prior term — THE one dispatch every solver loss uses.

    ``joint_limits`` ((lo, hi) per flat articulated DOF, e.g. from
    ``objectives.pose_limits_from_corpus``) COMPOSES with either prior:
    the l2/Mahalanobis term shapes the interior of the feasible set, the
    hinge walls off its boundary (hyperextension reads 2D keypoints as
    well as the true pose; only a boundary term rules it out). Needs the
    axis-angle coordinates, so it applies under pose_space 'aa'/'pca' —
    _check_pose_prior refuses '6d' + limits.
    """
    ff = (_fingers_flat(pose_space, params, p)
          if pose_prior == "mahalanobis" or joint_limits is not None
          else None)
    if pose_prior == "mahalanobis":
        reg = pose_prior_weight * objectives.mahalanobis_pose_prior(
            params, ff, pose_prior_vars
        )
    else:
        reg = pose_prior_weight * objectives.l2_prior(
            _pose_deviation(pose_space, p, dtype)
        )
    if joint_limits is not None:
        lo, hi = joint_limits
        reg = reg + joint_limit_weight * objectives.pose_limit_prior(
            ff, lo, hi
        )
    return reg


def _pose_to_aa(pose_space, params, p):
    """Final parameters -> the reference's axis-angle convention. The 6d
    log map is only evaluated on results, never inside the loss."""
    if pose_space == "aa":
        return p["pose"]
    if pose_space == "6d":
        return ops.axis_angle_from_matrix(ops.matrix_from_6d(p["rot6d"]))
    return core.decode_pca(params, p["pca"], p["global_rot"])


class FitResult(NamedTuple):
    pose: jnp.ndarray          # [..., 16, 3] recovered axis-angle pose
    shape: jnp.ndarray         # [..., S] recovered shape coefficients
    final_loss: jnp.ndarray    # [...] last-step data loss
    loss_history: jnp.ndarray  # [..., n_steps] data-loss curve
    pca: Optional[jnp.ndarray] = None  # [..., n_pca] when pose_space="pca"
    trans: Optional[jnp.ndarray] = None  # [..., 3] when fit_trans=True


def _check_data_term(data_term: str, camera, conf) -> None:
    """One validation policy for every solver entry point."""
    if data_term not in ("verts", "joints", "keypoints2d", "points",
                         "silhouette", "depth"):
        raise ValueError(
            "data_term must be 'verts', 'joints', 'keypoints2d', "
            f"'points', 'silhouette' or 'depth', got {data_term!r}"
        )
    if data_term in ("keypoints2d", "silhouette", "depth"):
        if camera is None:
            raise ValueError(
                f"data_term={data_term!r} needs a viz.camera.Camera (or "
                "WeakPerspectiveCamera)"
            )
        if data_term == "depth" and hasattr(camera, "scale"):
            # Weak perspective's z column is rotation-only (roughly 0
            # for an origin-centered hand) — a meters-scale depth target
            # against it is a meaningless residual, silently.
            raise ValueError(
                "data_term='depth' needs a real projection (Camera or "
                "IntrinsicsCamera); weak perspective has no depth axis"
            )
        if is_multiview(camera):
            if data_term != "silhouette":
                raise ValueError(
                    "a camera list (multi-view) is only supported for "
                    f"data_term='silhouette'; {data_term} takes one camera"
                )
            if len(camera) == 0:
                raise ValueError("camera list is empty")
        if conf is not None and data_term != "keypoints2d":
            raise ValueError(
                "target_conf only applies to data_term='keypoints2d'"
            )
    elif camera is not None or conf is not None:
        # Accepting these would silently fit unweighted/unprojected data.
        raise ValueError(
            "camera/target_conf only apply to the image-space data terms "
            "('keypoints2d', 'silhouette', 'depth'), got "
            f"data_term={data_term!r}"
        )


# Data terms whose rows are skeleton keypoints (the terms the fingertip
# extension applies to — 'verts'/'points' address mesh vertices directly).
KEYPOINT_TERMS = ("joints", "keypoints2d")


def normalize_tips_kwarg(fn):
    """Resolve ``tip_vertex_ids`` to a hashable tuple BEFORE the jit boundary.

    The jitted solvers declare ``tip_vertex_ids`` static; without this a
    documented-as-valid list/array spec would die at the jit boundary as
    'unhashable type' instead of reaching ``resolve_tip_ids``'s
    normalization and named errors. Applies only to keyword passing —
    which is how every internal call site and example passes it.
    """
    @functools.wraps(fn)
    def wrapper(params, *args, tip_vertex_ids=None, **kw):
        # shape[-2] is the vertex axis for single ([V, 3]) AND stacked
        # two-hand ([2, V, 3]) parameter trees.
        tip_vertex_ids = core.resolve_tip_ids(
            tip_vertex_ids, params.v_template.shape[-2]
        )
        return fn(params, *args, tip_vertex_ids=tip_vertex_ids, **kw)

    return wrapper


def validate_mask_target(fn):
    """Reject out-of-range silhouette targets BEFORE the jit boundary.

    Segmentation masks routinely arrive as uint8 0/255; the soft-IoU
    loss's [0, 1] precondition would otherwise fail SILENTLY — with p in
    [0, 1] and t up to 255 the "intersection" exceeds the "union", the
    loss goes negative at ~255x the documented scale, and the data
    gradient swamps the priors this ill-posed term depends on. Value
    checks are impossible inside jit (tracers carry no values), so this
    runs on the concrete target at the outermost wrapper; traced targets
    (an already-jitted caller) pass through unchecked.

    The target and ``data_term`` are located by BINDING the call to the
    wrapped function's signature (``functools.wraps`` chains through
    jit's ``__wrapped__``), so keyword targets (``targets=frames``) and
    positional ``data_term`` both resolve — a (params, target, *args)
    wrapper shape would break the former and silently skip the latter.
    """
    import inspect

    sig = inspect.signature(fn)
    target_name = list(sig.parameters)[1]   # fit: target_verts; seq: targets

    @functools.wraps(fn)
    def wrapper(*args, **kw):
        try:
            bound = sig.bind(*args, **kw)
        except TypeError:
            # Malformed call: let the real function raise its own error.
            return fn(*args, **kw)
        data_term = bound.arguments.get("data_term")
        is_sil = data_term == "silhouette"
        is_depth = data_term == "depth"
        masks = []
        if is_sil:
            masks.append(bound.arguments.get(target_name))
        masks.append(bound.arguments.get("target_mask"))  # aux (kp2d+mask)
        import numpy as np

        for m in masks:
            if m is None or isinstance(m, jax.core.Tracer):
                continue
            t = np.asarray(m)
            if t.size and (float(t.min()) < 0.0 or float(t.max()) > 1.0):
                raise ValueError(
                    "silhouette target mask must be in [0, 1], got "
                    f"range [{float(t.min()):g}, {float(t.max()):g}] "
                    "— divide a 0/255 uint8 mask by 255"
                )
        if is_sil or is_depth:
            # Image targets need at least [H, W]: name the shape error
            # here, before an axis=(-2,-1) reduction or a shape[-2]
            # lookup can raise a bare AxisError/IndexError downstream.
            d = bound.arguments.get(target_name)
            if d is not None and not isinstance(d, jax.core.Tracer):
                if np.asarray(d).ndim < 2:
                    raise ValueError(
                        f"data_term='{data_term}' targets must be image-"
                        f"shaped [..., H, W]; got shape "
                        f"{np.asarray(d).shape}"
                    )
        if is_depth:
            d = bound.arguments.get(target_name)
            if d is not None and not isinstance(d, jax.core.Tracer):
                t = np.asarray(d)
                # PER IMAGE, not whole-array: one all-invalid frame in a
                # batch/clip (sensor dropout) would contribute zero
                # gradients and report its untouched init as a converged
                # fit.
                # (t.ndim >= 2 is guaranteed: the image-shape gate above
                # raised the named error for anything lower.)
                if t.size and not (t > 0).any(axis=(-2, -1)).all():
                    raise ValueError(
                        "depth target has image(s) with no valid "
                        "(positive) pixels — drop dropped-out frames "
                        "before fitting"
                    )
                # Joins the camera-resolution check below (the [0, 1]
                # range check does NOT apply — depth is in meters).
                masks.append(d)
        if (is_sil or is_depth
                or bound.arguments.get("target_mask") is not None):
            # Degenerate render parameters give a constant/NaN image and
            # a zero-gradient "fit" of the init; sil_sigma is traced
            # INSIDE the jitted solver, so its value check belongs here.
            sigma = bound.arguments.get("sil_sigma", 1.0)
            if (not isinstance(sigma, jax.core.Tracer)
                    and float(sigma) <= 0):
                raise ValueError(f"sil_sigma must be > 0 pixels, "
                                 f"got {sigma}")
            cam = bound.arguments.get("camera")
            cams = cam if is_multiview(cam) else (cam,)
            for c in cams:
                # Any projection's magnification: a zero collapses
                # every vertex to one point (constant mask, zero
                # gradients, the init returned as a "fit").
                for attr in ("scale", "focal", "fx", "fy"):
                    val = getattr(c, attr, None)
                    if (val is not None
                            and not isinstance(val, jax.core.Tracer)
                            and float(val) <= 0):
                        raise ValueError(
                            f"camera {attr} must be > 0 (a zero {attr} "
                            "projects every vertex to one point — "
                            f"constant mask, zero gradients), got {val}"
                        )
                # An IntrinsicsCamera bakes the image resolution into
                # its NDC; rasterizing a DIFFERENT-resolution mask
                # through it silently rescales the projection (e.g. a
                # 256px hand crop against a 640x480 calibration).
                cw, ch = getattr(c, "width", None), getattr(c, "height",
                                                           None)
                if cw is not None and ch is not None:
                    for m in masks:
                        if m is None or isinstance(m, jax.core.Tracer):
                            continue
                        mh, mw = np.shape(m)[-2:]
                        if (mh, mw) != (int(ch), int(cw)):
                            raise ValueError(
                                f"mask resolution {mh}x{mw} does not "
                                "match the IntrinsicsCamera calibration "
                                f"{int(ch)}x{int(cw)} — crop/resize "
                                "masks AND adjust K together"
                            )
        return fn(*args, **kw)

    return wrapper


def prepare_self_pen(fn):
    """Build the [V, V] self-penetration mask BEFORE the jit boundary.

    The mask derives from concrete parameter arrays (numpy argmax over
    skinning weights, rest-pose distances) — impossible inside jit where
    params are tracers. ``self_penetration_weight`` is STATIC (a concrete
    float; changing it recompiles): gating on it lets zero-weight fits
    skip the [V, V] pairwise term and its backward entirely, which a
    traced weight could not (the common case pays nothing).
    """
    @functools.wraps(fn)
    def wrapper(params, *args, self_penetration_weight=0.0,
                self_penetration_radius=0.004, _self_pen_mask=None, **kw):
        if self_penetration_weight and _self_pen_mask is None:
            _self_pen_mask = objectives.self_penetration_mask(
                params, self_penetration_radius
            )
        return fn(params, *args,
                  self_penetration_weight=self_penetration_weight,
                  self_penetration_radius=self_penetration_radius,
                  _self_pen_mask=_self_pen_mask, **kw)

    return wrapper


def check_keypoint_spec(params, data_term, tip_vertex_ids, keypoint_order,
                        target, fn_name):
    """Shared tip/order validation + target row check for every solver.

    Returns ``(tips, n_kp)``: the resolved tip tuple (or None) and the
    keypoint count the spec yields — THE one definition of that count, so
    the conf-length checks can't drift from the target-row check. Target
    row counts are static shapes, so a 21-row target with no tip spec (or
    vice versa) fails HERE with the fix spelled out instead of as a
    broadcast error mid-trace.
    """
    if keypoint_order not in ("mano", "openpose"):
        raise ValueError(
            f"keypoint_order must be 'mano' or 'openpose', "
            f"got {keypoint_order!r}"
        )
    if data_term not in KEYPOINT_TERMS:
        if tip_vertex_ids is not None or keypoint_order != "mano":
            raise ValueError(
                "tip_vertex_ids/keypoint_order only apply to the keypoint "
                f"data terms {KEYPOINT_TERMS}, got data_term={data_term!r}"
            )
        return None, params.j_regressor.shape[0]
    tips = core.resolve_tip_ids(tip_vertex_ids, params.v_template.shape[-2])
    n_kp = params.j_regressor.shape[0] + (len(tips) if tips else 0)
    if keypoint_order == "openpose" and n_kp != 21:
        raise ValueError(
            "keypoint_order='openpose' is the 21-keypoint convention "
            f"(16 joints + 5 tips); this spec yields {n_kp} keypoints"
        )
    if target.shape[-2] != n_kp:
        n_joints = params.j_regressor.shape[0]
        raise ValueError(
            f"{fn_name}: target has {target.shape[-2]} keypoint rows but "
            f"the model produces {n_kp} ({n_joints} joints"
            f"{f' + {len(tips)} tips' if tips else ''}); pass "
            "tip_vertex_ids='smplx'|'manopth' (or explicit vertex ids) "
            "for 21-keypoint targets"
        )
    return tips, n_kp


def normalize_conf(target_conf, n_kp: int, dtype):
    """THE one conf policy: scalars lift to a per-keypoint vector; vectors
    must match the keypoint spec's count (named error, not a broadcast
    crash mid-trace). Returns the normalized array (or None)."""
    if target_conf is None:
        return None
    target_conf = jnp.asarray(target_conf, dtype)
    if target_conf.ndim == 0:
        return jnp.broadcast_to(target_conf, (n_kp,))
    if target_conf.shape[-1] != n_kp:
        # e.g. a stale 16-entry confidence vector with a 21-keypoint fit.
        raise ValueError(
            f"target_conf has {target_conf.shape[-1]} entries but this "
            f"keypoint spec yields {n_kp} keypoints"
        )
    return target_conf


def is_multiview(camera) -> bool:
    """True when ``camera`` is a LIST of cameras (multi-view silhouette).

    THE one detection everywhere: a plain ``isinstance(camera, tuple)``
    is wrong because ``Camera``/``WeakPerspectiveCamera`` are NamedTuples
    — tuple subclasses — and a single camera would read as a "list" of
    its own fields. A camera is whatever exposes ``project``.
    """
    return (isinstance(camera, (list, tuple))
            and not hasattr(camera, "project"))


def check_silhouette_views(camera, target, fn_name: str) -> int:
    """Per-problem target rank for the silhouette term (2, or 3 when
    multi-view), after validating the view axis against the camera list.
    Static shapes, so a views/cameras mismatch fails here by name instead
    of as a broadcast error mid-trace."""
    if not is_multiview(camera):
        return 2
    if target.ndim < 3 or target.shape[-3] != len(camera):
        # ndim < 3 = a single [H, W] mask with a camera list: without
        # this, the batched dispatch would read mask ROWS as problems
        # and die mid-trace — the unnamed failure this check pre-empts.
        views = target.shape[-3] if target.ndim >= 3 else "no"
        raise ValueError(
            f"{fn_name}: {len(camera)} cameras but target has "
            f"{views} views on axis -3 (shape {target.shape}; "
            "multi-view silhouette targets are [..., n_views, H, W])"
        )
    return 3


def check_aux_mask(data_term, target_mask, dtype, n_frames=None):
    """THE validation for the auxiliary keypoints2d mask (fit AND
    fit_sequence — one copy, one error text). Returns the cast mask."""
    if data_term != "keypoints2d":
        # The pure-mask problem is data_term='silhouette'; the aux mask
        # exists to COMBINE with the keypoint term.
        raise ValueError(
            "target_mask is the auxiliary mask for "
            "data_term='keypoints2d' (for mask-only fitting use "
            f"data_term='silhouette'); got data_term={data_term!r}"
        )
    target_mask = jnp.asarray(target_mask, dtype)
    if n_frames is None:
        if target_mask.ndim not in (2, 3) or 0 in target_mask.shape:
            raise ValueError(
                "target_mask must be a non-empty [H, W] (or batched "
                f"[B, H, W]) mask, got {target_mask.shape}"
            )
    elif (target_mask.ndim != 3 or target_mask.shape[0] != n_frames
          or 0 in target_mask.shape):
        raise ValueError(
            "fit_sequence target_mask must be [T, H, W] per-frame "
            f"masks matching {n_frames} frames, got {target_mask.shape}"
        )
    return target_mask


def check_hands_silhouette(camera, robust, targets, seq: bool,
                           fn_name: str,
                           mask_layout: str = "auto") -> bool:
    """Shared validation for the two-hand mask term; returns ``per_hand``
    (instance masks vs one combined mask). One definition for fit_hands
    AND fit_hands_sequence so the rules cannot drift.

    The one genuinely ambiguous shape — a [2, H, W] target at a SEQUENCE
    entry point, which reads equally as a 2-frame combined clip or as
    ONE frame of per-hand masks sent to the wrong function — refuses to
    guess: ``mask_layout="combined"`` claims the clip reading; the
    per-hand single frame belongs to fit_hands.
    """
    if is_multiview(camera):
        raise ValueError(
            f"{fn_name} takes ONE camera; multi-view silhouette is a "
            "single-hand feature (fit with a camera tuple)"
        )
    if robust != "none":
        raise ValueError("robust does not apply to data_term='silhouette'")
    if mask_layout not in ("auto", "combined", "per_hand"):
        raise ValueError(
            "mask_layout must be 'auto', 'combined' or 'per_hand', got "
            f"{mask_layout!r}"
        )
    combined_ndim = 3 if seq else 2          # [T, H, W] / [H, W]
    hand_axis = 1 if seq else 0
    per_hand_ok = (targets.ndim == combined_ndim + 1
                   and targets.shape[hand_axis] == 2)
    combined_ok = targets.ndim == combined_ndim
    if mask_layout == "combined":
        ok = combined_ok
    elif mask_layout == "per_hand":
        ok = per_hand_ok
    else:
        ok = combined_ok or per_hand_ok
        if seq and combined_ok and targets.shape[0] == 2:
            raise ValueError(
                f"{fn_name}: a [2, H, W] mask target is ambiguous — a "
                "2-frame combined clip or ONE frame of per-hand instance "
                "masks. Pass mask_layout='combined' for the clip reading; "
                "for one frame of per-hand masks use fit_hands()"
            )
    ok = ok and 0 not in targets.shape
    if not ok:
        t = "[T, " if seq else "["
        raise ValueError(
            f"silhouette targets must be {t}H, W] combined masks or "
            f"per-hand {t}2, H, W] instance masks "
            f"(mask_layout={mask_layout!r}), got {targets.shape}"
            + ("; for one frame use fit_hands()" if seq else "")
        )
    return per_hand_ok and mask_layout != "combined"


def _data_loss(out, offset, target, data_term: str, camera, conf,
               robust: str = "none", robust_scale: float = 0.01,
               tips=None, keypoint_order: str = "mano",
               faces=None, sil_sigma: float = 0.7):
    """The one data-term dispatch shared by every Adam solver.

    - ``verts``: full-mesh L2 (known correspondence).
    - ``joints``: sparse 3D keypoints (detector/mocap output); shape is
      weakly observable from 16 joints — pair with shape_prior_weight.
    - ``keypoints2d``: posed joints through the pinhole projection.
      Depth is only observable through perspective scaling, so use the
      priors (and fit_trans=True) — ill-posed without them.
    - ``points``: correspondence-FREE registration to an unstructured
      point cloud [N, 3] (depth-sensor scan): one-sided chamfer, each
      observed point to its nearest mesh vertex. Partial views are fine;
      pair with the priors (unobserved regions are unconstrained) and
      ``fit_trans=True`` when the scan is not origin-aligned.
    - ``silhouette``: soft-IoU against a binary/float [H, W] mask — the
      mesh is differentiably rasterized through ``camera`` at the
      target's resolution (viz.soft_silhouette) and compared as images.
      The only term that observes the SURFACE from one view without any
      detector; heavily ill-posed alone (any pose with the same outline
      matches), so pair with priors, and with keypoints2d when available.
      A TUPLE of cameras with [..., C, H, W] targets fits all views
      jointly (mean per-view IoU) — the visual-hull setup: two or more
      calibrated views restore the depth axis a single outline cannot
      observe.
    - ``depth``: a sensor depth image [H, W] in view-space meters
      (<= 0 = invalid, excluded — the universal depth-map convention),
      compared against the soft z-buffer render (viz.soft_depth). The
      one single-view image term that observes FULL 3D translation;
      ``robust="huber"`` bounds the boundary-pixel tails.

    ``robust="huber"`` replaces the per-point squared distance with a
    Huber penalty at scale ``robust_scale`` (same units as the data:
    meters for 3D terms, NDC for 2D) — un-flagged outliers contribute
    bounded gradients. Returns a scalar: single problems reduce
    naturally; clip-shaped inputs ([T, ...]) mean over frames.
    """
    if robust not in ("none", "huber"):
        raise ValueError(f"robust must be 'none' or 'huber', got {robust!r}")
    if (robust == "huber" and not isinstance(robust_scale, jax.core.Tracer)
            and float(robust_scale) <= 0):
        # A zero scale makes the whole data term identically zero (the
        # fit would silently return the initialization); negative rewards
        # outliers. robust_scale is static in the jitted entry points, so
        # it is always concrete there (incl. numpy scalars — hence
        # float(), not an isinstance whitelist). Checked before ANY term
        # branch so the depth path gets it too.
        raise ValueError(f"robust_scale must be > 0, got {robust_scale}")
    if data_term == "depth":
        # A sensor depth image: the ONE single-view term that observes
        # full 3D translation (a silhouette cannot see z; depth IS z).
        # Invalid (<= 0) pixels are excluded; Huber applies per pixel
        # (sensor depth is heavy-tailed at object boundaries).
        from mano_hand_tpu.viz.silhouette import soft_depth
        penalty = (
            (lambda sq: objectives.huber(sq, robust_scale))
            if robust == "huber" else None
        )
        pred = soft_depth(
            out.verts + offset, faces, camera,
            height=target.shape[-2], width=target.shape[-1],
            sigma=sil_sigma,
        )
        return jnp.mean(objectives.depth_loss(pred, target, penalty))
    if data_term == "silhouette":
        if robust != "none":
            # The IoU is already bounded per image; there is no per-point
            # distance for Huber to act on.
            raise ValueError("robust does not apply to data_term='silhouette'")
        from mano_hand_tpu.viz.silhouette import soft_silhouette
        verts = out.verts + offset
        h, w = target.shape[-2], target.shape[-1]
        if is_multiview(camera):
            # Multi-view: one [H, W] render per calibrated camera, view
            # axis stacked at -3 to line up with [..., C, H, W] targets.
            sil = jnp.stack(
                [soft_silhouette(verts, faces, c, height=h, width=w,
                                 sigma=sil_sigma) for c in camera],
                axis=-3,
            )
        else:
            sil = soft_silhouette(verts, faces, camera, height=h, width=w,
                                  sigma=sil_sigma)
        return jnp.mean(objectives.silhouette_iou_loss(sil, target))
    penalty = (
        (lambda sq: objectives.huber(sq, robust_scale))
        if robust == "huber" else None
    )
    if data_term == "verts":
        return objectives.vertex_l2(out.verts + offset, target, penalty)
    if data_term == "points":
        return objectives.point_cloud_l2(out.verts + offset, target, penalty)
    # Keypoint terms: the 16 skeleton joints, optionally extended with
    # fingertip vertex picks (tips resolved/validated by
    # check_keypoint_spec) and re-ordered to the target's convention.
    kp = core.keypoints(out, tips, keypoint_order)
    if data_term == "joints":
        return objectives.joint_l2(kp + offset, target, penalty)
    xy = camera.project(kp + offset)[..., :2]
    return jnp.mean(objectives.keypoint2d_l2(xy, target, conf, penalty))


def _run_adam(loss_fn, theta0, optimizer, n_steps: int):
    """The shared jitted optimization loop: lax.scan over Adam steps.

    ``loss_fn(p) -> (total, data)``; the history records the data loss
    *before* each update, and the returned parameters are re-evaluated
    once so final_loss describes them, not the state one step behind.
    """
    opt_state0 = optimizer.init(theta0)

    def step(carry, _):
        p, opt_state = carry
        (_, data), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        p = optax.apply_updates(p, updates)
        return (p, opt_state), data

    (p_final, _), history = jax.lax.scan(
        step, (theta0, opt_state0), None, length=n_steps
    )
    _, final_loss = loss_fn(p_final)
    return p_final, final_loss, history


def _fit_single(
    params: ManoParams,
    target: jnp.ndarray,  # [V, 3] | [J, 3] | [J, 2] | [N, 3] (see data_term)
    conf: Optional[jnp.ndarray] = None,  # [J] keypoint confidences
    *,
    n_steps: int,
    optimizer: optax.GradientTransformation,
    pose_space: str,
    n_pca: int,
    pose_prior_weight: float,
    shape_prior_weight: float,
    data_term: str = "verts",
    camera=None,
    fit_trans: bool = False,
    robust: str = "none",
    robust_scale: float = 0.01,
    init: Optional[dict] = None,
    pose_prior: str = "l2",
    pose_prior_vars: Optional[jnp.ndarray] = None,
    joint_limits=None,           # (lo, hi) per flat articulated DOF
    joint_limit_weight: float = 0.0,
    tips=None,
    keypoint_order: str = "mano",
    self_penetration_weight: float = 0.0,
    self_penetration_radius: float = 0.004,
    self_pen_mask: Optional[jnp.ndarray] = None,
    sil_sigma: float = 0.7,
    target_mask: Optional[jnp.ndarray] = None,  # [H, W] aux mask
    mask_weight: float = 0.1,
    frozen_shape: Optional[jnp.ndarray] = None,  # [S]: pose-only fit
) -> FitResult:
    _check_data_term(data_term, camera, conf)
    _check_pose_prior(pose_prior, pose_space, joint_limits)
    dtype = params.v_template.dtype
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]
    # Frozen-betas mode (the specialization split, models/core.py): beta
    # is a known per-subject constant, so it leaves the parameter dict —
    # the optimizer state, gradients and updates all shrink to pose-only.
    freeze = frozen_shape is not None
    if freeze:
        frozen_shape = jnp.asarray(frozen_shape, dtype).reshape(n_shape)

    theta0 = _pose_init(pose_space, (), n_joints, n_pca, dtype,
                        allowed={"aa", "pca", "6d"})
    if not freeze:
        theta0["shape"] = jnp.zeros((n_shape,), dtype)
    if fit_trans:
        # Global translation DOF: the model itself has none (the reference
        # keeps hands at the origin), but image-space fitting needs the
        # hand placed in the camera frustum.
        theta0["trans"] = jnp.zeros((3,), dtype)

    if init:
        # Warm start: seed any subset of the parameters (previous frame's
        # solution, a detector initializer, a coarse fit). Keys must match
        # the active parameterization.
        unknown = set(init) - set(theta0)
        if unknown:
            raise ValueError(
                f"init keys {sorted(unknown)} not in this parameterization "
                f"{sorted(theta0)} (pose_space={pose_space!r}, "
                f"fit_trans={fit_trans})"
            )
        for k, v in init.items():
            v = jnp.asarray(v, dtype)
            if v.shape != theta0[k].shape:
                # No silent reshape: a transposed or re-flattened seed has
                # the right element count but scrambled joints, and would
                # quietly degrade to worse-than-cold convergence.
                raise ValueError(
                    f"init[{k!r}] shape {v.shape} != expected "
                    f"{theta0[k].shape}"
                )
            theta0[k] = v

    def shape_of(p):
        return frozen_shape if freeze else p["shape"]

    def model_out(p):
        if pose_space == "6d":
            return core.forward_rotmats(
                params, ops.matrix_from_6d(p["rot6d"]), shape_of(p)
            )
        return core.forward(params, _pose_to_aa(pose_space, params, p),
                            shape_of(p))

    def loss_fn(p):
        out = model_out(p)
        offset = p["trans"] if fit_trans else 0.0
        data = _data_loss(out, offset, target, data_term, camera, conf,
                          robust, robust_scale, tips, keypoint_order,
                          params.faces, sil_sigma)
        if target_mask is not None:
            # The standard tracking energy: sparse keypoints pin the
            # skeleton, the mask refines the surface outline — both
            # through ONE camera. Reuses the silhouette term verbatim.
            data = data + mask_weight * _data_loss(
                out, offset, target_mask, "silhouette", camera, None,
                "none", robust_scale, None, "mano", params.faces,
                sil_sigma,
            )
        # Prior weights may be traced scalars (see fit): plain multiplies.
        reg = _pose_reg(pose_space, pose_prior, pose_prior_vars, params, p,
                        dtype, pose_prior_weight, joint_limits,
                        joint_limit_weight)
        if not freeze:
            # A frozen beta is a constant: its prior would add a constant
            # with zero gradient — skip the term (and its backward).
            reg = reg + shape_prior_weight * objectives.l2_prior(p["shape"])
        if self_pen_mask is not None and self_penetration_weight:
            # Static gate (see prepare_self_pen; the weight check keeps a
            # prebuilt-mask-with-zero-weight call from tracing the dense
            # term): fingers must not pass through each other — the
            # failure mode of sparse keypoint observations, which say
            # nothing about the surface between.
            reg = reg + self_penetration_weight * objectives.self_penetration(
                out.verts, self_pen_mask, self_penetration_radius
            )
        return data + reg, data

    p_final, final_loss, history = _run_adam(
        loss_fn, theta0, optimizer, n_steps
    )
    return FitResult(
        pose=_pose_to_aa(pose_space, params, p_final),
        shape=shape_of(p_final),
        final_loss=final_loss,
        loss_history=history,
        pca=p_final.get("pca"),
        trans=p_final.get("trans"),
    )


@validate_mask_target
@normalize_tips_kwarg
@prepare_self_pen
@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "pose_space", "n_pca", "data_term",
                     "fit_trans", "robust", "robust_scale", "pose_prior",
                     "tip_vertex_ids", "keypoint_order",
                     "self_penetration_weight", "self_penetration_radius"),
)
def fit(
    params: ManoParams,
    target_verts: jnp.ndarray,  # [V, 3] or [B, V, 3] ([J, 3] joints;
                                # [J, 2] keypoints2d; [N, 3] points)
    n_steps: int = 200,
    lr: float = 0.05,
    pose_space: str = "aa",
    n_pca: int = 45,
    pose_prior_weight: float = 0.0,
    shape_prior_weight: float = 0.0,
    data_term: str = "verts",
    camera=None,
    target_conf: Optional[jnp.ndarray] = None,  # [J] or [B, J]
    fit_trans: bool = False,
    robust: str = "none",
    robust_scale: float = 0.01,
    init: Optional[dict] = None,
    pose_prior: str = "l2",
    pose_prior_vars: Optional[jnp.ndarray] = None,  # [C] component vars
    joint_limits=None,           # (lo, hi) per flat articulated DOF
    joint_limit_weight: float = 1.0,
    tip_vertex_ids=None,         # None | "smplx" | "manopth" | vertex ids
    keypoint_order: str = "mano",  # "mano" | "openpose" (21-kp targets)
    self_penetration_weight: float = 0.0,   # STATIC: nonzero recompiles
    self_penetration_radius: float = 0.004,
    _self_pen_mask=None,         # built by prepare_self_pen; do not pass
    sil_sigma: float = 0.7,      # silhouette edge softness, pixels
    target_mask: Optional[jnp.ndarray] = None,  # [H, W] / [B, H, W]
    mask_weight: float = 0.1,
    frozen_shape: Optional[jnp.ndarray] = None,  # [S] or [B, S]
) -> FitResult:
    """Recover pose/shape for one target mesh or a batch of them.

    Batched targets fit as independent problems in parallel (vmap); this is
    BASELINE.json config 4 at batch=256. ``lr`` and the prior weights are
    traced operands, so a hyperparameter sweep reuses one compiled program.
    ``data_term='keypoints2d'`` fits 2D detector output: posed joints are
    projected through ``camera`` (a pinhole ``viz.camera.Camera``, or a
    ``viz.WeakPerspectiveCamera`` for HMR-style (s, tx, ty) annotations)
    and compared in image space, optionally confidence-weighted; pair
    with ``fit_trans=True`` (adds a global translation DOF) and nonzero
    priors — under pinhole projection depth is only observable through
    perspective scaling, and under weak perspective not at all (keep the
    z-prior on). ``data_term='silhouette'`` fits a segmentation MASK
    instead: the mesh is differentiably rasterized through ``camera``
    (viz.soft_silhouette, edge softness ``sil_sigma`` pixels) and scored
    by soft IoU at the target's [H, W] resolution — the right term when
    a segmenter is trusted but no keypoint detector is; it observes only
    the outline, so keep the pose priors on. When BOTH a detector and a
    segmenter are available, fit keypoints2d and pass the mask as
    ``target_mask`` (+ ``mask_weight``): the classic tracking energy —
    sparse keypoints pin the skeleton, the mask refines the outline,
    both through the one ``camera``. For a custom
    optimizer use ``fit_with_optimizer`` (not jitted at this level so the
    transformation can be any optax object).

    ``pose_prior="mahalanobis"`` swaps the isotropic pose regularizer for
    the data-driven ``objectives.mahalanobis_pose_prior`` (deviation from
    the asset's mean pose in PCA-whitened space; ``pose_prior_vars`` adds
    per-component variances, e.g. from
    ``objectives.pose_component_variances`` over scan poses). The priors
    carry ill-posed fits — sparse joints, 2D keypoints, partial clouds —
    toward anatomically plausible poses instead of the flat zero pose.

    ``joint_limits`` (a per-DOF ``(lo, hi)`` pair in articulated
    axis-angle coordinates, e.g. from
    ``objectives.pose_limits_from_corpus`` over the official assets'
    scan poses) adds ``objectives.pose_limit_prior`` — a squared hinge
    that is ZERO inside the admissible box and walls off hyperextension
    and reversed bends outside it. It composes with either
    ``pose_prior`` (interior shaping vs boundary enforcement) and costs
    one elementwise pass; ``joint_limit_weight`` scales it (the default
    1.0 is strong relative to a hinge violation measured in radians).

    ``tip_vertex_ids`` extends the keypoint data terms with fingertip
    vertex picks — the 21-keypoint convention every major hand dataset
    and detector uses (MANO's skeleton has no tips). Pass ``"smplx"`` or
    ``"manopth"`` for the two circulating vertex-id conventions on the
    official mesh, or explicit vertex ids; ``keypoint_order="openpose"``
    matches OpenPose/FreiHAND-ordered targets. Fingertips pin the distal
    phalanx orientations that 16 joints leave entirely unobserved.

    ``self_penetration_weight > 0`` (a STATIC float — changing it
    recompiles; zero-weight fits skip the term entirely) adds
    ``objectives.self_penetration``: a hinge that keeps non-adjacent
    body parts — fingers, thumb vs palm — from passing through each
    other, the classic failure of sparse keypoint observations. The
    part-adjacency mask is built from the asset's skinning weights
    before the jit boundary (``prepare_self_pen``).

    ``frozen_shape`` pins beta to a known per-subject constant and fits
    pose only (the specialization split's first-order counterpart of
    ``fit_lm``'s frozen mode — see ``models.core.specialize``): the
    parameter dict, optimizer state and gradients all shrink to the
    pose DOFs, the shape prior drops out, and ``FitResult.shape``
    returns the frozen betas. [B, S] gives batched problems their own
    subjects; ``init`` must not seed ``"shape"``.
    """
    return fit_with_optimizer(
        params, target_verts, optax.adam(lr),
        n_steps=n_steps, pose_space=pose_space, n_pca=n_pca,
        pose_prior_weight=pose_prior_weight,
        shape_prior_weight=shape_prior_weight,
        data_term=data_term, camera=camera, target_conf=target_conf,
        fit_trans=fit_trans, robust=robust, robust_scale=robust_scale,
        init=init, pose_prior=pose_prior, pose_prior_vars=pose_prior_vars,
        joint_limits=joint_limits, joint_limit_weight=joint_limit_weight,
        tip_vertex_ids=tip_vertex_ids, keypoint_order=keypoint_order,
        self_penetration_weight=self_penetration_weight,
        self_penetration_radius=self_penetration_radius,
        _self_pen_mask=_self_pen_mask,
        sil_sigma=sil_sigma,
        target_mask=target_mask,
        mask_weight=mask_weight,
        frozen_shape=frozen_shape,
    )


@validate_mask_target
@prepare_self_pen
def fit_with_optimizer(
    params: ManoParams,
    target_verts: jnp.ndarray,
    optimizer: optax.GradientTransformation,
    n_steps: int = 200,
    pose_space: str = "aa",
    n_pca: int = 45,
    pose_prior_weight: float = 0.0,
    shape_prior_weight: float = 0.0,
    data_term: str = "verts",
    camera=None,
    target_conf: Optional[jnp.ndarray] = None,
    fit_trans: bool = False,
    robust: str = "none",
    robust_scale: float = 0.01,
    init: Optional[dict] = None,
    pose_prior: str = "l2",
    pose_prior_vars: Optional[jnp.ndarray] = None,
    joint_limits=None,
    joint_limit_weight: float = 1.0,
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    self_penetration_weight: float = 0.0,
    self_penetration_radius: float = 0.004,
    _self_pen_mask=None,
    sil_sigma: float = 0.7,
    target_mask: Optional[jnp.ndarray] = None,
    mask_weight: float = 0.1,
    frozen_shape: Optional[jnp.ndarray] = None,
) -> FitResult:
    _check_data_term(data_term, camera, target_conf)
    if target_mask is not None:
        target_mask = check_aux_mask(
            data_term, target_mask, params.v_template.dtype
        )
    target_verts = jnp.asarray(target_verts, params.v_template.dtype)
    if frozen_shape is not None:
        frozen_shape = jnp.asarray(frozen_shape, params.v_template.dtype)
        n_sh = params.shape_basis.shape[-1]
        if frozen_shape.ndim not in (1, 2) or frozen_shape.shape[-1] != n_sh:
            raise ValueError(
                f"frozen_shape must be [{n_sh}] (or [B, {n_sh}] for "
                f"batched problems), got {frozen_shape.shape}"
            )
    tips, n_kp = check_keypoint_spec(
        params, data_term, tip_vertex_ids, keypoint_order, target_verts,
        "fit",
    )
    single = functools.partial(
        _fit_single,
        params,
        n_steps=n_steps,
        optimizer=optimizer,
        pose_space=pose_space,
        n_pca=n_pca,
        pose_prior_weight=pose_prior_weight,
        shape_prior_weight=shape_prior_weight,
        data_term=data_term,
        camera=camera,
        fit_trans=fit_trans,
        robust=robust,
        robust_scale=robust_scale,
        pose_prior=pose_prior,
        pose_prior_vars=pose_prior_vars,
        joint_limits=joint_limits,
        joint_limit_weight=joint_limit_weight,
        tips=tips,
        keypoint_order=keypoint_order,
        self_penetration_weight=self_penetration_weight,
        self_penetration_radius=self_penetration_radius,
        self_pen_mask=_self_pen_mask,
        sil_sigma=sil_sigma,
        mask_weight=mask_weight,
    )
    if data_term == "points" and target_verts.shape[-2] == 0:
        # A zero-point cloud (empty depth-scan foreground) would mean() over
        # an empty axis -> NaN in every parameter, silently.
        raise ValueError("points target cloud is empty ([..., 0, 3])")
    target_conf = normalize_conf(target_conf, n_kp,
                                 params.v_template.dtype)
    single_ndim = 2
    if data_term == "silhouette":
        single_ndim = check_silhouette_views(camera, target_verts, "fit")
    if target_verts.ndim == single_ndim:
        if target_mask is not None and target_mask.ndim != 2:
            raise ValueError(
                "single-problem fits take one [H, W] target_mask, got "
                f"{target_mask.shape}"
            )
        if frozen_shape is not None and frozen_shape.ndim != 1:
            raise ValueError(
                "single-problem fits take one frozen_shape [S], got "
                f"{frozen_shape.shape}"
            )
        return single(target_verts, target_conf, init=init,
                      target_mask=target_mask, frozen_shape=frozen_shape)
    # Batched problems: map conf per-problem when it is [B, J]; a shared
    # [J] conf (or None) broadcasts via in_axes=None. A warm-start init
    # must carry the batch on every leaf (one seed per problem). The aux
    # mask follows the conf policy: [B, H, W] maps per problem, [H, W]
    # is shared — and the frozen betas follow it too ([B, S] per
    # problem, [S] one shared subject).
    if init:
        validate_batched_init(
            init, target_verts.shape[0],
            _batched_init_shapes(
                pose_space, params.j_regressor.shape[0], n_pca,
                params.shape_basis.shape[-1], fit_trans,
                freeze_shape=frozen_shape is not None,
            ),
            target_verts.shape, "fit",
        )
    conf_axis = 0 if (target_conf is not None
                      and target_conf.ndim == 2) else None
    mask_axis = 0 if (target_mask is not None
                      and target_mask.ndim == 3) else None
    fs_axis = 0 if (frozen_shape is not None
                    and frozen_shape.ndim == 2) else None
    if (mask_axis == 0
            and target_mask.shape[0] != target_verts.shape[0]):
        # Named error, not vmap's generic "inconsistent sizes".
        raise ValueError(
            f"batched target_mask has {target_mask.shape[0]} masks for "
            f"{target_verts.shape[0]} problems (shapes "
            f"{target_mask.shape} vs {target_verts.shape}); pass one "
            "[H, W] mask to share it"
        )
    if fs_axis == 0 and frozen_shape.shape[0] != target_verts.shape[0]:
        raise ValueError(
            f"batched frozen_shape has {frozen_shape.shape[0]} rows for "
            f"{target_verts.shape[0]} problems; pass one [S] vector to "
            "share the subject"
        )
    return jax.vmap(
        lambda t, c, i, m, f: single(t, c, init=i, target_mask=m,
                                     frozen_shape=f),
        in_axes=(0, conf_axis, 0 if init else None, mask_axis, fs_axis),
    )(target_verts, target_conf, init, target_mask, frozen_shape)


# ------------------------------------------------------------- sequences
class SequenceFitResult(NamedTuple):
    pose: jnp.ndarray          # [T, 16, 3] per-frame axis-angle pose
    shape: jnp.ndarray         # [S] ONE shape for the whole clip
    final_loss: jnp.ndarray    # [] mean per-frame data loss at the end
    loss_history: jnp.ndarray  # [n_steps] data-loss curve
    trans: Optional[jnp.ndarray] = None  # [T, 3] when fit_trans=True


@validate_mask_target
@normalize_tips_kwarg
@prepare_self_pen
@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "data_term", "fit_trans", "robust",
                     "robust_scale", "pose_space", "pose_prior",
                     "tip_vertex_ids", "keypoint_order",
                     "self_penetration_weight", "self_penetration_radius"),
)
def fit_sequence(
    params: ManoParams,
    targets: jnp.ndarray,  # [T, V, 3] | [T, J, 3] | [T, J, 2] | [T, N, 3]
    n_steps: int = 300,
    lr: float = 0.03,
    data_term: str = "verts",
    camera=None,
    target_conf: Optional[jnp.ndarray] = None,  # [T, J] or [J]
    fit_trans: bool = False,
    robust: str = "none",
    robust_scale: float = 0.01,
    smooth_pose_weight: float = 1e-3,
    smooth_trans_weight: float = 1e-3,
    pose_prior_weight: float = 0.0,
    shape_prior_weight: float = 1e-3,
    pose_space: str = "aa",
    pose_prior: str = "l2",
    pose_prior_vars: Optional[jnp.ndarray] = None,
    joint_limits=None,
    joint_limit_weight: float = 1.0,
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    self_penetration_weight: float = 0.0,   # STATIC: nonzero recompiles
    self_penetration_radius: float = 0.004,
    _self_pen_mask=None,
    sil_sigma: float = 0.7,
    target_mask: Optional[jnp.ndarray] = None,  # [T, H, W] aux masks
    mask_weight: float = 0.1,
) -> SequenceFitResult:
    """Track a whole motion clip as ONE optimization problem.

    Unlike vmapping ``fit`` over frames, the clip shares a single shape
    (one hand, one identity — the per-frame shape ambiguity collapses)
    and couples consecutive frames with squared-velocity smoothness
    priors on pose (and translation), so frames with occluded or
    corrupted observations borrow information from their neighbors.
    The reference's closest analogue is the serial per-frame animation
    loop (/root/reference/data_explore.py:12-15); here all T frames'
    forwards are one batched program inside one jitted Adam loop.

    Pose is parameterized per frame as axis-angle ([T, 16, 3], the
    default) or the 6D continuous representation
    (``pose_space="6d"``) — in 6D the velocity coupling is wrap-free
    (axis-angle jumps by 2*pi at the chart boundary read as huge fake
    velocities on long clips with large rotations), and results decode
    back to axis-angle. The smoothness weights scale mean squared
    frame-to-frame differences. The 1e-3 defaults keep the data term
    dominant on clean dense targets; raise toward ~1e-2 for noisy sparse
    observations (the regime the occlusion-bridging tests validate),
    lower toward 0 for fast motion sampled coarsely.
    """
    _check_data_term(data_term, camera, target_conf)
    _check_pose_prior(pose_prior, pose_space, joint_limits)
    dtype = params.v_template.dtype
    targets = jnp.asarray(targets, dtype)
    want_ndim = 3
    if data_term == "silhouette":
        want_ndim = 1 + check_silhouette_views(camera, targets,
                                               "fit_sequence")
    if targets.ndim != want_ndim:
        # A [V, 3]/[J, 3] single frame would otherwise be read as V or J
        # one-point frames via broadcasting and fit garbage silently.
        raise ValueError(
            "fit_sequence targets must be [T, rows, coords] ([T, H, W] "
            "masks / [T, n_views, H, W] multi-view for the silhouette "
            "term); for a single frame use fit(). Got shape "
            f"{targets.shape}"
        )
    if data_term == "points" and targets.shape[-2] == 0:
        raise ValueError("points target cloud is empty ([T, 0, 3])")
    if target_mask is not None:
        target_mask = check_aux_mask(
            data_term, target_mask, dtype, n_frames=targets.shape[0]
        )
    tips, n_kp = check_keypoint_spec(
        params, data_term, tip_vertex_ids, keypoint_order, targets,
        "fit_sequence",
    )
    t_frames = targets.shape[0]
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]
    target_conf = normalize_conf(target_conf, n_kp, dtype)
    if target_conf is not None:
        target_conf = jnp.broadcast_to(target_conf, (t_frames, n_kp))

    theta0 = _pose_init(pose_space, (t_frames,), n_joints, n_pca=0,
                        dtype=dtype, allowed={"aa", "6d"})
    theta0["shape"] = jnp.zeros((n_shape,), dtype)
    if fit_trans:
        theta0["trans"] = jnp.zeros((t_frames, 3), dtype)

    pose_key = "pose" if pose_space == "aa" else "rot6d"

    def loss_fn(p):
        shapes = jnp.broadcast_to(p["shape"], (t_frames, n_shape))
        if pose_space == "6d":
            out = core.forward_batched_rotmats(
                params, ops.matrix_from_6d(p["rot6d"]), shapes
            )
        else:
            out = core.forward_batched(params, p["pose"], shapes)
        offset = (
            p["trans"][:, None, :] if fit_trans
            else jnp.zeros((), dtype)
        )
        data = _data_loss(out, offset, targets, data_term, camera,
                          target_conf, robust, robust_scale, tips,
                          keypoint_order, params.faces, sil_sigma)
        if target_mask is not None:
            # Per-frame aux masks over the whole clip — same combined
            # energy as fit's, one camera (see fit's docstring).
            data = data + mask_weight * _data_loss(
                out, offset, target_mask, "silhouette", camera, None,
                "none", robust_scale, None, "mano", params.faces,
                sil_sigma,
            )
        # t_frames is static: skip velocity terms for single-frame clips
        # (mean over an empty array is NaN and would poison every grad).
        # Velocity couples whichever representation is being optimized —
        # in 6D it is wrap-free by construction.
        if t_frames > 1:
            vel = p[pose_key][1:] - p[pose_key][:-1]
            reg = smooth_pose_weight * jnp.mean(vel ** 2)
            if fit_trans:
                tvel = p["trans"][1:] - p["trans"][:-1]
                reg = reg + smooth_trans_weight * jnp.mean(tvel ** 2)
        else:
            reg = jnp.zeros((), dtype)
        reg = (
            reg
            + _pose_reg(pose_space, pose_prior, pose_prior_vars, params, p,
                        dtype, pose_prior_weight, joint_limits,
                        joint_limit_weight)
            + shape_prior_weight * objectives.l2_prior(p["shape"])
        )
        if _self_pen_mask is not None and self_penetration_weight:
            # self_penetration broadcasts over the frame axis; the final
            # mean over [T, V] equals the mean of per-frame means.
            reg = reg + self_penetration_weight * objectives.self_penetration(
                out.verts, _self_pen_mask, self_penetration_radius
            )
        return data + reg, data

    p_final, final_loss, history = _run_adam(
        loss_fn, theta0, optax.adam(lr), n_steps
    )
    return SequenceFitResult(
        pose=_pose_to_aa(pose_space, params, p_final),
        shape=p_final["shape"],
        final_loss=final_loss,
        loss_history=history,
        trans=p_final.get("trans"),
    )


# ----------------------------------------------------- bucketed wrappers
def _jit_cache_size(fn) -> Optional[int]:
    """Entry count of the underlying jit cache, unwrapping the validation
    decorators (they all ``functools.wraps``). None when unavailable —
    the counters then simply don't tick, they never lie."""
    while not hasattr(fn, "_cache_size") and hasattr(fn, "__wrapped__"):
        fn = fn.__wrapped__
    try:
        return int(fn._cache_size())
    except Exception:  # noqa: BLE001 — observability must not break fits
        return None


def bucketed_fit_call(fit_fn, params, targets, *, min_bucket, max_bucket,
                      counters, init, fn_name, **kw):
    """Shared engine of ``fit_bucketed``/``fit_lm_bucketed``.

    Pads the PROBLEM axis (leading dim) of a batched-fit call up to a
    power-of-two bucket (serving/buckets.py) so tracking-style workloads
    with ragged problem counts reuse ``log2(max_bucket)`` compiled fit
    programs instead of retracing per novel count. Pad problems repeat
    problem 0 (live numerics, normal convergence); their results are
    sliced back off every leaf of the returned NamedTuple. Warm-start
    ``init`` leaves are padded the same way. ``counters``
    (utils.profiling.ServingCounters) observes real retraces via the
    solver's jit cache size — not a guess — plus padding waste.
    """
    from mano_hand_tpu.serving import buckets as bucket_mod

    targets = jnp.asarray(targets)
    if targets.ndim < 3:
        raise ValueError(
            f"{fn_name} wraps BATCHED problems ([B, rows, coords] / "
            f"[B, H, W] targets); got {targets.shape} — call the "
            "unbucketed solver for a single problem")
    b = targets.shape[0]
    bucket = bucket_mod.bucket_for(
        b, bucket_mod.bucket_sizes(min_bucket, max_bucket))
    padded = bucket_mod.pad_rows(targets, bucket)
    if init is not None:
        init = bucket_mod.pad_tree_rows(init, bucket)
    # Per-problem auxiliary kwargs ride the same problem axis as the
    # targets and must pad with them (an unpadded [B, ...] conf against
    # [bucket, ...] targets dies as a vmap axis mismatch mid-trace).
    # Batched-vs-shared is decided by RANK, exactly like the solvers
    # themselves do (conf: [B, J] vs [J]; mask: [B, H, W] vs [H, W];
    # frozen betas: [B, S] vs [S]) — a shape[0]==b test alone would pad
    # a shared [H, W] mask whose height merely coincides with the
    # problem count.
    for aux, batched_ndim in (("target_conf", 2), ("target_mask", 3),
                              ("frozen_shape", 2)):
        v = kw.get(aux)
        if v is not None:
            v = jnp.asarray(v)
            if v.ndim == batched_ndim and v.shape[0] == b:
                kw[aux] = bucket_mod.pad_rows(v, bucket)
    before = _jit_cache_size(fit_fn)
    res = fit_fn(params, padded, init=init, **kw)
    after = _jit_cache_size(fit_fn)
    if counters is not None:
        if before is not None and after is not None and after > before:
            counters.count_compile(after - before)
        counters.count_dispatch(bucket, b)
    return type(res)(*(None if x is None else x[:b] for x in res))


def fit_bucketed(
    params: ManoParams,
    target_verts: jnp.ndarray,   # [B, rows, coords] / [B, H, W]
    *,
    min_bucket: int = 1,
    max_bucket: int = 1024,
    counters=None,
    init: Optional[dict] = None,
    **kw,
) -> FitResult:
    """``fit`` for many-small-problem streams with ragged problem counts.

    The serving engine's bucket policy applied to FITTING (the tracking
    shape of the workload: per-frame batches of independent problems
    whose count varies frame to frame): the problem batch is padded to
    the nearest power-of-two bucket and the pad problems' results are
    masked off, so steady traffic reuses a handful of compiled programs
    — zero retraces after warm-up (pinned in tests/test_serving.py).
    All ``fit`` kwargs pass through; ``counters`` observes compiles and
    padding waste.
    """
    return bucketed_fit_call(
        fit, params, target_verts, min_bucket=min_bucket,
        max_bucket=max_bucket, counters=counters, init=init,
        fn_name="fit_bucketed", **kw)
