from mano_hand_tpu.fitting.objectives import (
    huber,
    inter_penetration,
    joint_l2,
    keypoint2d_l2,
    l2_prior,
    mahalanobis_pose_prior,
    max_vertex_error,
    mirror_pose_limits,
    pose_component_variances,
    pose_limit_prior,
    pose_limits_from_corpus,
    self_penetration,
    self_penetration_mask,
    vertex_l2,
)
from mano_hand_tpu.fitting.initialize import (
    initialize_from_joints,
    rigid_align,
)
from mano_hand_tpu.fitting.hands import (
    HandsFitResult,
    HandsSequenceFitResult,
    fit_hands,
    fit_hands_sequence,
)
from mano_hand_tpu.fitting.solvers import (
    FitResult,
    SequenceFitResult,
    fit,
    fit_sequence,
    fit_with_optimizer,
)
from mano_hand_tpu.fitting.lm import LMResult, fit_lm
from mano_hand_tpu.fitting.restarts import fit_restarts
from mano_hand_tpu.fitting.tracking import (
    TrackState,
    make_hands_tracker,
    make_tracker,
    track_clip,
    track_hands_clip,
)

__all__ = [
    "FitResult",
    "HandsFitResult",
    "SequenceFitResult",
    "fit_hands",
    "fit_hands_sequence",
    "HandsSequenceFitResult",
    "inter_penetration",
    "self_penetration",
    "self_penetration_mask",
    "fit",
    "fit_sequence",
    "fit_with_optimizer",
    "LMResult",
    "fit_lm",
    "fit_restarts",
    "TrackState",
    "make_hands_tracker",
    "make_tracker",
    "track_clip",
    "track_hands_clip",
    "vertex_l2",
    "joint_l2",
    "keypoint2d_l2",
    "huber",
    "l2_prior",
    "mahalanobis_pose_prior",
    "mirror_pose_limits",
    "pose_component_variances",
    "pose_limit_prior",
    "pose_limits_from_corpus",
    "initialize_from_joints",
    "rigid_align",
    "max_vertex_error",
]
