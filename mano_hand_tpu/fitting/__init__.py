from mano_hand_tpu.fitting.objectives import (
    huber,
    joint_l2,
    keypoint2d_l2,
    l2_prior,
    max_vertex_error,
    vertex_l2,
)
from mano_hand_tpu.fitting.solvers import (
    FitResult,
    SequenceFitResult,
    fit,
    fit_sequence,
    fit_with_optimizer,
)
from mano_hand_tpu.fitting.lm import LMResult, fit_lm

__all__ = [
    "FitResult",
    "SequenceFitResult",
    "fit",
    "fit_sequence",
    "fit_with_optimizer",
    "LMResult",
    "fit_lm",
    "vertex_l2",
    "joint_l2",
    "keypoint2d_l2",
    "huber",
    "l2_prior",
    "max_vertex_error",
]
