"""Streaming (online) hand tracking: one frame at a time, warm-started.

``fit_sequence`` solves a whole clip jointly — the right tool offline,
useless at a live sensor. This module is the online counterpart: a
``track_step(state, frame_target) -> (state, result)`` API where each
frame's solve warm-starts from the previous frame's solution, so a
handful of optimizer steps per frame suffices (the solution moves only
as far as the hand moved since the last frame).

The reference's closest analogue is its serial per-frame animation loop
(/root/reference/data_explore.py:12-15) — forward-only. Here each frame
runs a jitted inverse solve; every call after the first hits the jit
cache, so per-frame latency is one compiled program (bench.py measures
it as ``config5_track_ms_per_frame``).

Typical use::

    state, step = make_tracker(params, n_steps=10, data_term="verts")
    for frame in sensor:
        state, res = step(state, frame)
        consume(res.pose, res.shape)
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.fitting import lm as lm_mod
from mano_hand_tpu.fitting import solvers


class TrackState(NamedTuple):
    """Warm-start carried between frames (the previous frame's solution)."""

    pose: jnp.ndarray            # [J, 3] axis-angle
    shape: jnp.ndarray           # [S]
    trans: Optional[jnp.ndarray] = None  # [3] when the tracker fits it
    frame: int = 0               # frames consumed so far (host-side int)


def make_tracker(
    params: ManoParams,
    n_steps: int = 10,
    solver: str = "adam",
    data_term: str = "verts",
    lr: float = 0.02,
    fit_trans: bool = False,
    shape_prior_weight: float = 1e-3,
    camera=None,
    frozen_shape=None,           # [S]: pose-only tracking, betas pinned
    deadline_s: Optional[float] = None,
    retries: int = 0,
    init_pose=None,              # [J, 3]: seed the warm start directly
    **solver_kw,
) -> Tuple[TrackState, Callable]:
    """Build a streaming tracker; returns ``(initial_state, track_step)``.

    ``track_step(state, target) -> (state, result)`` fits ONE frame,
    seeded from ``state`` (frame 0: the rest pose, or — on the 3D
    correspondence terms "verts"/"joints" — the closed-form Kabsch
    alignment of the rest skeleton to the first target, so a stream that
    OPENS far from the rest orientation starts in the right basin
    instead of burning its few per-frame steps escaping the wrong one).
    ``solver`` is ``"adam"`` (any data term, robust/priors via
    ``**solver_kw``) or ``"lm"`` (verts/joints/ICP terms — converges in
    very few steps on clean targets, the lowest-latency choice). All
    per-frame shapes are static, so every frame after the first reuses
    one compiled program.

    The shape estimate is re-optimized each frame but warm-started, so it
    settles once the subject is established (one identity per stream —
    the same collapse ``fit_sequence`` gets by construction).

    ``frozen_shape`` pins beta for the WHOLE stream (the specialization
    split's tracking mode, ``models.core.specialize``): every frame
    solves pose only — 48 free columns instead of 58 on the LM path —
    and ``TrackState.shape`` carries the constant. The right mode once
    the subject's betas are known (a calibration fit, an enrolled user);
    with the true betas the per-frame solves reach the same optimum as
    the free-shape solve (tests/test_specialize.py).

    ``init_pose`` seeds the warm start from a KNOWN pose instead of the
    rest pose — a resumed stream (serving/streams.py carries the last
    converged pose across a session re-open) or any caller with a prior
    estimate. The seed IS the warm start, so the frame-0 closed-form
    Kabsch alignment is skipped (``TrackState.frame`` starts at 1):
    re-seeding from the first target would throw away exactly the
    continuity the caller is passing in.

    ``deadline_s``/``retries`` opt every frame's solve into SUPERVISED
    execution (``runtime.supervise.supervised_call``): a live tracker
    is exactly the long-running device loop a tunnel drop wedges
    forever (the C-level RPC no signal clears), so each frame's device
    work is bounded by the deadline, transient failures get bounded
    retries with backoff, and a terminal failure raises
    (``RetriesExhausted``) WITHOUT corrupting ``state`` — the caller
    keeps the last good warm start and can resume the stream after the
    outage. Default (both unset): the plain direct call, zero overhead.
    """
    if solver not in ("adam", "lm"):
        raise ValueError(f"solver must be 'adam' or 'lm', got {solver!r}")
    # (fit_trans works with both solvers since LM grew its translation
    # DOF — round 5; each branch below warm-starts it.)
    if solver == "lm" and solver_kw.get("self_penetration_weight"):
        # Fail at build time — not as a TypeError out of the first
        # frame's solve.
        raise ValueError("self_penetration_weight requires solver='adam' "
                         "(LM's GN residual has no hinge term)")
    if solver == "lm" and solver_kw.get("joint_limits") is not None:
        raise ValueError("joint_limits requires solver='adam' (the limit "
                         "hinge is a first-order energy term)")
    if solver_kw.get("pose_space", "aa") != "aa":
        # The tracker's whole mechanism is the decoded-pose warm start
        # ({"pose": ...} each frame) — structurally incompatible with a
        # coefficient parameterization. Fail at build time with the why,
        # not as an init-keys error out of the first frame's trace
        # (fit_restarts guards the same way).
        raise ValueError(
            "make_tracker warm-starts the decoded pose each frame; "
            f"pose_space must stay 'aa', got "
            f"{solver_kw['pose_space']!r}"
        )
    if solver == "adam" and solver_kw.get("self_penetration_weight"):
        # Build the [V, V] part-adjacency mask ONCE for the stream — the
        # per-frame path must not redo the O(V^2) host build + transfer
        # every frame (prepare_self_pen skips the rebuild when given).
        from mano_hand_tpu.fitting import objectives

        solver_kw.setdefault("_self_pen_mask", objectives.self_penetration_mask(
            params, solver_kw.get("self_penetration_radius", 0.004)
        ))
    dtype = params.v_template.dtype
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]
    if frozen_shape is not None:
        frozen_shape = jnp.asarray(frozen_shape, dtype).reshape(n_shape)
    if init_pose is not None:
        init_pose = jnp.asarray(init_pose, dtype).reshape(n_joints, 3)
    state0 = TrackState(
        pose=(jnp.zeros((n_joints, 3), dtype) if init_pose is None
              else init_pose),
        shape=(jnp.zeros((n_shape,), dtype) if frozen_shape is None
               else frozen_shape),
        trans=jnp.zeros((3,), dtype) if fit_trans else None,
        # A caller-seeded pose IS the warm start: frame=1 skips the
        # frame-0 Kabsch re-seed, which would overwrite it.
        frame=0 if init_pose is None else 1,
    )

    def track_step(state: TrackState, target) -> Tuple[TrackState, object]:
        target = jnp.asarray(target, dtype)
        pose0 = state.pose
        trans0 = state.trans
        if (state.frame == 0 and data_term in ("verts", "joints")
                and target.ndim == 2 and target.shape[-1] == 3):
            # Closed-form first-frame seed (one SVD; `frame` is a Python
            # int, so this branch never enters a trace): a stream that
            # OPENS far from the rest orientation starts in the right
            # basin instead of burning its few per-frame steps escaping
            # the wrong one.
            from mano_hand_tpu.fitting.initialize import (
                initialize_from_joints, initialize_from_verts,
            )

            try:
                seed = (initialize_from_joints(
                            params, target,
                            solver_kw.get("tip_vertex_ids"),
                            solver_kw.get("keypoint_order", "mano"))
                        if data_term == "joints"
                        else initialize_from_verts(params, target))
                pose0 = seed["pose"].astype(dtype)
                if fit_trans:
                    # The rotation seed only lands in the right basin
                    # TOGETHER with its pivot-compensating translation.
                    trans0 = seed["trans"].astype(dtype)
            except ValueError:
                pass   # row-count mismatch etc.: keep the rest seed
        init = {"pose": pose0}
        if frozen_shape is None:
            # Free-shape mode warm-starts beta; in frozen mode there is
            # no such parameter to seed (the solvers would reject it).
            init["shape"] = state.shape
        if fit_trans:
            init["trans"] = trans0
        def solve():
            if solver == "lm":
                return lm_mod.fit_lm(
                    params, target, n_steps=n_steps, data_term=data_term,
                    fit_trans=fit_trans, init=init,
                    frozen_shape=frozen_shape, **solver_kw,
                )
            return solvers.fit(
                params, target, n_steps=n_steps, lr=lr,
                data_term=data_term, camera=camera,
                fit_trans=fit_trans,
                shape_prior_weight=shape_prior_weight,
                init=init, frozen_shape=frozen_shape, **solver_kw,
            )

        if deadline_s is not None or retries:
            from mano_hand_tpu.runtime.supervise import supervised_call

            # block_until_ready INSIDE the supervised window — the hang
            # class lives in the device work, not the Python dispatch.
            res = supervised_call(
                lambda: jax.block_until_ready(solve()),
                deadline_s=deadline_s, retries=retries,
                name=f"track-step-{solver}")
        else:
            res = solve()
        new_state = TrackState(
            pose=res.pose,
            shape=res.shape,
            trans=getattr(res, "trans", None),
            frame=state.frame + 1,
        )
        return new_state, res

    return state0, track_step


def make_hands_tracker(
    stacked: ManoParams,          # core.stack_params(left, right)
    n_steps: int = 10,
    data_term: str = "joints",
    lr: float = 0.02,
    fit_trans: bool = True,
    shape_prior_weight: float = 1e-3,
    camera=None,
    **solver_kw,
) -> Tuple[TrackState, Callable]:
    """Streaming TWO-hand tracker over ``fit_hands`` (interacting hands).

    Same contract as ``make_tracker`` but the state carries both hands
    ([2, ...] leaves) and each frame solves them jointly — shared camera
    for 2D terms, and the inter-penetration repulsion
    (``repulsion_weight`` via ``**solver_kw``) keeps warm-started
    surfaces from drifting through each other during close interaction,
    which is exactly when per-hand trackers fail. ``fit_trans`` defaults
    ON: real two-hand observations are never both origin-centered.
    """
    import inspect

    from mano_hand_tpu.fitting import hands as hands_mod

    # Validate pass-through kwargs at BUILD time (same policy as
    # make_tracker's explicit checks): an unsupported option must not
    # surface as a TypeError out of the first live frame's solve. Names
    # the wrapper itself supplies are just as invalid in solver_kw —
    # they would collide as "multiple values for argument" at frame 1.
    allowed = set(inspect.signature(hands_mod.fit_hands).parameters) - {
        "stacked", "targets", "n_steps", "lr", "data_term", "camera",
        "fit_trans", "shape_prior_weight", "init",
    }
    unknown = set(solver_kw) - allowed
    if unknown:
        raise ValueError(
            f"make_hands_tracker got options it cannot pass to fit_hands: "
            f"{sorted(unknown)} (tracker-managed arguments like 'init' are "
            "set per frame; self_penetration_*/ICP options are single-hand "
            "fit/fit_lm features)"
        )
    dtype = stacked.v_template.dtype
    n_joints = stacked.j_regressor.shape[-2]
    n_shape = stacked.shape_basis.shape[-1]
    state0 = TrackState(
        pose=jnp.zeros((2, n_joints, 3), dtype),
        shape=jnp.zeros((2, n_shape), dtype),
        trans=jnp.zeros((2, 3), dtype) if fit_trans else None,
        frame=0,
    )

    def track_step(state: TrackState, target) -> Tuple[TrackState, object]:
        target = jnp.asarray(target, dtype)
        pose0, trans0 = state.pose, state.trans
        if (state.frame == 0 and data_term in ("verts", "joints")
                and target.ndim == 3 and target.shape[0] == 2
                and target.shape[-1] == 3):
            # Same frame-0 closed-form seed as make_tracker, per hand
            # (each hand's rest skeleton differs — unstack the pytree).
            from mano_hand_tpu.fitting.initialize import (
                initialize_from_joints, initialize_from_verts,
            )

            try:
                seeds = []
                for h in range(2):
                    prm = jax.tree_util.tree_map(lambda x: x[h], stacked)
                    seeds.append(
                        initialize_from_joints(
                            prm, target[h],
                            solver_kw.get("tip_vertex_ids"),
                            solver_kw.get("keypoint_order", "mano"))
                        if data_term == "joints"
                        else initialize_from_verts(prm, target[h]))
                pose0 = jnp.stack(
                    [s["pose"] for s in seeds]).astype(dtype)
                if fit_trans:
                    trans0 = jnp.stack(
                        [s["trans"] for s in seeds]).astype(dtype)
            except ValueError:
                pass   # row-count mismatch etc.: keep the rest seed
        init = {"pose": pose0, "shape": state.shape}
        if fit_trans:
            init["trans"] = trans0
        res = hands_mod.fit_hands(
            stacked, target, n_steps=n_steps, lr=lr, data_term=data_term,
            camera=camera, fit_trans=fit_trans,
            shape_prior_weight=shape_prior_weight, init=init, **solver_kw,
        )
        new_state = TrackState(
            pose=res.pose,
            shape=res.shape,
            trans=res.trans,
            frame=state.frame + 1,
        )
        return new_state, res

    return state0, track_step


def track_clip(
    params: ManoParams,
    targets,                      # [T, rows, coords]
    **tracker_kw,
):
    """Convenience: run the streaming tracker over a pre-recorded clip.

    Returns ``(poses [T, J, 3], shapes [T, S], final_state)``. Unlike
    ``fit_sequence`` this is strictly causal — frame t sees only frames
    <= t — which is exactly the online constraint; on smooth clips the
    end-of-clip pose lands within tolerance of the joint solve
    (tests/test_tracking.py).
    """
    targets = jnp.asarray(targets)
    state, step = make_tracker(params, **tracker_kw)
    return _run_clip(state, step, targets)


def _run_clip(state, step, targets):
    """The one frame-loop body shared by both clip conveniences."""
    poses, shapes = [], []
    for t in range(targets.shape[0]):
        state, _ = step(state, targets[t])
        poses.append(state.pose)
        shapes.append(state.shape)
    return jnp.stack(poses), jnp.stack(shapes), state


def track_hands_clip(
    stacked: ManoParams,
    targets,                      # [T, 2, rows, coords] frame-major
    **tracker_kw,
):
    """Two-hand ``track_clip``: causal streaming over a recorded clip.

    Returns ``(poses [T, 2, J, 3], shapes [T, 2, S], final_state)`` —
    the online counterpart of ``fit_hands_sequence`` (which solves the
    clip jointly, acausally).
    """
    targets = jnp.asarray(targets)
    if tracker_kw.get("data_term") == "silhouette":
        # Mask clips: [T, H, W] combined or [T, 2, H, W] per-hand — the
        # same layouts fit_hands accepts per frame (each frame slice is
        # [H, W] / [2, H, W]). mask_layout resolves the one ambiguous
        # shape exactly as in fit_hands_sequence (the shared validator).
        from mano_hand_tpu.fitting import solvers

        solvers.check_hands_silhouette(
            tracker_kw.get("camera"), tracker_kw.get("robust", "none"),
            targets, seq=True, fn_name="track_hands_clip",
            mask_layout=tracker_kw.pop("mask_layout", "auto"),
        )
    elif targets.ndim != 4 or targets.shape[1] != 2:
        raise ValueError(
            f"targets must be [T, 2, rows, coords], got {targets.shape}"
        )
    state, step = make_hands_tracker(stacked, **tracker_kw)
    return _run_clip(state, step, targets)
