"""Multi-restart fitting: escape local minima by solving many inits at once.

The ill-posed data terms (sparse joints, 2D keypoints, partial clouds —
the ones docs/api.md routes at the priors) are also MULTI-MODAL: a
single gradient or GN descent from the zero pose can lock into the wrong
basin (fingers matched to the wrong fingers, 180-degree wrist flips).
The classic fix is restarts, and the TPU shape of restarts is free
parallelism: R anatomically plausible inits (``core.sample_poses`` —
z ~ N(0, I) through the asset's PCA basis, not raw axis-angle noise)
solved as ONE batched program — the same vmap the solvers already use
for batched problems — then argmin over final losses. Wall-clock is one
fit, not R fits.

The reference has no fitting at all; restarts are frontier surface on
top of BASELINE.json config 4.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core


def fit_restarts(
    params: ManoParams,
    target: jnp.ndarray,        # [V|J|N, 3] | [J, 2] | [H, W] mask
                                #   | [n_views, H, W] — ONE problem
    n_restarts: int = 8,
    key=0,
    solver: str = "adam",       # "adam" (fitting.fit) | "lm" (fit_lm)
    pca_scale: float = 1.0,
    global_rot_scale: float = 0.5,
    component_vars: Optional[jnp.ndarray] = None,
    include_zero: bool = True,
    include_kabsch: bool = True,
    **solver_kw,
):
    """Solve one fitting problem from ``n_restarts`` inits; keep the best.

    Returns ``(best, restart_losses)``: ``best`` is the single-problem
    ``FitResult``/``LMResult`` of the winning restart, ``restart_losses``
    the final loss per restart (spread = how multi-modal the problem
    was; all-equal = restarts were unnecessary). ``include_zero`` keeps
    the zero pose as restart 0, so the result is never worse than the
    plain single fit. ``solver_kw`` passes through to ``fitting.fit`` /
    ``fitting.fit_lm`` (data_term, priors, camera, fit_trans, ...).

    ``include_kabsch`` (on by default) additionally seeds one restart
    from the CLOSED-FORM rigid alignment of the rest model to the
    target (``fitting.initialize_from_joints``/``_verts`` — applicable
    to the correspondence terms "verts"/"joints"; silently inapplicable
    elsewhere): on far-rotated problems that deterministic seed is in
    the right basin by construction, while sampled restarts only cover
    rotation space with luck.

    Restarts own the warm start, and sampled inits are axis-angle poses
    — ``init=`` and non-default ``pose_space`` are rejected rather than
    silently dropped.
    """
    from mano_hand_tpu.fitting import lm as lm_mod
    from mano_hand_tpu.fitting import solvers

    if solver not in ("adam", "lm"):
        raise ValueError(f"solver must be 'adam' or 'lm', got {solver!r}")
    if "init" in solver_kw:
        raise ValueError("fit_restarts owns init; remove the init kwarg")
    if solver_kw.get("pose_space", "aa") != "aa":
        raise ValueError(
            "fit_restarts samples axis-angle inits; pose_space must stay "
            f"'aa', got {solver_kw['pose_space']!r}"
        )
    if n_restarts < 1:
        raise ValueError(f"n_restarts must be >= 1, got {n_restarts}")
    target = jnp.asarray(target, params.v_template.dtype)
    want_ndim = 2
    if solver_kw.get("data_term") == "silhouette":
        # Masks are [H, W] per problem — or [n_views, H, W] with a
        # camera list (the multi-view term); restarts matter here
        # because outlines are the most multi-modal data of all.
        want_ndim = solvers.check_silhouette_views(
            solver_kw.get("camera"), target, "fit_restarts"
        )
    if target.ndim != want_ndim:
        raise ValueError(
            "fit_restarts solves ONE problem (target [rows, 2|3], or an "
            "[H, W] / [n_views, H, W] mask for the silhouette term); for "
            f"independent batches call the solver directly, got shape "
            f"{target.shape}"
        )

    dtype = params.v_template.dtype
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]

    kabsch = None
    if include_kabsch and target.shape[-1] == 3:
        from mano_hand_tpu.fitting.initialize import (
            initialize_from_joints, initialize_from_verts,
        )

        dt = solver_kw.get("data_term", "verts")
        if dt == "joints":
            kabsch = initialize_from_joints(
                params, target,
                tip_vertex_ids=solver_kw.get("tip_vertex_ids"),
                keypoint_order=solver_kw.get("keypoint_order", "mano"),
            )
        elif dt == "verts":
            kabsch = initialize_from_verts(params, target)

    n_sampled = n_restarts - int(include_zero) - int(kabsch is not None)
    if n_sampled < 0:
        # No row left for the Kabsch seed (e.g. the long-standing
        # n_restarts=1 call): drop it rather than break the documented
        # never-worse-than-a-plain-fit contract.
        kabsch = None
        n_sampled = n_restarts - int(include_zero)
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)
    poses = []
    if include_zero:
        poses.append(jnp.zeros((1, n_joints, 3), dtype))
    if kabsch is not None:
        poses.append(kabsch["pose"][None].astype(dtype))
    if n_sampled:
        poses.append(core.sample_poses(
            params, key, n_sampled,
            pca_scale=pca_scale, global_rot_scale=global_rot_scale,
            component_vars=component_vars,
        ).astype(dtype))
    init = {
        "pose": jnp.concatenate(poses, axis=0),
        "shape": jnp.zeros((n_restarts, n_shape), dtype),
    }
    # Both solvers carry the trans DOF now (fit_lm grew it in round 5);
    # the Kabsch rotation row only lands in the right basin TOGETHER
    # with its pivot-compensating translation.
    if solver_kw.get("fit_trans"):
        trans = jnp.zeros((n_restarts, 3), dtype)
        if kabsch is not None:
            # The Kabsch row gets its own translation seed too.
            trans = trans.at[int(include_zero)].set(
                kabsch["trans"].astype(dtype))
        init["trans"] = trans

    tiled = jnp.broadcast_to(target, (n_restarts, *target.shape))
    if solver == "adam":
        result = solvers.fit(params, tiled, init=init, **solver_kw)
    else:
        result = lm_mod.fit_lm(params, tiled, init=init, **solver_kw)
    losses = result.final_loss
    # A wild sampled init can diverge to NaN under adam; argmin's NaN
    # semantics would then SELECT it (np.argmin([nan, .1]) == 0) and
    # break the include_zero never-worse guarantee. NaN = worst.
    i = int(jnp.argmin(jnp.where(jnp.isnan(losses), jnp.inf, losses)))
    best = type(result)(
        *(None if leaf is None else leaf[i] for leaf in result)
    )
    return best, losses
