"""Levenberg-Marquardt pose/shape fitting (second-order inverse MANO).

The reference has no fitting at all; BASELINE.json config 4 mandates
gradient-based recovery, and first-order Adam (solvers.py) covers it. This
module adds the solver of choice for small-parameter mesh fitting:
damped Gauss-Newton over the ~58-dim (pose, shape) space.

TPU-first shape of the problem: the residual Jacobian [V*3, P] is
assembled ANALYTICALLY by default (AD differentiates only the 16-joint
chain; the vertex Jacobian is bounded einsums — fitting/jacobian.py;
``jacobian="ad"`` keeps the plain ``jax.jacfwd`` replay as a
cross-check), the normal matrix JtJ is a [P, P] MXU matmul, and the
solve is a tiny batched LU — all inside one ``lax.scan`` step with
branch-free accept/reject damping (``jnp.where``, no host control
flow). A batch of independent problems vmaps over the scan.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from mano_hand_tpu import ops
from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.fitting import jacobian as jacobian_mod
from mano_hand_tpu.fitting import objectives, solvers
from mano_hand_tpu.models import core

# Data terms with per-step ICP correspondence assignment.
_ICP_TERMS = ("points", "point_to_plane")


class LMResult(NamedTuple):
    pose: jnp.ndarray          # [..., 16, 3] recovered axis-angle pose
    shape: jnp.ndarray         # [..., S] recovered shape coefficients
    final_loss: jnp.ndarray    # [...] final mean-squared residual over ALL
    #   rows: vertex or joint rows per data_term, plus the Tikhonov shape
    #   rows — not directly comparable across data terms or to the Adam
    #   path's data loss.
    loss_history: jnp.ndarray  # [..., n_steps]
    damping_history: jnp.ndarray  # [..., n_steps] lambda per step
    trans: Optional[jnp.ndarray] = None  # [..., 3] when fit_trans


def _fit_single(
    params: ManoParams,
    target_verts: jnp.ndarray,  # [V, 3] | [J, 3] | [N, 3] (data_term)
    *,
    n_steps: int,
    init_damping: float,
    damping_up: float,
    damping_down: float,
    shape_weight: float,
    data_term: str = "verts",
    init: Optional[dict] = None,
    trim_fraction: float = 0.0,
    robust_weights: str = "none",
    robust_scale: Optional[float] = None,
    tips=None,
    keypoint_order: str = "mano",
    jacobian: str = "analytic",
    normal_eq: str = "high",
    pose_space: str = "aa",
    n_pca: int = 45,
    fit_trans: bool = False,
    frozen_shape: Optional[jnp.ndarray] = None,  # [S]: pose-only GN
) -> LMResult:
    dtype = params.v_template.dtype
    # One-pass bf16 normal equations (roadmap candidate for 200+ steps/s):
    # JtJ/Jtr are the step's largest matmuls ([R~2344, 58] contractions);
    # Precision.DEFAULT runs them in one MXU pass instead of HIGH's three.
    # J entries are O(1) and accumulation stays f32, and the damped
    # accept/reject loop tolerates direction noise (same argument as the
    # LU-vs-Cholesky note below) — but numerics are only trusted measured
    # ON-CHIP, so the default stays "high" until the bench ratio says
    # otherwise.
    ne_precision = (core.DEFAULT_PRECISION if normal_eq == "high"
                    else jax.lax.Precision.DEFAULT)
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]

    # Frozen-betas (pose-only) mode, the specialization split's tracking
    # counterpart (models/core.py:specialize): beta is a known per-subject
    # constant, so it leaves the parameter vector entirely — 48 free
    # columns instead of 58 in axis-angle — and re-enters through the
    # unravel below, exactly like the PCA decode does.
    freeze = frozen_shape is not None
    if freeze:
        frozen_shape = jnp.asarray(frozen_shape, dtype).reshape(n_shape)

    if pose_space == "pca":
        # Same parameterization keys as the Adam solvers' PCA mode
        # (solvers._pose_shapes): truncated finger-pose coefficients +
        # a free global-rotation row. GN in 3+n_pca+S dims — the normal
        # matrix shrinks quadratically with n_pca.
        theta0 = {
            "global_rot": jnp.zeros((3,), dtype),
            "pca": jnp.zeros((n_pca,), dtype),
        }
    else:
        theta0 = {
            "pose": jnp.zeros((n_joints, 3), dtype),
        }
    if not freeze:
        theta0["shape"] = jnp.zeros((n_shape,), dtype)
    if fit_trans:
        # Global translation DOF (same key as solvers.fit): predictions
        # are rigidly shifted, so its residual Jacobian is an identity
        # block per 3D row — added explicitly on the analytic path, and
        # free on the AD path.
        theta0["trans"] = jnp.zeros((3,), dtype)
    if init:
        # Warm start (same contract as solvers.fit): ICP in particular
        # needs one — nearest-neighbor assignments from the rest pose
        # lock in a local basin.
        unknown = set(init) - set(theta0)
        if unknown:
            raise ValueError(
                f"init keys {sorted(unknown)} not in {sorted(theta0)}"
            )
        for k, v in init.items():
            v = jnp.asarray(v, dtype)
            if v.shape != theta0[k].shape:
                raise ValueError(
                    f"init[{k!r}] shape {v.shape} != {theta0[k].shape}"
                )
            theta0[k] = v
    flat0, unravel_raw = ravel_pytree(theta0)
    if pose_space == "pca" or freeze:
        # The decode — and, in frozen mode, the constant beta injection —
        # is part of the unravel, so every consumer — the residual, the
        # Tikhonov rows, AND jacobian.forward_with_jacobian (whose
        # jacfwd of the tiny joint chain then carries
        # d pose/d (global_rot, pca) automatically, decode_pca being
        # linear, and sees exact-zero d_shape for a frozen beta) — sees
        # the familiar {"pose", "shape"} dict with zero mode-specific
        # code anywhere downstream.
        def unravel(f):
            raw = unravel_raw(f)
            pose = (core.decode_pca(params, raw["pca"],
                                    global_rot=raw["global_rot"])
                    if pose_space == "pca" else raw["pose"])
            return {"pose": pose,
                    "shape": frozen_shape if freeze else raw["shape"]}
    else:
        unravel = unravel_raw
    n_params = flat0.shape[0]
    target = target_verts.reshape(-1)
    # ravel_pytree flattens dict leaves in sorted-key order; the trans
    # columns' flat range falls out of the same ordering.
    if fit_trans:
        off = 0
        for k in sorted(theta0):
            size = int(theta0[k].size)
            if k == "trans":
                trans_sl = slice(off, off + size)
            off += size

    def trans_of(flat):
        return unravel_raw(flat)["trans"] if fit_trans else None

    def values_of(flat):
        """(verts, posed_joints) by the active backend's estimator.

        One estimator per run: the accept test compares losses of the
        current iterate against a candidate, so both must come from the
        SAME numeric path (the fused and staged forwards differ by
        ~float32 rounding — enough to flip accepts at the floor).
        """
        if jacobian == "analytic":
            verts, pj = jacobian_mod.forward_values(params, unravel, flat)
        else:
            p = unravel(flat)
            # Fused-basis forward: under jacfwd the blend stage's 58
            # tangent columns batch into ONE [P, S+P] x [S+P, V*3] MXU
            # matmul instead of 58 replays of the staged contractions.
            out = core.forward_fused(params, p["pose"], p["shape"])
            verts, pj = out.verts, out.posed_joints
        if fit_trans:
            tr = trans_of(flat)
            verts, pj = verts + tr, pj + tr
        return verts, pj

    def rows_from(verts, posed_joints, p_shape, corr):
        """THE per-data-term residual row construction — shared by the
        AD path (under jacfwd), the analytic path, and scoring, so the
        backends cannot drift apart."""
        if data_term == "points":
            # Point-to-point ICP residual under the step's FROZEN
            # correspondence assignment (GN never differentiates the
            # argmin, matching classic ICP). Trim weights zero the rows
            # of rejected points — residual shape stays static.
            idx, w = corr
            d = verts[idx] - target_verts.reshape(-1, 3)
            res = (d * w[:, None]).reshape(-1)
        elif data_term == "point_to_plane":
            # Point-to-plane: signed distance along the step's FROZEN
            # surface normal — one row per point. Sliding tangentially
            # along the surface is free, which is why this converges in
            # fewer steps than point-to-point on smooth regions (the
            # classic Chen & Medioni refinement).
            idx, normals, w = corr
            d = verts[idx] - target_verts.reshape(-1, 3)
            res = jnp.sum(d * normals, axis=-1) * w
        else:
            pred = (
                verts if data_term == "verts"
                else core.select_keypoints(verts, posed_joints, tips,
                                           keypoint_order)
            )
            res = pred.reshape(-1) - target
        # Tikhonov rows keep beta near 0 when vertices underdetermine it.
        # Always present (zero rows when the traced weight is 0, which is
        # mathematically a no-op on JtJ/Jtr) so the residual shape — and
        # therefore the jit cache key — is weight-independent.
        return jnp.concatenate([res, shape_weight * p_shape])

    def residual(flat, corr=None):
        verts, posed_joints = values_of(flat)
        return rows_from(verts, posed_joints, unravel(flat)["shape"], corr)

    def assignment(flat):
        verts = values_of(flat)[0]
        points = target_verts.reshape(-1, 3)
        idx = objectives.nearest_vertex_idx(verts, points)
        # Trimmed ICP: reject the worst trim_fraction of points THIS step
        # (sensor outliers, non-hand foreground) — the standard trimming
        # since the GN residual has no robustifier. The quantile is over
        # the frozen assignment's distances; trim_fraction=0 keeps all.
        d2 = jnp.sum((verts[idx] - points) ** 2, axis=-1)
        thresh = jnp.quantile(d2, 1.0 - trim_fraction)
        w = (d2 <= thresh).astype(dtype)
        if robust_weights != "none":
            # Soft robust reweighting (IRLS): per-point weights from the
            # frozen assignment's distances, so graded outliers are
            # downweighted in proportion instead of the all-or-nothing
            # trim cut. Residual rows scale by sqrt(w_irls) — the GN
            # normal equations then see exactly the IRLS weights.
            d = jnp.sqrt(jnp.maximum(d2, 1e-18))
            if robust_scale is None:
                # Robust sigma from the median absolute distance (the
                # MAD-to-sigma constant); floored to keep late-stage
                # near-perfect fits from dividing by ~0.
                sigma = jnp.maximum(1.4826 * jnp.median(d), 1e-6)
            else:
                sigma = jnp.asarray(robust_scale, dtype)
            if robust_weights == "tukey":
                u = d / (4.685 * sigma)
                w_irls = jnp.where(u < 1.0, (1.0 - u * u) ** 2, 0.0)
            else:  # "geman" (Geman-McClure)
                u2 = (d / sigma) ** 2
                w_irls = 1.0 / (1.0 + u2) ** 2
            w = w * jnp.sqrt(w_irls).astype(dtype)
        if data_term == "point_to_plane":
            # Normals of the CURRENT surface at the assigned vertices,
            # frozen with the assignment for this step.
            normals = ops.vertex_normals(verts, params.faces)[idx]
            return idx, normals, w
        return idx, w

    def analytic_res_jac(flat, corr):
        """Residual + exact Jacobian without the 58-column forward replay.

        ``jax.jacfwd`` of the full residual materializes [P, V, 3, 3]
        tangent slabs and is bandwidth-bound (7.5 of the 9.4 ms step at
        b=256 on-chip); here AD touches only the V-free joint chain and
        the vertex Jacobian is three [V, 3, P]-bounded einsums
        (fitting/jacobian.py). Rows match ``residual`` exactly.
        """
        fj = jacobian_mod.forward_with_jacobian(params, unravel, flat,
                                                shape_frozen=freeze)
        verts, pj = fj.verts, fj.posed_joints
        if fit_trans:
            tr = trans_of(flat)
            verts, pj = verts + tr, pj + tr
        res = rows_from(verts, pj, unravel(flat)["shape"], corr)
        eye3 = jnp.eye(3, dtype=dtype)
        if data_term == "points":
            idx, w = corr
            jac = (fj.verts_jac[idx] * w[:, None, None]).reshape(
                -1, n_params
            )
            # d res/d trans for w-scaled point rows: w ⊗ I3. The small
            # chain never sees trans, so its jacfwd columns there are
            # zero — the identity block is the whole derivative.
            if fit_trans:
                blk = (w[:, None, None] * eye3).reshape(-1, 3)
                jac = jac.at[:, trans_sl].add(blk)
        elif data_term == "point_to_plane":
            idx, normals, w = corr
            jac = w[:, None] * jnp.einsum(
                "nc,ncp->np", normals, fj.verts_jac[idx],
                precision=core.DEFAULT_PRECISION,
            )
            if fit_trans:  # d(n·(x+t-p))/dt = n, w-scaled
                jac = jac.at[:, trans_sl].add(normals * w[:, None])
        elif data_term == "verts":
            jac = fj.verts_jac.reshape(-1, n_params)
            if fit_trans:
                jac = jac.at[:, trans_sl].add(
                    jnp.tile(eye3, (verts.shape[0], 1)))
        else:  # joints (optionally extended with fingertips)
            _, kp_jac = jacobian_mod.keypoint_jacobian(
                fj, tips, keypoint_order
            )
            jac = kp_jac.reshape(-1, n_params)
            if fit_trans:  # every keypoint (joint or tip) translates
                jac = jac.at[:, trans_sl].add(
                    jnp.tile(eye3, (kp_jac.shape[0], 1)))
        jac = jnp.concatenate([jac, shape_weight * fj.shape_jac])
        return res, jac

    def loss_of(flat):
        # Fresh assignment when scoring (ICP's true objective is the
        # chamfer, not the residual under a stale correspondence).
        corr = (assignment(flat) if data_term in _ICP_TERMS else None)
        r = residual(flat, corr)
        return (r * r).mean()

    def step(carry, _):
        flat, damping = carry
        corr = (assignment(flat) if data_term in _ICP_TERMS else None)
        if jacobian == "analytic":
            r, jac = analytic_res_jac(flat, corr)
        else:
            res_fn = lambda f: residual(f, corr)  # noqa: E731
            r = res_fn(flat)
            jac = jax.jacfwd(res_fn)(flat)             # [R, P]
        jtj = jnp.einsum(
            "rp,rq->pq", jac, jac, precision=ne_precision
        )                                              # [P, P] (MXU)
        jtr = jnp.einsum(
            "rp,r->p", jac, r, precision=ne_precision
        )
        a = jtj + damping * jnp.diag(jnp.diag(jtj)) \
            + 1e-9 * jnp.eye(n_params, dtype=dtype)
        # Batched LU, not Cholesky: under vmap, cho_factor/cho_solve
        # lowers to a per-problem triangular pipeline that measured 8x
        # slower than the batched LU kernel at [256, 58, 58] on a v5e
        # chip (0.151 vs 0.019 ms — bench_results/probe_solve.py). The
        # ~1e-4-relative direction difference is noise to a damped
        # accept/reject loop (convergence tests unchanged).
        delta = jnp.linalg.solve(a, jtr)
        candidate = flat - delta
        old = (r * r).mean()
        new = loss_of(candidate)
        accept = new < old
        flat = jnp.where(accept, candidate, flat)
        damping = jnp.where(
            accept, damping * damping_down, damping * damping_up
        )
        damping = jnp.clip(damping, 1e-10, 1e8)
        return (flat, damping), (jnp.where(accept, new, old), damping)

    (flat_fin, _), (history, dhist) = jax.lax.scan(
        step, (flat0, jnp.asarray(init_damping, dtype)), None, length=n_steps
    )
    p_fin = unravel(flat_fin)
    return LMResult(
        pose=p_fin["pose"],
        shape=p_fin["shape"],
        final_loss=loss_of(flat_fin),
        loss_history=history,
        damping_history=dhist,
        trans=trans_of(flat_fin),
    )


@solvers.normalize_tips_kwarg
@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "data_term", "trim_fraction",
                     "robust_weights", "robust_scale", "tip_vertex_ids",
                     "keypoint_order", "jacobian", "normal_eq",
                     "pose_space", "n_pca", "fit_trans"),
)
def fit_lm(
    params: ManoParams,
    target_verts: jnp.ndarray,  # [V, 3] or [B, V, 3] ([J, 3] joints;
                                # [N, 3] points)
    n_steps: int = 30,
    init_damping: float = 1e-3,
    damping_up: float = 10.0,
    damping_down: float = 0.3,
    shape_weight: float = 0.0,
    data_term: str = "verts",
    init: Optional[dict] = None,
    trim_fraction: float = 0.0,
    robust_weights: str = "none",
    robust_scale: Optional[float] = None,
    tip_vertex_ids=None,         # None | "smplx" | "manopth" | vertex ids
    keypoint_order: str = "mano",  # "mano" | "openpose"
    jacobian: str = "analytic",  # "analytic" | "ad"
    normal_eq: str = "high",     # "high" | "bf16"
    pose_space: str = "aa",      # "aa" | "pca"
    n_pca: int = 45,
    fit_trans: bool = False,
    frozen_shape: Optional[jnp.ndarray] = None,  # [S] or [B, S]
) -> LMResult:
    """Recover (pose, shape) by damped Gauss-Newton; batch via vmap.

    Converges to numerical floor in tens of steps where Adam needs
    hundreds — the preferred solver when targets are clean meshes.
    ``data_term="joints"`` fits 16 posed joints instead (a [48+S]-row
    residual — even cheaper per step); 16 joints underdetermine shape,
    so pair it with a nonzero ``shape_weight``. ``tip_vertex_ids``
    extends the joints term with fingertip vertex picks (the standard
    21-keypoint set — ``"smplx"``/``"manopth"`` conventions or explicit
    ids; ``keypoint_order="openpose"`` for OpenPose/FreiHAND-ordered
    targets): tips observe the distal phalanx rotations that the 16
    skeleton joints miss entirely, so 21-point LM recovers full finger
    articulation where 16-point LM cannot. ``data_term="points"``
    is true point-to-point ICP: per step, nearest-vertex correspondences
    are re-assigned and a GN solve runs on the frozen assignment —
    registration to an unstructured [N, 3] scan in ~10 steps; warm-start
    via ``init`` (assignments from the rest pose lock in a local basin).
    ``trim_fraction`` (ICP terms only) rejects that fraction of the
    worst-matching points EACH step (re-evaluated with the assignment) —
    trimmed ICP, the standard outlier defense since the GN residual has
    no robustifier. ``robust_weights`` ("tukey" | "geman", ICP terms
    only) instead downweights points CONTINUOUSLY by their frozen-
    assignment distance (IRLS weights on the GN rows): the right tool
    for graded (non-binary) noise, where any hard trim cut either keeps
    bad points or discards good ones; ``robust_scale`` pins the scale
    (meters), default auto from the per-step median distance. Both
    compose (trim the catastrophic, reweight the rest).
    ``data_term="point_to_plane"`` is the Chen & Medioni
    refinement:
    residuals are signed distances along the current surface normals
    (one row per point), letting points slide freely along the surface.
    Use it as the POLISH stage after a point-to-point fit — plane
    residuals alone leave the tangential directions unconstrained and
    the registration can drift (measured: 29 mm from a coarse start vs
    0.06 mm as polish). For robust or 2D-projected energies use
    solvers.fit (first-order).

    ``jacobian="analytic"`` (default) assembles the residual Jacobian
    exactly without replaying 58 forward-mode columns through the mesh
    (fitting/jacobian.py): AD differentiates only the 16-joint chain and
    the vertex Jacobian is three bounded einsums — measured 5.5 ms/step
    vs 10.7 for ``"ad"`` at batch 256 on a v5e chip (93 -> 182 steps/s),
    identical convergence (tests/test_jacobian.py). ``"ad"`` keeps the
    plain ``jax.jacfwd`` path as the cross-check.

    ``normal_eq="bf16"`` builds JtJ/Jtr in one bf16 MXU pass instead of
    the model default's three (f32 accumulation; the J entries are O(1)
    so the normal matrix tolerates it the way the LU direction noise
    does). Off by default pending the bench's on-chip convergence-ratio
    measurement (bench config4b records both variants).

    ``pose_space="pca"`` runs GN in the truncated PCA pose space
    (``global_rot [3]`` + ``pca [n_pca]`` + shape — same keys as
    ``solvers.fit``'s PCA mode, reference semantics
    /root/reference/mano_np.py:66-72): the decode folds into the
    parameter unravel, so the analytic Jacobian's joint-chain jacfwd
    carries d pose/d coefficients automatically and the normal matrix
    shrinks quadratically with ``n_pca`` (e.g. 58 -> 25 dims at
    n_pca=12). The natural fit when targets are sparse (joints /
    keypoints) or the pose prior of the PCA space is wanted implicitly;
    returns the DECODED full pose.

    ``fit_trans=True`` adds a global translation DOF (key ``"trans"``,
    as in ``solvers.fit``) — required for registering UNCENTERED scans
    with the ICP terms, where no pose articulation can absorb a rigid
    offset. Its residual Jacobian is an exact identity block per 3D row
    (plane rows: the normal), composable with either pose space;
    ``LMResult.trans`` carries the estimate (None otherwise).

    ``frozen_shape`` pins beta to a KNOWN per-subject value (e.g. the
    betas baked by ``models.core.specialize``) and solves for pose only
    — the specialization split's tracking mode: 48 free columns instead
    of 58 in axis-angle, a [48, 48] normal matrix, and the analytic
    Jacobian skips the shape-basis tangent slab entirely
    (fitting/jacobian.py ``shape_frozen``). Composes with either pose
    space, ``fit_trans``, and every data term; a [B, S] array gives each
    batched problem its own frozen subject. ``LMResult.shape`` returns
    the frozen betas; warm-start ``init`` must not carry a ``"shape"``
    key (there is no such free parameter — the validation names it).
    With fixed true betas it reaches the same optimum as the full
    58-col solve on shape-consistent targets (tests/test_specialize.py).
    """
    if data_term not in ("verts", "joints", "points",
                         "point_to_plane"):
        raise ValueError(
            "fit_lm data_term must be 'verts', 'joints', 'points' or "
            f"'point_to_plane', got {data_term!r}"
        )
    target_verts = jnp.asarray(target_verts, params.v_template.dtype)
    if data_term in _ICP_TERMS and target_verts.shape[-2] == 0:
        raise ValueError("points target cloud is empty ([..., 0, 3])")
    # "joints" is the only keypoint term here (2D/projective energies are
    # the first-order solvers' job); verts/ICP terms reject tip specs.
    tips, _ = solvers.check_keypoint_spec(
        params, data_term, tip_vertex_ids, keypoint_order, target_verts,
        "fit_lm",
    )
    # trim_fraction is static (a config knob), so these validate concretely.
    # jnp.quantile would silently CLAMP an out-of-range fraction — e.g. 1.0
    # keeps only the single nearest point and returns a garbage fit with a
    # tiny loss.
    if not 0.0 <= float(trim_fraction) < 1.0:
        raise ValueError(
            f"trim_fraction must be in [0, 1), got {trim_fraction}"
        )
    if trim_fraction and data_term not in _ICP_TERMS:
        raise ValueError(
            "trim_fraction only applies to the ICP data terms "
            f"{_ICP_TERMS}, got data_term={data_term!r}"
        )
    if robust_weights not in ("none", "tukey", "geman"):
        raise ValueError(
            "robust_weights must be 'none', 'tukey' or 'geman', "
            f"got {robust_weights!r}"
        )
    if robust_weights != "none" and data_term not in _ICP_TERMS:
        raise ValueError(
            "robust_weights only applies to the ICP data terms "
            f"{_ICP_TERMS}, got data_term={data_term!r}"
        )
    if robust_scale is not None and float(robust_scale) <= 0:
        raise ValueError(f"robust_scale must be > 0, got {robust_scale}")
    if jacobian not in ("analytic", "ad"):
        raise ValueError(
            f"jacobian must be 'analytic' or 'ad', got {jacobian!r}"
        )
    if normal_eq not in ("high", "bf16"):
        raise ValueError(
            f"normal_eq must be 'high' or 'bf16', got {normal_eq!r}"
        )
    if pose_space not in ("aa", "pca"):
        raise ValueError(
            "fit_lm pose_space must be 'aa' or 'pca' (6D adds nothing to "
            f"GN — it optimizes rotations via the chain anyway), got "
            f"{pose_space!r}"
        )
    if pose_space == "pca":
        max_pca = params.pca_basis.shape[0]
        if not 1 <= int(n_pca) <= max_pca:
            raise ValueError(
                f"n_pca must be in [1, {max_pca}], got {n_pca}"
            )
    n_shape = params.shape_basis.shape[-1]
    if frozen_shape is not None:
        frozen_shape = jnp.asarray(frozen_shape, params.v_template.dtype)
        if frozen_shape.ndim not in (1, 2) \
                or frozen_shape.shape[-1] != n_shape:
            raise ValueError(
                f"frozen_shape must be [{n_shape}] (or [B, {n_shape}] for "
                f"batched problems), got {frozen_shape.shape}"
            )
    single = functools.partial(
        _fit_single,
        params,
        n_steps=n_steps,
        init_damping=init_damping,
        damping_up=damping_up,
        damping_down=damping_down,
        shape_weight=shape_weight,
        data_term=data_term,
        trim_fraction=trim_fraction,
        robust_weights=robust_weights,
        robust_scale=robust_scale,
        tips=tips,
        keypoint_order=keypoint_order,
        jacobian=jacobian,
        normal_eq=normal_eq,
        pose_space=pose_space,
        n_pca=n_pca,
        fit_trans=fit_trans,
    )
    if target_verts.ndim == 2:
        if frozen_shape is not None and frozen_shape.ndim != 1:
            raise ValueError(
                "single-problem fit_lm takes one frozen_shape [S], got "
                f"{frozen_shape.shape}"
            )
        return single(target_verts, init=init, frozen_shape=frozen_shape)
    # Batched problems: a [B, S] frozen_shape maps per problem (each its
    # own frozen subject); a shared [S] broadcasts via in_axes=None —
    # the target_conf policy applied to the frozen betas.
    fs_axis = None
    if frozen_shape is not None and frozen_shape.ndim == 2:
        if frozen_shape.shape[0] != target_verts.shape[0]:
            raise ValueError(
                f"batched frozen_shape has {frozen_shape.shape[0]} rows "
                f"for {target_verts.shape[0]} problems"
            )
        fs_axis = 0
    if init is not None:
        # Batched warm start: one seed per problem on every init leaf.
        init = {k: jnp.asarray(v, params.v_template.dtype)
                for k, v in init.items()}
        solvers.validate_batched_init(
            init, target_verts.shape[0],
            # LM's theta0 follows the Adam solvers' parameterizations
            # ("aa" or "pca", optional trans, frozen beta dropped) —
            # same shape source, no hand-written mirror.
            solvers._batched_init_shapes(
                pose_space, params.j_regressor.shape[0], n_pca,
                params.shape_basis.shape[-1], fit_trans=fit_trans,
                freeze_shape=frozen_shape is not None,
            ),
            target_verts.shape, "fit_lm",
        )
    return jax.vmap(
        lambda t, i, f: single(t, init=i, frozen_shape=f),
        in_axes=(0, 0 if init else None, fs_axis),
    )(target_verts, init, frozen_shape)


def fit_lm_bucketed(
    params: ManoParams,
    target_verts: jnp.ndarray,   # [B, rows, 3]
    *,
    min_bucket: int = 1,
    max_bucket: int = 1024,
    counters=None,
    init: Optional[dict] = None,
    **kw,
) -> LMResult:
    """``fit_lm`` for many-small-problem streams with ragged batch sizes.

    The serving bucket policy (serving/buckets.py) applied to the GN
    solver — the tracking workload shape: per-frame batches of
    independent problems whose count varies (detections appear and
    drop). The problem batch pads to the nearest power-of-two bucket
    (pad problems repeat problem 0 — live numerics, normal convergence)
    and every leaf of the LMResult is sliced back to the live problems,
    so steady ragged traffic reuses ``log2(max_bucket)`` compiled scan
    programs with zero retraces after warm-up (tests/test_serving.py
    asserts this via ``counters``, a utils.profiling.ServingCounters).
    All ``fit_lm`` kwargs pass through.
    """
    return solvers.bucketed_fit_call(
        fit_lm, params, target_verts, min_bucket=min_bucket,
        max_bucket=max_bucket, counters=counters, init=init,
        fn_name="fit_lm_bucketed", **kw)
