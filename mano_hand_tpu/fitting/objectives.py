"""Fitting objectives and priors.

The reference has no fitting capability at all; BASELINE.json's north star
adds it ("the JAX path is fully differentiable so pose/shape can be
recovered by gradient descent on TPU"). Objectives are pure functions of
(predicted, target) plus optional parameter priors, composable into one
scalar loss for optax.
"""

from __future__ import annotations

import jax.numpy as jnp

from mano_hand_tpu.ops.common import DEFAULT_PRECISION


def _pairwise_sq_dist(pred_verts: jnp.ndarray,    # [..., V, 3]
                      target_points: jnp.ndarray,  # [..., N, 3]
                      ) -> jnp.ndarray:
    """[..., N, V] squared distances — THE one implementation of the
    cancellation-prone pairwise expansion (|t|^2 - 2 t.v + |v|^2); the
    chamfer objective, ICP assignment, tests, and examples all ride it.
    One MXU matmul plus broadcasts (~2.3 MFLOP per thousand points),
    trivially batch/frame-parallel."""
    return (
        jnp.sum(target_points ** 2, axis=-1)[..., :, None]
        - 2.0 * jnp.einsum("...nc,...vc->...nv", target_points, pred_verts,
                           precision=DEFAULT_PRECISION)
        + jnp.sum(pred_verts ** 2, axis=-1)[..., None, :]
    )


def nearest_vertex_sq_dist(pred_verts: jnp.ndarray,    # [..., V, 3]
                           target_points: jnp.ndarray,  # [..., N, 3]
                           ) -> jnp.ndarray:
    """Per-point squared distance to the nearest mesh vertex: [..., N],
    clamped at 0 (the expansion can go slightly negative in fp)."""
    return jnp.maximum(
        jnp.min(_pairwise_sq_dist(pred_verts, target_points), axis=-1), 0.0
    )


def nearest_vertex_idx(pred_verts: jnp.ndarray,    # [..., V, 3]
                       target_points: jnp.ndarray,  # [..., N, 3]
                       ) -> jnp.ndarray:
    """Index of the nearest mesh vertex per point: [..., N] int32 — the
    ICP correspondence assignment."""
    return jnp.argmin(_pairwise_sq_dist(pred_verts, target_points), axis=-1)


def point_cloud_l2(pred_verts: jnp.ndarray,    # [..., V, 3]
                   target_points: jnp.ndarray,  # [..., N, 3]
                   penalty=None) -> jnp.ndarray:
    """One-sided chamfer: each observed point to its nearest mesh vertex.

    The correspondence-free registration objective (depth-sensor scans,
    partial point clouds): every observed point must lie on the mesh;
    mesh regions with no observations are unpenalized — exactly right for
    partial views, where the two-sided term would drag unobserved surface
    toward the data. The min is the standard ICP subgradient (flows to
    the closest vertex); N is static per compile.
    """
    sq = nearest_vertex_sq_dist(pred_verts, target_points)
    return jnp.mean(sq if penalty is None else penalty(sq))


def vertex_l2(pred_verts: jnp.ndarray, target_verts: jnp.ndarray,
              penalty=None) -> jnp.ndarray:
    """Mean per-vertex penalty (the data term).

    ``penalty`` maps per-point squared distances elementwise (e.g.
    ``huber``); None means plain squared distance. The solvers route
    every data term through these helpers, so a change here IS a change
    to what fit/fit_sequence optimize.
    """
    sq = jnp.sum((pred_verts - target_verts) ** 2, axis=-1)
    return jnp.mean(sq if penalty is None else penalty(sq))


def joint_l2(pred_joints: jnp.ndarray, target_joints: jnp.ndarray,
             penalty=None) -> jnp.ndarray:
    """Mean per-joint penalty (sparser, better conditioned early)."""
    sq = jnp.sum((pred_joints - target_joints) ** 2, axis=-1)
    return jnp.mean(sq if penalty is None else penalty(sq))


def max_vertex_error(pred_verts: jnp.ndarray, target_verts: jnp.ndarray) -> jnp.ndarray:
    """Max per-vertex Euclidean error — the BASELINE.json accuracy metric."""
    return jnp.max(jnp.linalg.norm(pred_verts - target_verts, axis=-1))


def keypoint2d_l2(
    pred_xy: jnp.ndarray,      # [..., J, 2] projected keypoints
    target_xy: jnp.ndarray,    # [..., J, 2] observed keypoints
    conf: jnp.ndarray = None,  # [..., J] optional per-keypoint confidence
    penalty=None,              # elementwise map of squared distances
) -> jnp.ndarray:
    """(Confidence-weighted) mean squared 2D reprojection error.

    The data term for fitting to detector output: 3D joints projected
    through a pinhole ``viz.camera.Camera`` against observed 2D keypoints.
    ``conf`` downweights occluded/unreliable detections; weights are
    normalized so the loss scale is independent of how many keypoints are
    trusted. Reduction is over the keypoint axis only — batched inputs get
    one loss per problem in both the weighted and unweighted branches.
    """
    err = jnp.sum((pred_xy - target_xy) ** 2, axis=-1)
    if penalty is not None:
        err = penalty(err)
    if conf is None:
        return jnp.mean(err, axis=-1)
    return jnp.sum(conf * err, axis=-1) / jnp.maximum(
        jnp.sum(conf, axis=-1), 1e-12
    )


def silhouette_iou_loss(pred_sil: jnp.ndarray,    # [..., H, W] in [0, 1]
                        target_mask: jnp.ndarray,  # [..., H, W] in [0, 1]
                        ) -> jnp.ndarray:
    """1 - soft IoU between a rendered soft silhouette and a target mask.

    The standard mask-supervision energy: scale-free (a hand covering 4%
    of the frame weighs the same as one covering 40% — a plain per-pixel
    MSE is dominated by the background and goes flat) and bounded in
    [0, 1]. Soft intersection = sum(p*t), soft union = sum(p + t - p*t)
    (the SoftRas convention): with a binary target the loss is 0 iff the
    prediction is 1 on the mask and 0 off it; for two SOFT images it
    bottoms out slightly above 0 (p*p < p), which shifts the floor, not
    the argmin. Reduction is over the two image axes only, so
    batched/clip inputs get one loss per image — mean over frames at the
    call site. The epsilon keeps the empty-empty case (no hand in frame,
    no mask) a well-defined zero loss.
    """
    inter = jnp.sum(pred_sil * target_mask, axis=(-2, -1))
    union = jnp.sum(pred_sil + target_mask, axis=(-2, -1)) - inter
    return 1.0 - (inter + 1e-6) / (union + 1e-6)


def depth_loss(pred_depth: jnp.ndarray,    # [..., H, W] meters
               target_depth: jnp.ndarray,  # [..., H, W]; <=0 = invalid
               penalty=None) -> jnp.ndarray:
    """Masked mean squared depth error against a sensor depth image.

    Depth sensors return 0 (or negative sentinel) where they have no
    reading — those pixels carry no information and are excluded, the
    universal depth-map convention. ``penalty`` maps per-pixel SQUARED
    errors (e.g. ``huber`` — sensor depth is heavy-tailed at object
    boundaries). Reduction over the image axes only: one loss per
    image, mean over frames at the call site. An image with zero valid
    pixels contributes 0 (not NaN); the solvers reject all-invalid
    targets up front where values are concrete.
    """
    valid = target_depth > 0.0          # NaN > 0 is False: NaN-invalid
    #   sensor maps (the ROS/Open3D float convention) mask out too.
    # The double-where: sanitize the INPUT before it enters the residual,
    # not just the output — masking sq afterwards still leaves
    # (pred - NaN) in the graph, and backward's 0-cotangent times that
    # NaN poisons every gradient (the classic jnp.where pitfall).
    safe_target = jnp.where(valid, target_depth, 0.0)
    sq = jnp.where(valid, (pred_depth - safe_target) ** 2, 0.0)
    if penalty is not None:
        sq = penalty(sq)
        sq = jnp.where(valid, sq, 0.0)  # penalty(0) need not be 0
    v = valid.astype(pred_depth.dtype)
    return (
        jnp.sum(sq, axis=(-2, -1))
        / jnp.maximum(jnp.sum(v, axis=(-2, -1)), 1.0)
    )


def huber(sq_dist: jnp.ndarray, delta: float) -> jnp.ndarray:
    """Huber penalty on per-point SQUARED distances.

    Quadratic (= sq_dist) within ``delta`` of zero, linear in distance
    beyond — outliers contribute bounded gradients instead of dragging
    the fit. Formulated on squared distances so the inlier branch never
    takes a sqrt (grad-safe at exact zero); the outlier branch's sqrt
    argument is clamped from below by delta^2, away from zero.
    """
    d2 = delta * delta
    inlier = sq_dist <= d2
    safe = jnp.sqrt(jnp.maximum(sq_dist, d2))
    return jnp.where(inlier, sq_dist, 2.0 * delta * safe - d2)


def inter_penetration(verts_a: jnp.ndarray,   # [..., V, 3]
                      verts_b: jnp.ndarray,   # [..., W, 3]
                      radius: float) -> jnp.ndarray:
    """Soft inter-mesh repulsion: penalize vertex pairs closer than ``radius``.

    Symmetric hinge on nearest-neighbor distances between two meshes —
    zero once every vertex of each mesh is at least ``radius`` (meters)
    from the other, quadratic inside. This is the standard contact/
    penetration regularizer for interacting-hands fitting: noisy or
    sparse observations routinely pull the two fitted hands through each
    other; physically they can touch but not overlap. The hinge is on
    DISTANCE (not squared distance) so the gradient does not vanish as
    surfaces approach contact; the sqrt is clamped away from zero.
    """
    # One pairwise expansion serves both directions (min over each axis);
    # the term runs every optimizer step, so don't pay the [V, W] matmul
    # and its backward twice.
    d2 = jnp.maximum(_pairwise_sq_dist(verts_a, verts_b), 0.0)  # [..., W, V]

    def hinge(sq):
        d = jnp.sqrt(jnp.maximum(sq, 1e-12))
        return jnp.mean(jnp.maximum(radius - d, 0.0) ** 2)

    return 0.5 * (hinge(jnp.min(d2, axis=-1)) + hinge(jnp.min(d2, axis=-2)))


def self_penetration_mask(params, radius: float = 0.004) -> jnp.ndarray:
    """[V, V] bool mask of vertex pairs the self-penetration term may
    penalize: pairs whose body parts lie on DIFFERENT kinematic chains
    (neither is an ancestor of the other), AND which are farther than
    ``radius`` apart in the REST pose.

    Segmenting by dominant skinning weight assigns each vertex to one of
    the 16 parts. A finger's whole NON-ROOT ancestor chain is excluded —
    not just parent/child — because a curling finger legitimately brings
    its own distal pad near its own proximal segment (DIP vs MCP parts
    are two hops apart) and must not repel itself open. The root is
    special-cased: it is every joint's ancestor, so excluding ancestor
    relations through it would silently free ALL palm pairs — exactly
    the thumb-through-palm case the term exists for. Palm keeps only
    direct parent/child adjacency (the knuckle-base regions that
    genuinely overlap it). The rest-pose distance filter removes
    remaining pairs already close in the neutral hand (adjacent finger
    bases). What remains is cross-chain proximity — fingers against each
    other, thumb and fingers against the palm. Note the term is a SOFT
    prior, like every repulsion regularizer: genuine cross-finger
    contact pays a small hinge cost traded against the data weight; what
    it prevents is the surface-through-surface solutions sparse
    keypoints cannot rule out. Constant per asset: compute once and
    reuse (a [V, V] bool is ~605 KB — one byte per bool; the solvers'
    ``prepare_self_pen`` accepts a prebuilt mask via ``_self_pen_mask``,
    which per-frame callers like the tracker use).
    """
    import numpy as np

    w = np.asarray(params.lbs_weights)
    parents = list(params.parents)
    n_joints = w.shape[1]
    part = w.argmax(axis=1)                               # [V]
    # excluded[a, b]: same part, direct parent/child, or same-chain via
    # NON-root ancestors (the root is everyone's ancestor — routing the
    # chain relation through it would exempt every palm pair).
    excluded = np.eye(n_joints, dtype=bool)
    for j in range(n_joints):
        p = parents[j]
        if p is not None and p >= 0:
            excluded[p, j] = excluded[j, p] = True        # direct
            k = parents[p]
            while k is not None and k >= 0 and parents[k] is not None \
                    and parents[k] >= 0:
                # k is a non-root strict ancestor of j.
                excluded[k, j] = excluded[j, k] = True
                k = parents[k]
    rest = np.asarray(params.v_template)
    d2 = ((rest[:, None, :] - rest[None, :, :]) ** 2).sum(-1)
    far_at_rest = d2 > radius * radius
    return jnp.asarray(
        ~excluded[part[:, None], part[None, :]] & far_at_rest
    )


def self_penetration(verts: jnp.ndarray,   # [..., V, 3]
                     mask: jnp.ndarray,    # [V, V] from self_penetration_mask
                     radius: float) -> jnp.ndarray:
    """Soft SELF-collision repulsion for one hand (leading axes broadcast).

    Hinge on distances between masked vertex pairs only — fingers may
    touch (the mask excludes same/adjacent parts and rest-pose
    neighbors) but not pass through each other, the failure mode of
    sparse-observation fitting (16 or 21 keypoints say nothing about the
    surface between them). Mean over each vertex's nearest masked
    neighbor, matching ``inter_penetration``'s scale.
    """
    d2 = jnp.maximum(_pairwise_sq_dist(verts, verts), 0.0)    # [V, V]
    # Unmasked pairs are pushed beyond the hinge instead of being
    # dropped, so each row's min stays well-defined and differentiable.
    d2 = jnp.where(mask, d2, (2.0 * radius) ** 2)
    d = jnp.sqrt(jnp.maximum(jnp.min(d2, axis=-1), 1e-12))
    return jnp.mean(jnp.maximum(radius - d, 0.0) ** 2)


def l2_prior(x: jnp.ndarray) -> jnp.ndarray:
    """Quadratic prior toward zero (pose/shape regularizer)."""
    return jnp.mean(x ** 2)


def mahalanobis_pose_prior(
    params,
    fingers_flat: jnp.ndarray,        # [..., 3*(J-1)] articulated axis-angle
    component_vars: jnp.ndarray = None,  # [C] per-component variances
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Data-driven pose prior: squared deviation from the anatomical mean
    pose, measured in PCA-whitened component space.

    The asset's ``pca_basis``/``pca_mean`` encode the pose distribution the
    model was built from (the reference regularizes implicitly by
    truncating to few PCA dims, /root/reference/mano_np.py:67-68); this
    makes that knowledge an explicit Mahalanobis energy:

        z = (theta_fingers - pca_mean) @ pinv(pca_basis);  mean(z^2 / var)

    Unlike ``l2_prior`` it (a) pulls toward the MEAN pose, not the zero
    pose (a flat, non-anatomical hand), and (b) with ``component_vars``
    (estimated from real poses via ``pose_component_variances``) charges
    deviation along rare directions more than along common ones. The
    global rotation row is deliberately NOT part of the energy — where the
    hand points is not anatomically constrained. Scalar output (mean over
    all leading axes too, matching ``l2_prior``'s reduction contract).
    """
    basis = jnp.asarray(params.pca_basis, fingers_flat.dtype)
    mean = jnp.asarray(params.pca_mean, fingers_flat.dtype)
    # pinv is [45, C]-tiny, batch-invariant, and hoisted by XLA out of
    # vmapped/scanned programs; for orthonormal bases it equals basis.T.
    pinv = jnp.linalg.pinv(basis)
    z = jnp.einsum("...f,fc->...c", fingers_flat - mean, pinv,
                   precision=precision)
    if component_vars is not None:
        z = z / jnp.sqrt(jnp.asarray(component_vars, z.dtype))
    return jnp.mean(z ** 2)


def pose_limit_prior(
    fingers_flat: jnp.ndarray,   # [..., 3*(J-1)] articulated axis-angle
    lo: jnp.ndarray,             # [3*(J-1)] (or broadcastable) lower bounds
    hi: jnp.ndarray,             # [3*(J-1)] upper bounds, radians
) -> jnp.ndarray:
    """Anatomical joint-limit prior: squared hinge outside per-DOF bounds.

    Quadratic in / past the violation (``relu(lo - x)^2 + relu(x - hi)^2``)
    so the energy is zero everywhere inside the admissible box — unlike
    the Mahalanobis prior it never fights observations within range, it
    only walls off hyperextension and reversed bends (the classic failure
    of keypoint-only fits: a knuckle folded backwards explains 2D
    observations exactly as well as the true pose). Bounds are per flat
    axis-angle DOF; derive them from a pose corpus with
    ``pose_limits_from_corpus`` (nothing anatomical ships hardcoded — the
    corpus, e.g. the official assets' scan poses, is the anatomy).
    Scalar output, same reduction contract as ``l2_prior``.
    """
    x = fingers_flat
    lo = jnp.asarray(lo, x.dtype)
    hi = jnp.asarray(hi, x.dtype)
    under = jnp.maximum(lo - x, 0.0)
    over = jnp.maximum(x - hi, 0.0)
    return jnp.mean(under ** 2 + over ** 2)


def pose_limits_from_corpus(params, poses, expand: float = 0.15):
    """Per-DOF axis-angle bounds ``(lo, hi)`` from a pose corpus.

    ``poses`` accepts the same formats as ``pose_component_variances``
    ([N, 16, 3] full, [N, 15, 3] articulated, [N, 45] flat — e.g.
    ``assets.scans.decode_scan_poses`` output). Bounds are the corpus
    min/max per flat DOF, expanded by ``expand`` radians on both sides
    (observed poses are a sample, not the boundary, of the feasible
    set). Feed to ``fit(joint_limits=..., joint_limit_weight=...)``.
    """
    flat = _flat_articulated(params, poses)
    return flat.min(axis=0) - expand, flat.max(axis=0) + expand


def _flat_articulated(params, poses) -> jnp.ndarray:
    """Normalize a pose corpus to flat articulated axis-angle [N, 3*(J-1)].

    Accepts [N, J, 3] full (global-rotation row dropped), [N, J-1, 3]
    articulated, or already-flat [N, 3*(J-1)]."""
    poses = jnp.asarray(poses)
    n_aa = jnp.asarray(params.pca_mean).shape[-1]
    if poses.ndim == 3 and poses.shape[-2] * 3 == n_aa + 3:
        poses = poses[..., 1:, :]  # drop the global-rotation row
    return poses.reshape(poses.shape[0], n_aa)


def mirror_pose_limits(lo, hi):
    """Right-hand bounds from left-hand ones (or vice versa).

    The official assets relate the two sides by negating the y/z
    axis-angle components per joint (the scan extractor's [1, -1, -1]
    mirror, /root/reference/dump_model.py:38). Negation swaps AND flips
    a bound pair, so for those components ``lo' = -hi`` and
    ``hi' = -lo``; the x (flexion) component carries over unchanged.
    Use with ``fit_hands(joint_limits=(stack([lo, lo']), stack([hi,
    hi'])))`` when the corpus covers only one side.
    """
    lo = jnp.asarray(lo)
    hi = jnp.asarray(hi)
    sign = jnp.tile(jnp.asarray([1.0, -1.0, -1.0], lo.dtype),
                    lo.shape[-1] // 3)
    flipped = sign < 0
    return (jnp.where(flipped, -hi, lo), jnp.where(flipped, -lo, hi))


def pose_component_variances(params, poses) -> jnp.ndarray:
    """Per-component variances of a pose corpus in PCA component space.

    ``poses`` is [N, 16, 3] full axis-angle (global row dropped),
    [N, 15, 3] articulated, or [N, 45] flat — e.g. the scan poses the
    official assets ship (``assets.scans.decode_scan_poses``). Feed the
    result to ``mahalanobis_pose_prior`` / ``fit(pose_prior_vars=...)``.
    A small floor keeps near-degenerate components from exploding the
    whitened energy.
    """
    flat = _flat_articulated(params, poses)
    pinv = jnp.linalg.pinv(jnp.asarray(params.pca_basis, flat.dtype))
    z = jnp.einsum("nf,fc->nc", flat - jnp.asarray(params.pca_mean,
                                                   flat.dtype), pinv,
                   precision=DEFAULT_PRECISION)
    return jnp.maximum(jnp.var(z, axis=0), 1e-6)
