"""Fitting objectives and priors.

The reference has no fitting capability at all; BASELINE.json's north star
adds it ("the JAX path is fully differentiable so pose/shape can be
recovered by gradient descent on TPU"). Objectives are pure functions of
(predicted, target) plus optional parameter priors, composable into one
scalar loss for optax.
"""

from __future__ import annotations

import jax.numpy as jnp


def vertex_l2(pred_verts: jnp.ndarray, target_verts: jnp.ndarray) -> jnp.ndarray:
    """Mean squared vertex distance (the data term)."""
    return jnp.mean(jnp.sum((pred_verts - target_verts) ** 2, axis=-1))


def joint_l2(pred_joints: jnp.ndarray, target_joints: jnp.ndarray) -> jnp.ndarray:
    """Mean squared joint distance (sparser, better conditioned early)."""
    return jnp.mean(jnp.sum((pred_joints - target_joints) ** 2, axis=-1))


def max_vertex_error(pred_verts: jnp.ndarray, target_verts: jnp.ndarray) -> jnp.ndarray:
    """Max per-vertex Euclidean error — the BASELINE.json accuracy metric."""
    return jnp.max(jnp.linalg.norm(pred_verts - target_verts, axis=-1))


def keypoint2d_l2(
    pred_xy: jnp.ndarray,      # [..., J, 2] projected keypoints
    target_xy: jnp.ndarray,    # [..., J, 2] observed keypoints
    conf: jnp.ndarray = None,  # [..., J] optional per-keypoint confidence
) -> jnp.ndarray:
    """(Confidence-weighted) mean squared 2D reprojection error.

    The data term for fitting to detector output: 3D joints projected
    through a pinhole ``viz.camera.Camera`` against observed 2D keypoints.
    ``conf`` downweights occluded/unreliable detections; weights are
    normalized so the loss scale is independent of how many keypoints are
    trusted. Reduction is over the keypoint axis only — batched inputs get
    one loss per problem in both the weighted and unweighted branches.
    """
    err = jnp.sum((pred_xy - target_xy) ** 2, axis=-1)
    if conf is None:
        return jnp.mean(err, axis=-1)
    return jnp.sum(conf * err, axis=-1) / jnp.maximum(
        jnp.sum(conf, axis=-1), 1e-12
    )


def l2_prior(x: jnp.ndarray) -> jnp.ndarray:
    """Quadratic prior toward zero (pose/shape regularizer)."""
    return jnp.mean(x ** 2)
