"""Analytic residual Jacobian for the LM solver.

``jax.jacfwd`` of the full forward replays 58 tangent columns through the
blend + skinning chain; XLA materializes [58, V, 3, 3]-scale tangent
intermediates per problem and the LM step becomes bandwidth-bound on them
— measured 7.5 ms of the 9.4 ms step at batch 256 on a v5e chip, and
routing the replay through the fused-basis forward did not move it
(`docs/roadmap.md` 1b).

The structure the replay ignores: with pose/shape as the unknowns,
skinned vertices are

    verts_v = M_v @ v_posed_v + sum_j w_vj b_j
    M_v     = sum_j w_vj A_j

where (A_j, b_j) are the 16 skinning transforms — a function of theta
with NO vertex dimension — and v_posed is LINEAR in theta's effects
(shape basis columns; pose-corrective basis columns through R). So:

  * differentiate ONLY the tiny joint chain with ``jacfwd`` (16 joints x
    (9 + 3 + 3) outputs x 58 inputs — a few thousand numbers);
  * assemble the [V, 3, 58] vertex Jacobian with three einsums whose
    intermediates never exceed [V, 3, 58].

Exact (no approximation): validated against ``jax.jacfwd`` of the full
residual in tests/test_jacobian.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mano_hand_tpu import ops
from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core
from mano_hand_tpu.ops.common import DEFAULT_PRECISION


class ForwardJacobian(NamedTuple):
    """Forward values + exact Jacobians wrt the flat (pose, shape) vector."""

    verts: jnp.ndarray         # [V, 3]
    posed_joints: jnp.ndarray  # [J, 3]
    verts_jac: jnp.ndarray     # [V, 3, P]
    joints_jac: jnp.ndarray    # [J, 3, P]
    shape_jac: jnp.ndarray     # [S, P] selector rows (Tikhonov block)


def _small_chain(params: ManoParams, unravel, precision):
    """The joint-dimension-only forward: the part worth differentiating
    with AD (no vertex axis anywhere)."""
    _, joint_template, joint_shape_basis = core.fused_blend_bases(
        params, precision
    )

    def small(f):
        th = unravel(f)
        rot = ops.rotation_matrix(th["pose"])
        jnt = joint_template + jnp.einsum(
            "jcs,s->jc", joint_shape_basis, th["shape"], precision=precision
        )
        world_rot, world_t = ops.forward_kinematics(
            params.parents, rot, jnt, precision
        )
        skin_rot, skin_t = ops.skinning_transforms(
            world_rot, world_t, jnt, precision
        )
        return skin_rot, skin_t, world_t, rot, th["shape"]

    return small


def _values(params, skin_rot, skin_t, v_posed, precision):
    """Skinned vertices + the per-vertex blended rotation M — THE value
    expression shared by ``forward_values`` and ``forward_with_jacobian``
    so both estimators are numerically identical (the LM accept test
    compares losses across them)."""
    w = params.lbs_weights
    m_per_vertex = jnp.einsum("vj,jab->vab", w, skin_rot,
                              precision=precision)
    verts = (
        jnp.einsum("vab,vb->va", m_per_vertex, v_posed, precision=precision)
        + jnp.einsum("vj,ja->va", w, skin_t, precision=precision)
    )
    return m_per_vertex, verts


def _v_posed(params, rot, shape, precision):
    v_shaped = ops.shape_blend(
        params.v_template, params.shape_basis, shape, precision
    )
    return ops.pose_blend(v_shaped, params.pose_basis, rot, precision)


def forward_values(
    params: ManoParams,
    unravel,
    flat: jnp.ndarray,
    precision=DEFAULT_PRECISION,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(verts [V, 3], posed_joints [J, 3]) by exactly the same numeric
    path as ``forward_with_jacobian`` — for scoring candidates in the
    analytic LM loop without paying for the Jacobian."""
    small = _small_chain(params, unravel, precision)
    skin_rot, skin_t, world_t, rot, shape = small(flat)
    v_posed = _v_posed(params, rot, shape, precision)
    _, verts = _values(params, skin_rot, skin_t, v_posed, precision)
    return verts, world_t


def forward_with_jacobian(
    params: ManoParams,
    unravel,                 # ravel_pytree unravel for {"pose", "shape"}
    flat: jnp.ndarray,       # [P] flattened (pose, shape)
    precision=DEFAULT_PRECISION,
    shape_frozen: bool = False,
) -> ForwardJacobian:
    """One forward pass + its full analytic Jacobian.

    ``unravel`` defines the column layout — the same ravel the solver
    optimizes in, so no ordering assumptions are baked in here.

    ``shape_frozen=True`` declares that ``unravel`` injects beta as a
    CONSTANT (the specialization split's pose-only tracking mode, where
    ``flat`` carries only the 48 pose columns): ``d_shape`` from the
    small chain's jacfwd is then exactly zero, so the shape-basis term
    of ``dv`` — a [V, 3, S] x [S, P] contraction of structural zeros —
    is skipped outright. Bit-safe: adding an exactly-zero slab is the
    identity, so the assembled Jacobian is unchanged.
    """
    n_params = flat.shape[0]
    small = _small_chain(params, unravel, precision)
    vals = small(flat)
    d_skin_rot, d_skin_t, d_world_t, d_rot, d_shape = jax.jacfwd(small)(flat)
    skin_rot, skin_t, world_t, rot, shape = vals

    # v_posed and its Jacobian: linear in beta (shape basis) and in
    # vec(R[1:]) (pose-corrective basis); d_rot carries rot's dependence
    # on the flat vector, so the pose AND any cross terms come along.
    v_posed = _v_posed(params, rot, shape, precision)
    n_pose_basis = params.pose_basis.shape[-1]
    d_vec_rot = d_rot[1:].reshape(n_pose_basis, n_params)
    dv = jnp.einsum("vcf,fp->vcp", params.pose_basis, d_vec_rot,
                    precision=precision)
    if not shape_frozen:
        dv = dv + jnp.einsum("vcs,sp->vcp", params.shape_basis, d_shape,
                             precision=precision)

    # verts_v = (sum_j w_vj A_j) v_v + sum_j w_vj b_j; product rule over
    # the three theta-dependent factors. Intermediates stay [V, 3, P].
    w = params.lbs_weights
    m_per_vertex, verts = _values(params, skin_rot, skin_t, v_posed,
                                  precision)
    # The dA term MUST contract (j, b) together: the per-vertex outer
    # product O[v, j, b] = w[v, j] * v[v, b] turns it into one
    # [V, J*3] x [J*3, 3*P] matmul with no [V, 3, 3, P]-scale
    # intermediate (a three-operand einsum left to XLA materialized one
    # and ate the analytic path's advantage — measured).
    n_joints = w.shape[1]
    outer = (w[:, :, None] * v_posed[:, None, :]).reshape(
        -1, n_joints * 3
    )
    da_flat = d_skin_rot.transpose(0, 2, 1, 3).reshape(n_joints * 3, -1)
    # The M @ dv term is a [3, 3] x [3, P] contraction per vertex — as an
    # einsum/dot it lowers to B*V microscopic gemms (measured ms-scale);
    # unrolled over the K=3 axis it is three fused elementwise
    # multiply-adds over the [V, 3, P] slab (VPU work, ~0.4 ms at b=256).
    m_dot_dv = sum(
        m_per_vertex[:, :, b, None] * dv[:, b, None, :] for b in range(3)
    )
    verts_jac = (
        m_dot_dv
        + jnp.matmul(outer, da_flat, precision=precision).reshape(
            -1, 3, n_params
        )
        + jnp.einsum("vj,jap->vap", w, d_skin_t, precision=precision)
    )
    return ForwardJacobian(
        verts=verts,
        posed_joints=world_t,
        verts_jac=verts_jac,
        joints_jac=d_world_t,
        shape_jac=d_shape,
    )


def keypoint_jacobian(
    fj: ForwardJacobian,
    tips,                       # resolved tuple or None
    keypoint_order: str,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(keypoints [K, 3], jac [K, 3, P]) under the same selection/ordering
    as ``core.keypoints`` — tip rows are vertex rows of the mesh Jacobian,
    selected by the SAME shared helper (axis=0: rows of [K, 3, P])."""
    kp = core.select_keypoints(fj.verts, fj.posed_joints, tips,
                               keypoint_order)
    jac = core.select_keypoints(fj.verts_jac, fj.joints_jac, tips,
                                keypoint_order, axis=0)
    return kp, jac
