"""Joint two-hand fitting (interacting hands).

The reference evaluates left and right hands in separate, unrelated calls
(two asset files, /root/reference/dump_model.py:48-49; serial loop,
/root/reference/data_explore.py:12-15). Real two-hand data — mocap,
egocentric video, InterHand-style captures — is one OBSERVATION of two
hands in one frame of reference, and fitting them independently lets
noisy or sparse observations pull the meshes through each other.

``fit_hands`` optimizes both hands as ONE problem: stacked-parameter
forward (one XLA program, hand-batched matmuls — ``core.forward_hands``'s
layout), per-hand pose/shape/translation, a shared camera for 2D terms,
and an optional inter-penetration repulsion term
(``objectives.inter_penetration``) that keeps the two fitted surfaces
from overlapping — they may touch, not intersect. TPU-first shape: the
whole solve is one jitted ``lax.scan`` of Adam steps, hand axis vmapped,
exactly like the single-hand solvers.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.fitting import objectives, solvers
from mano_hand_tpu.models import core


def _hands_silhouette_loss(stacked, verts, targets, camera, sil_sigma,
                           per_hand: bool):
    """Mask loss for the two-hand solvers.

    ``verts`` carries the hand axis at -3 ([2, V, 3] or [T, 2, V, 3]);
    each hand renders with ITS OWN faces (left/right winding differs in
    the stacked tree). ``per_hand`` scores [.., 2, H, W] instance masks
    per hand; otherwise the two renders combine by the same
    probabilistic union the rasterizer uses across faces — one soft
    image of BOTH hands against one combined segmenter mask.
    """
    from mano_hand_tpu.viz.silhouette import soft_silhouette

    h, w = targets.shape[-2], targets.shape[-1]
    sil_l = soft_silhouette(verts[..., 0, :, :], stacked.faces[0], camera,
                            height=h, width=w, sigma=sil_sigma)
    sil_r = soft_silhouette(verts[..., 1, :, :], stacked.faces[1], camera,
                            height=h, width=w, sigma=sil_sigma)
    if per_hand:
        sil = jnp.stack([sil_l, sil_r], axis=-3)
    else:
        sil = 1.0 - (1.0 - sil_l) * (1.0 - sil_r)
    return jnp.mean(objectives.silhouette_iou_loss(sil, targets))


class HandsFitResult(NamedTuple):
    pose: jnp.ndarray          # [2, 16, 3] axis-angle (left, right)
    shape: jnp.ndarray         # [2, S]
    final_loss: jnp.ndarray    # [] final data loss (both hands)
    loss_history: jnp.ndarray  # [n_steps]
    trans: Optional[jnp.ndarray] = None  # [2, 3] when fit_trans=True


@solvers.validate_mask_target
@solvers.normalize_tips_kwarg
@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "data_term", "fit_trans", "robust",
                     "robust_scale", "tip_vertex_ids", "keypoint_order"),
)
def fit_hands(
    stacked: ManoParams,        # core.stack_params(left, right)
    targets: jnp.ndarray,       # [2, rows, coords], hand-major (L, R)
    n_steps: int = 200,
    lr: float = 0.05,
    data_term: str = "verts",
    camera=None,                # ONE camera observing both hands
    target_conf: Optional[jnp.ndarray] = None,  # [K] or [2, K]
    fit_trans: bool = False,
    robust: str = "none",
    robust_scale: float = 0.01,
    pose_prior_weight: float = 0.0,
    shape_prior_weight: float = 0.0,
    joint_limits=None,          # (lo, hi), each [45] shared or [2, 45]
    joint_limit_weight: float = 1.0,
    repulsion_weight: float = 0.0,
    repulsion_radius: float = 0.004,
    init: Optional[dict] = None,
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    sil_sigma: float = 0.7,
) -> HandsFitResult:
    """Recover both hands' pose/shape (and translation) from one frame.

    ``stacked`` is ``core.stack_params(left, right)`` — [2, ...] leaves.
    ``targets`` is hand-major: ``targets[0]`` observes the left hand,
    ``targets[1]`` the right, in the same world/camera frame. All data
    terms of ``fit`` except the ICP ones apply, including the 21-keypoint
    extension. ``fit_trans=True`` gives each hand its own translation —
    effectively mandatory for real two-hand observations, which are never
    both origin-centered.

    ``data_term="silhouette"`` fits segmentation masks: per-hand
    ``[2, H, W]`` instance masks, or ONE combined ``[H, W]`` mask — the
    common segmenter output where both hands share a class — scored
    against the soft UNION of the two hands' renders. The combined form
    is where joint fitting earns its keep: each hand explains part of
    one observation, and ``repulsion_weight`` keeps the explanation from
    collapsing both hands onto the same blob. A combined mask cannot say
    WHICH hand explains which region — from a cold start the swapped
    assignment is an equally good optimum (measured) — so warm-start
    ``init["trans"]`` from detector boxes or the previous frame.

    ``repulsion_weight > 0`` adds ``objectives.inter_penetration``
    between the two fitted surfaces at ``repulsion_radius`` (meters):
    with sparse or noisy observations of close interaction the
    unconstrained solution routinely interpenetrates; the hinge term is
    zero whenever the hands are separated, so it only acts where it is
    needed. Weight ~1-10 relative to a unit data term is a reasonable
    starting range (the repulsion is mean-squared meters, same scale as
    the 3D data terms).
    """
    if stacked.side != "stacked":
        raise ValueError(
            "fit_hands takes core.stack_params(left, right) output "
            f"([2, ...] leaves); got side={stacked.side!r}. For one hand "
            "use fit()."
        )
    # Unsupported-term rejection FIRST: running the generic validator
    # before it would demand a camera for a term this entry point does
    # not support at all.
    if data_term in ("points", "depth"):
        raise ValueError(
            "fit_hands supports verts/joints/keypoints2d/silhouette; for "
            "scan registration fit each hand with fit_lm (ICP needs "
            "per-hand correspondence anyway), and for depth images fit "
            "each hand on its cropped depth region"
        )
    solvers._check_data_term(data_term, camera, target_conf)
    dtype = stacked.v_template.dtype
    targets = jnp.asarray(targets, dtype)
    per_hand_masks = False
    if data_term == "silhouette":
        # [H, W] = ONE combined mask covering both hands (a segmenter's
        # single hand class — the hands render as a soft UNION);
        # [2, H, W] = per-hand instance masks.
        per_hand_masks = solvers.check_hands_silhouette(
            camera, robust, targets, seq=False, fn_name="fit_hands"
        )
    elif targets.ndim != 3 or targets.shape[0] != 2:
        raise ValueError(
            f"targets must be [2, rows, coords] hand-major, got "
            f"{targets.shape}"
        )
    # Row/tips validation rides the shared validator; n_joints etc. come
    # from one hand's slice of the stacked tree.
    one = jax.tree_util.tree_map(lambda x: x[0], stacked)
    tips, n_kp = solvers.check_keypoint_spec(
        one, data_term, tip_vertex_ids, keypoint_order, targets, "fit_hands"
    )
    n_joints = one.j_regressor.shape[0]
    n_shape = one.shape_basis.shape[-1]
    target_conf = solvers.normalize_conf(target_conf, n_kp, dtype)
    if target_conf is not None:
        target_conf = jnp.broadcast_to(target_conf, (2, n_kp))

    theta0 = {
        "pose": jnp.zeros((2, n_joints, 3), dtype),
        "shape": jnp.zeros((2, n_shape), dtype),
    }
    if fit_trans:
        theta0["trans"] = jnp.zeros((2, 3), dtype)
    if init:
        unknown = set(init) - set(theta0)
        if unknown:
            raise ValueError(
                f"init keys {sorted(unknown)} not in {sorted(theta0)}"
            )
        for k, v in init.items():
            v = jnp.asarray(v, dtype)
            if v.shape != theta0[k].shape:
                raise ValueError(
                    f"init[{k!r}] shape {v.shape} != {theta0[k].shape} "
                    "(hand-major: both hands)"
                )
            theta0[k] = v

    def loss_fn(p):
        # One program: vmap the single-hand forward over the hand axis of
        # params AND variables (forward_hands' layout, batch dim absent).
        out = jax.vmap(
            lambda prm, pose, shape: core.forward(prm, pose, shape)
        )(stacked, p["pose"], p["shape"])
        offset = p["trans"][:, None, :] if fit_trans else 0.0
        if data_term == "silhouette":
            data = _hands_silhouette_loss(
                stacked, out.verts + offset, targets, camera, sil_sigma,
                per_hand_masks,
            )
        else:
            data = solvers._data_loss(
                out, offset, targets, data_term, camera, target_conf,
                robust, robust_scale, tips, keypoint_order,
            )
        reg = (
            pose_prior_weight * objectives.l2_prior(p["pose"][:, 1:])
            + shape_prior_weight * objectives.l2_prior(p["shape"])
        )
        if joint_limits is not None:
            # Bounds broadcast [45] (shared) or [2, 45] (per-hand —
            # mirrored sides have mirrored boxes, see
            # objectives.mirror_pose_limits) against [2, 45] poses.
            lo, hi = joint_limits
            reg = reg + joint_limit_weight * objectives.pose_limit_prior(
                p["pose"][:, 1:].reshape(2, -1), lo, hi
            )
        # repulsion_weight rides as a traced operand (hyperparameter
        # sweeps reuse one program), so the term is always computed;
        # at ~2x778^2 pairwise distances it is small next to the forward.
        verts = out.verts + offset
        reg = reg + repulsion_weight * objectives.inter_penetration(
            verts[0], verts[1], repulsion_radius
        )
        return data + reg, data

    p_final, final_loss, history = solvers._run_adam(
        loss_fn, theta0, optax.adam(lr), n_steps
    )
    return HandsFitResult(
        pose=p_final["pose"],
        shape=p_final["shape"],
        final_loss=final_loss,
        loss_history=history,
        trans=p_final.get("trans"),
    )


class HandsSequenceFitResult(NamedTuple):
    pose: jnp.ndarray          # [T, 2, 16, 3] per-frame, per-hand
    shape: jnp.ndarray         # [2, S] ONE shape per hand for the clip
    final_loss: jnp.ndarray    # []
    loss_history: jnp.ndarray  # [n_steps]
    trans: Optional[jnp.ndarray] = None  # [T, 2, 3] when fit_trans=True


@solvers.validate_mask_target
@solvers.normalize_tips_kwarg
@functools.partial(
    jax.jit,
    static_argnames=("n_steps", "data_term", "fit_trans", "robust",
                     "robust_scale", "tip_vertex_ids", "keypoint_order",
                     "mask_layout"),
)
def fit_hands_sequence(
    stacked: ManoParams,        # core.stack_params(left, right)
    targets: jnp.ndarray,       # [T, 2, rows, coords] frame-major
    n_steps: int = 300,
    lr: float = 0.03,
    data_term: str = "verts",
    camera=None,
    target_conf: Optional[jnp.ndarray] = None,  # [K] or [2, K]
    fit_trans: bool = False,
    robust: str = "none",
    robust_scale: float = 0.01,
    smooth_pose_weight: float = 1e-3,
    smooth_trans_weight: float = 1e-3,
    pose_prior_weight: float = 0.0,
    shape_prior_weight: float = 1e-3,
    joint_limits=None,          # (lo, hi), each [45] shared or [2, 45]
    joint_limit_weight: float = 1.0,
    repulsion_weight: float = 0.0,
    repulsion_radius: float = 0.004,
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    sil_sigma: float = 0.7,
    mask_layout: str = "auto",   # "combined" | "per_hand" | "auto"
) -> HandsSequenceFitResult:
    """Track a two-hand clip as ONE optimization problem.

    The two-hand counterpart of ``fit_sequence`` (frame-major
    ``[T, 2, rows, coords]`` targets, matching
    ``anim.evaluate_two_hand_sequence``'s layout): each hand keeps ONE
    shape across the clip, per-frame pose (and translation), with
    squared-velocity smoothness coupling consecutive frames — occluded
    frames borrow from their neighbors AND from the other hand's
    repulsion constraint when ``repulsion_weight > 0`` (applied per
    frame: interacting-hands clips are exactly where observations go
    missing and surfaces drift through each other).
    """
    if stacked.side != "stacked":
        raise ValueError(
            "fit_hands_sequence takes core.stack_params(left, right) "
            f"output; got side={stacked.side!r}. For one hand use "
            "fit_sequence()."
        )
    if data_term in ("points", "depth"):
        raise ValueError(
            "fit_hands_sequence supports verts/joints/keypoints2d/"
            "silhouette"
        )
    if mask_layout != "auto" and data_term != "silhouette":
        raise ValueError(
            "mask_layout only applies to data_term='silhouette', got "
            f"data_term={data_term!r}"
        )
    solvers._check_data_term(data_term, camera, target_conf)
    dtype = stacked.v_template.dtype
    targets = jnp.asarray(targets, dtype)
    per_hand_masks = False
    if data_term == "silhouette":
        # [T, H, W] combined per frame, or [T, 2, H, W] per-hand.
        per_hand_masks = solvers.check_hands_silhouette(
            camera, robust, targets, seq=True,
            fn_name="fit_hands_sequence", mask_layout=mask_layout,
        )
    elif targets.ndim != 4 or targets.shape[1] != 2:
        raise ValueError(
            "targets must be [T, 2, rows, coords] frame-major, got "
            f"{targets.shape}; for one frame use fit_hands()"
        )
    one = jax.tree_util.tree_map(lambda x: x[0], stacked)
    tips, n_kp = solvers.check_keypoint_spec(
        one, data_term, tip_vertex_ids, keypoint_order, targets,
        "fit_hands_sequence",
    )
    t_frames = targets.shape[0]
    n_joints = one.j_regressor.shape[0]
    n_shape = one.shape_basis.shape[-1]
    target_conf = solvers.normalize_conf(target_conf, n_kp, dtype)
    if target_conf is not None:
        target_conf = jnp.broadcast_to(target_conf, (t_frames, 2, n_kp))

    theta0 = {
        "pose": jnp.zeros((t_frames, 2, n_joints, 3), dtype),
        "shape": jnp.zeros((2, n_shape), dtype),
    }
    if fit_trans:
        theta0["trans"] = jnp.zeros((t_frames, 2, 3), dtype)

    def loss_fn(p):
        # Hand-major forward ([2, T, ...]): vmap the batched per-hand
        # forward over the hand axis of params AND variables, then view
        # frame-major for the data term.
        pose_hm = jnp.swapaxes(p["pose"], 0, 1)          # [2, T, 16, 3]
        shapes_hm = jnp.broadcast_to(
            p["shape"][:, None, :], (2, t_frames, n_shape)
        )
        out_hm = jax.vmap(
            lambda prm, pp, ss: core.forward_batched(prm, pp, ss)
        )(stacked, pose_hm, shapes_hm)
        out = jax.tree_util.tree_map(
            lambda x: jnp.swapaxes(x, 0, 1), out_hm     # [T, 2, ...]
        )
        offset = p["trans"][..., None, :] if fit_trans else 0.0
        if data_term == "silhouette":
            data = _hands_silhouette_loss(
                stacked, out.verts + offset, targets, camera, sil_sigma,
                per_hand_masks,
            )
        else:
            data = solvers._data_loss(
                out, offset, targets, data_term, camera, target_conf,
                robust, robust_scale, tips, keypoint_order,
            )
        if t_frames > 1:
            vel = p["pose"][1:] - p["pose"][:-1]
            reg = smooth_pose_weight * jnp.mean(vel ** 2)
            if fit_trans:
                tvel = p["trans"][1:] - p["trans"][:-1]
                reg = reg + smooth_trans_weight * jnp.mean(tvel ** 2)
        else:
            reg = jnp.zeros((), dtype)
        reg = (
            reg
            + pose_prior_weight * objectives.l2_prior(p["pose"][:, :, 1:])
            + shape_prior_weight * objectives.l2_prior(p["shape"])
        )
        if joint_limits is not None:
            # [T, 2, 45] against [45]/[2, 45] bounds — frames and hands
            # both broadcast into the hinge's mean.
            lo, hi = joint_limits
            reg = reg + joint_limit_weight * objectives.pose_limit_prior(
                p["pose"][:, :, 1:].reshape(
                    p["pose"].shape[0], 2, -1), lo, hi
            )
        verts = out.verts + offset
        # inter_penetration broadcasts over the frame axis: [T, V, 3]
        # per hand -> mean over frames comes out of the hinge means.
        reg = reg + repulsion_weight * objectives.inter_penetration(
            verts[:, 0], verts[:, 1], repulsion_radius
        )
        return data + reg, data

    p_final, final_loss, history = solvers._run_adam(
        loss_fn, theta0, optax.adam(lr), n_steps
    )
    return HandsSequenceFitResult(
        pose=p_final["pose"],
        shape=p_final["shape"],
        final_loss=final_loss,
        loss_history=history,
        trans=p_final.get("trans"),
    )
