"""Analytic fit initialization: closed-form global pose from keypoints.

The iterative solvers own articulation, but they are LOCAL: a fit seeded
at the rest orientation routinely locks into a wrong basin when the
observed hand is rotated far from it (the failure mode
``fitting.restarts`` brute-forces with R restarts x full solves). This
module replaces that brute force for the common case where 3D keypoints
exist: the optimal rigid alignment of the rest skeleton to the observed
keypoints has a CLOSED FORM (Kabsch, one 3x3 SVD), and its rotation /
translation drop directly into ``fit``/``fit_lm``'s warm-start ``init``
dict. One SVD instead of R full solves.

Reference root: the reference has no fitting at all — its only "global
pose" handling is the demo's hardcoded ``global_rot=[1,0,0]``
(/root/reference/mano_np.py:213). Convention note: the model rotates
about the ROOT JOINT (FK pivots the root at its rest position,
ops/fk.py), so the recovered translation compensates the pivot —
``model(x) = R (x - j0) + j0 + T`` is matched against the Kabsch frame
``target ~= R x + tau`` by ``T = tau + R j0 - j0``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from mano_hand_tpu import ops
from mano_hand_tpu.models import core


def rigid_align(src: jnp.ndarray, dst: jnp.ndarray):
    """Kabsch: the rotation/translation minimizing ||R src + t - dst||^2.

    ``src``/``dst`` are [..., K, 3] paired points (K >= 3, not all
    collinear). Returns ``(rot [..., 3, 3], t [..., 3])``; proper
    rotations only (det +1 — reflections are folded out the standard
    way, by flipping the smallest singular direction).
    """
    src = jnp.asarray(src)
    dst = jnp.asarray(dst)
    c_src = src.mean(axis=-2, keepdims=True)
    c_dst = dst.mean(axis=-2, keepdims=True)
    h = jnp.einsum("...ka,...kb->...ab", src - c_src, dst - c_dst)
    u, _, vt = jnp.linalg.svd(h)
    det = jnp.linalg.det(jnp.einsum("...ab,...bc->...ac",
                                    jnp.swapaxes(vt, -1, -2),
                                    jnp.swapaxes(u, -1, -2)))
    flip = jnp.concatenate(
        [jnp.ones_like(det)[..., None], jnp.ones_like(det)[..., None],
         det[..., None]], axis=-1)
    rot = jnp.einsum("...ba,...b,...bc->...ac", vt, flip,
                     jnp.swapaxes(u, -1, -2))
    t = c_dst[..., 0, :] - jnp.einsum("...ab,...b->...a",
                                      rot, c_src[..., 0, :])
    return rot, t


def initialize_from_joints(
    params,
    target_keypoints: jnp.ndarray,   # [..., K, 3]; K = 16 or 16+tips
    tip_vertex_ids=None,
    keypoint_order: str = "mano",
    shape: Optional[jnp.ndarray] = None,   # [..., S] if already estimated
) -> dict:
    """Closed-form ``init`` dict for ``fit``/``fit_lm`` from 3D keypoints.

    Rigidly aligns the REST-pose skeleton (16 joints, plus fingertip
    vertices when ``tip_vertex_ids`` is given — same spec/order contract
    as the keypoint data terms) to the observed keypoints and returns
    ``{"pose": [..., 16, 3] zeros with the global row set,
    "trans": [..., 3]}`` — feed as ``fit(..., init=..., fit_trans=True)``
    or drop "trans" for origin-centered problems. Articulation stays at
    the rest pose: that is the solver's job; this gets it into the right
    basin in one SVD. Batched targets broadcast.
    """
    target_keypoints = jnp.asarray(target_keypoints)
    dtype = target_keypoints.dtype
    n_joints = params.j_regressor.shape[0]
    rest = _rest_forward(params, shape, dtype)
    rest_kp = core.keypoints(rest, tip_vertex_ids, keypoint_order) \
        if tip_vertex_ids is not None else rest.posed_joints
    if target_keypoints.shape[-2] != rest_kp.shape[-2]:
        raise ValueError(
            f"target has {target_keypoints.shape[-2]} keypoints but the "
            f"spec yields {rest_kp.shape[-2]} (16 joints"
            + (" + tips" if tip_vertex_ids is not None else
               "; pass tip_vertex_ids for 21-keypoint targets") + ")")

    return _init_from_pairs(rest, rest_kp, target_keypoints, n_joints)


def initialize_from_verts(
    params,
    target_verts: jnp.ndarray,       # [..., V, 3] full-mesh targets
    shape: Optional[jnp.ndarray] = None,
) -> dict:
    """Same closed form, seeded from DENSE correspondence: rest-pose
    vertices vs a full [V, 3] target mesh (the ``data_term="verts"``
    setting — every row is a correspondence, so the alignment is even
    better conditioned than the 16-joint one)."""
    target_verts = jnp.asarray(target_verts)
    dtype = target_verts.dtype
    n_joints = params.j_regressor.shape[0]
    rest = _rest_forward(params, shape, dtype)
    if target_verts.shape[-2] != rest.verts.shape[-2]:
        raise ValueError(
            f"target has {target_verts.shape[-2]} rows but the mesh has "
            f"{rest.verts.shape[-2]} vertices (for unstructured clouds "
            "use the ICP terms; Kabsch needs correspondences)")
    return _init_from_pairs(rest, rest.verts, target_verts, n_joints)


def _rest_forward(params, shape, dtype):
    """Rest-pose forward for the init seeds: shape [S], per-problem
    [B, S] (vmapped), or a named error."""
    n_joints = params.j_regressor.shape[0]
    n_shape = params.shape_basis.shape[-1]
    zero_pose = jnp.zeros((n_joints, 3), dtype)
    if shape is None:
        shape = jnp.zeros((n_shape,), dtype)
    shape = jnp.asarray(shape, dtype)
    if shape.ndim == 1:
        return core.forward(params, zero_pose, shape)
    if shape.ndim == 2:
        import jax

        return jax.vmap(lambda s: core.forward(params, zero_pose, s))(shape)
    raise ValueError(f"shape must be [S] or [B, S], got {shape.shape}")


def _init_from_pairs(rest, rest_points, target, n_joints) -> dict:
    """Kabsch on paired points -> the solver init dict (shared tail)."""
    dtype = target.dtype
    rot, tau = rigid_align(
        jnp.broadcast_to(rest_points, target.shape), target
    )
    global_aa = ops.axis_angle_from_matrix(rot)

    # The FK pivots the root rotation at the rest root joint j0, so the
    # Kabsch frame's tau converts via T = tau + R j0 - j0.
    j0 = rest.joints[..., 0, :].astype(dtype)
    trans = tau + jnp.einsum("...ab,...b->...a", rot, j0) - j0

    batch = target.shape[:-2]
    pose = jnp.zeros((*batch, n_joints, 3), dtype)
    pose = pose.at[..., 0, :].set(global_aa)
    return {"pose": pose, "trans": trans.astype(dtype)}
