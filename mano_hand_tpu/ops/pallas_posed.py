"""Pallas TPU kernel: fused SubjectTable gather + pose-only forward.

The serving engine's steady-state work is the gathered pose-only path
(``models/core.py:forward_posed_gather``): every interactive request is
"row ``r`` of the batch runs the pose stage over subject
``idx[r]``'s baked shape constants". That path is pure XLA today, while
the full forward's Pallas fusion measured 2.2x (docs/roadmap.md PR-10 /
ROADMAP item 2a). This kernel is its Pallas twin: ONE launch covers

    row gather   rows = onehot(idx) @ table_planes       (MXU, exact*)
    pose blend   v_posed = rows + deltas @ pose_basis2   (MXU)
    FK           level-parallel lane-slab compose        (VPU)
    skinning     12 skinny [TB, J] dots + FMA combine    (MXU + VPU)

per batch tile, structured the way ``forward_verts_fused_full``
(ops/pallas_forward.py) fused the full forward — the FK/Rodrigues slab
machinery (``_rodrigues_slabs``/``_fk_slabs``/``level_layout``) is
REUSED from there verbatim (imported, not copied; the mirrored
one-/two-hand launch pair itself is untouched, so the CLAUDE.md
lockstep contract is unaffected).

Design points specific to the gathered path:

* **The gather lives INSIDE the launch.** The packed per-subject planes
  (``[C, 3*VP]`` coordinate-major v_shaped rows, three ``[C, J]`` rest-
  joint slabs) are VMEM-resident operands with constant index maps —
  fetched once per launch — and each batch tile gathers its rows with a
  one-hot MXU matmul built in-register from the int32 index block
  (``broadcasted_iota == idx``). No per-row HBM gather slab ever
  exists: the XLA path materializes ``[B, V, 3]`` gathered rows in HBM
  (~9.3 KB/eval written + re-read); here per-eval HBM input traffic is
  pose (192 B) + index (4 B).
* **The one-hot gather is policy-exact.** A 0/1 one-hot splits as
  ``hi = onehot, lo = 0``, so the standard 3-pass bf16 decomposition
  (ops/common.kernel_dot's HIGH path) degenerates to
  ``onehot @ t_hi + onehot @ t_lo`` — it reconstructs the table's
  bf16-pair representation exactly (~4e-6 relative), with no
  single-pass bf16 rounding of the gathered VALUES. Gathers are
  hardwired to this 3-pass form at every precision (it is a data
  movement, not a contraction — running it below HIGH would round
  baked rows to bf16 and blow the 1e-5 parity budget).
* **Runtime arguments only.** The table and the ``[B]`` int32 subject
  index are runtime args exactly like the XLA gathered program: one
  compiled kernel per (capacity, bucket) serves EVERY subject mixture
  — a new subject costs zero recompiles (the PR-4 contract carried
  into the kernel tier).
* **Capacity is a VMEM budget.** The resident packed table costs
  ``C * 3 * VP * 4`` bytes (~10.5 KB/row at V=778) beside the ~1.5 MB
  pose basis; ``POSED_FUSED_MAX_CAPACITY`` (512 -> ~5.5 MB) keeps the
  launch inside the 16 MB scoped-vmem budget the block-size sweeps
  established. Above it the caller (ServingEngine) stays on the XLA
  gathered program. The one-hot gather's FLOPs also scale with C —
  another reason the big-capacity regime belongs to XLA until a chip
  sweep says otherwise.

Respected measured dead-ends (docs/roadmap.md): HIGH stays the 3-pass
bf16 decomposition (never in-kernel native HIGHEST), launches stay
<= 8192 rows (serving buckets are <= 1024), and the solvers stay on
XLA — this kernel serves inference only (no custom VJP).

Numerics contract: within ~1e-5 max abs err (f32) of
``core.forward_posed_gather`` per row — NOT bit-identical (the kernel's
3-pass MXU policy vs XLA's CPU f32 math), which is why the serving
engine keeps the fused tier OUT of the PR-6 AOT lattice (the lattice's
contract is bit-identity with the live jit of the XLA family) and
exports the tier to the PR-9 numerics sentinel so drift is judged
against a clean SAME-TRACE fused reference.

Reference semantics: pose blend + FK + LBS of
/root/reference/mano_np.py:87-115 over baked per-subject constants
(mano_np.py:81-83 baked at ``specialize`` time).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mano_hand_tpu.ops.common import (
    DEFAULT_PRECISION, LANE, SUBLANE, cdiv as _cdiv,
    dot3 as _dot3, kernel_dot, split_hi_lo as _split_hi_lo,
    split_hi_lo_xla,
)
from mano_hand_tpu.ops.pallas_forward import (
    _fk_slabs, _rodrigues_slabs, level_layout,
)

#: Largest SubjectTable capacity the fused kernel keeps VMEM-resident
#: (~5.5 MB of packed rows at V=778, beside the ~1.5 MB pose basis).
#: The serving engine falls back to the XLA gathered program above it.
POSED_FUSED_MAX_CAPACITY = 512

#: Batch tile for the fused gathered kernel. 64 mirrors the full-fusion
#: kernel's swept winner (core.FUSED_FULL_BEST_BLOCK_B — same skinny
#: skin-dot structure, same VMEM pressure class); the chip sweep for
#: THIS kernel is queued behind the tunnel (bench config14), and its
#: winner lands here, one line, when it runs.
POSED_FUSED_BEST_BLOCK_B = 64


def posed_fused_capacity_ok(capacity: int) -> bool:
    """Whether a table of ``capacity`` rows fits the fused kernel's
    VMEM residency budget — THE predicate the serving engine gates its
    kernel-tier selection on (one definition; the kernel launch raises
    on violation rather than letting Mosaic OOM obscurely)."""
    return capacity <= POSED_FUSED_MAX_CAPACITY


def posed_gather_operands(table):
    """Launch operands from a :class:`core.SubjectTable`, all f32:

    ``(tvs [C, 3*VP], (tjx, tjy, tjz) [C, J] x3, pose_basis2 [Pp, 3*VP],
    wt2 [J, VP])``

    ``tvs`` packs the baked v_shaped rows coordinate-major (the fused
    kernels' layout: three aligned V-planes per row, V padded to the
    lane width); the joint slabs and ``wt2`` carry the joint axis in
    ``level_layout`` order so FK composes on aligned lane slices;
    ``pose_basis2`` rows follow the in-kernel delta layout (ab-major,
    permuted non-root joints — the pose-row half of
    ``pallas_forward.fused_full_operands``, without the shape/template
    rows: the shape stage arrives pre-baked via the gathered planes).
    Built inside the caller's jit with the table as a runtime arg, so
    XLA hoists nothing subject-specific into the executable.
    """
    f32 = jnp.float32
    perm, _ = level_layout(tuple(table.parents))
    perm_arr = jnp.asarray(perm)
    c = table.capacity
    v = table.n_verts
    j = table.n_joints
    p = table.pose_basis.shape[-1]
    vp = _cdiv(v, LANE) * LANE
    pp = _cdiv(p, SUBLANE) * SUBLANE

    tvs = jnp.pad(
        jnp.asarray(table.v_shaped, f32).transpose(0, 2, 1),  # [C, 3, V]
        [(0, 0), (0, 0), (0, vp - v)],
    ).reshape(c, 3 * vp)

    tj = jnp.asarray(table.joints, f32)[:, perm_arr, :]       # [C, J, 3]
    tjx, tjy, tjz = tj[:, :, 0], tj[:, :, 1], tj[:, :, 2]

    # Pose rows: ab-major, joints in perm order (root excluded) — the
    # exact row order the in-kernel delta concat produces (see
    # pallas_forward.fused_full_operands for the original derivation).
    pb = jnp.asarray(table.pose_basis, f32).transpose(2, 1, 0)  # [P, 3, V]
    order = [
        (perm[pos] - 1) * 9 + 3 * a + b
        for a in range(3) for b in range(3)
        for pos in range(1, j)
    ]
    basis = pb[jnp.asarray(order, jnp.int32)]                 # [P, 3, V]
    pose_basis2 = jnp.pad(
        basis, [(0, pp - p), (0, 0), (0, vp - v)]
    ).reshape(pp, 3 * vp)

    wt2 = jnp.pad(
        jnp.asarray(table.lbs_weights, f32).T[perm_arr],
        [(0, 0), (0, vp - v)],
    )                                                         # [J, VP]
    return tvs, (tjx, tjy, tjz), pose_basis2, wt2


def _gather_dot(onehot, t_hi, t_lo):
    """One-hot row gather as a 3-pass MXU matmul — EXACT reconstruction
    of the (hi, lo) bf16 pair (the one-hot's own lo-split is
    identically zero, so the a_lo*b_hi pass vanishes and nothing is
    rounded through single-pass bf16). Hardwired at every precision:
    a gather is data movement, and running it below this fidelity
    would round the baked table rows themselves."""
    oh_hi, _ = _split_hi_lo(onehot)   # 0/1 are exact in bf16; lo == 0
    d = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    return d(oh_hi, t_hi) + d(oh_hi, t_lo)


def _posed_gather_kernel(vp, levels, precision, split, *refs):
    """One batch tile of the gathered pose-only forward: index + pose
    slabs in, vertex coordinate planes out.

    Blocks (constant index maps — resident across the launch): the
    packed table planes (pre-split to bf16 hi/lo pairs at the XLA level
    in BOTH modes — the gather consumes the pair directly, and
    re-splitting ``[C, 3*VP]`` per grid step would redo the work the
    full kernel's HIGH path moved out of the loop), the three joint
    slabs (f32 — tiny), the pose basis and skin weights (hi/lo pairs
    under HIGH, f32 otherwise). Per tile: idx [TB, 1] int32 and the
    three pose coordinate slabs [TB, J].
    """
    if split:
        (tvs_hi, tvs_lo, tjx, tjy, tjz,
         pb_hi, pb_lo, wt_hi, wt_lo, idx, x, y, z) = (r[:] for r in refs[:13])
        outs = refs[13:16]
    else:
        (tvs_hi, tvs_lo, tjx, tjy, tjz,
         pb_op, wt_op, idx, x, y, z) = (r[:] for r in refs[:11])
        outs = refs[11:14]

    tb = x.shape[0]
    c = tvs_hi.shape[0]
    # One-hot gather rows, built in-register from the index block. An
    # out-of-range index gathers zeros (the XLA path clamps instead) —
    # unreachable through the engine, which only hands out live slots.
    iota = jax.lax.broadcasted_iota(jnp.int32, (tb, c), 1)
    onehot = (iota == idx).astype(jnp.float32)               # [TB, C]
    vs_rows = _gather_dot(onehot, tvs_hi, tvs_lo)            # [TB, 3*VP]
    # Rest-joint slabs gather at AT LEAST the exact-for-one-hot 3-pass
    # HIGH form — a gather is data movement like the vertex planes
    # above, never a contraction to run at reduced precision. Under
    # the bf16 tier (precision None/DEFAULT, PR 14) a bare kernel_dot
    # would lower to a single-pass bf16 dot and round the baked rest
    # joints BEFORE forward kinematics, compounding along the chain —
    # the committed policy keeps FK inputs f32 (review finding).
    gp = precision
    if gp is None or jax.lax.Precision(gp) == jax.lax.Precision.DEFAULT:
        gp = jax.lax.Precision.HIGH
    jx = kernel_dot(onehot, tjx, gp)                         # [TB, J]
    jy = kernel_dot(onehot, tjy, gp)
    jz = kernel_dot(onehot, tjz, gp)

    r_local = _rodrigues_slabs(x, y, z)
    world_r, skin_t = _fk_slabs(r_local, jx, jy, jz, levels)

    # Pose-corrective deltas in-register: ab-major over non-root joints
    # in perm order — matching posed_gather_operands' basis row order.
    deltas = [
        r_local[3 * a + b][:, 1:] - (1.0 if a == b else 0.0)
        for a in range(3) for b in range(3)
    ]
    coeff = jnp.concatenate(deltas, axis=1)                  # [TB, 9(J-1)]
    pp = (pb_hi if split else pb_op).shape[0]
    pad = pp - coeff.shape[1]
    if pad:
        coeff = jnp.concatenate(
            [coeff, jnp.zeros((tb, pad), coeff.dtype)], axis=1)

    if split:
        c_hi, c_lo = _split_hi_lo(coeff)
        vp_flat = vs_rows + _dot3(c_hi, c_lo, pb_hi, pb_lo)

        def skin_dot(lhs):
            l_hi, l_lo = _split_hi_lo(lhs)
            return _dot3(l_hi, l_lo, wt_hi, wt_lo)
    else:
        vp_flat = vs_rows + kernel_dot(coeff, pb_op, precision)

        def skin_dot(lhs):
            return kernel_dot(lhs, wt_op, precision)

    for a in range(3):
        acc = skin_dot(skin_t[a])
        for cc in range(3):
            m_ac = skin_dot(world_r[3 * a + cc])
            acc = acc + m_ac * vp_flat[:, cc * vp:(cc + 1) * vp]
        outs[a][:] = acc


def forward_posed_gather_fused(
    table,                     # core.SubjectTable (runtime argument)
    subject_idx: jnp.ndarray,  # [B] int32 row indices into the table
    pose: jnp.ndarray,         # [B, J, 3] axis-angle (row 0 global)
    precision=DEFAULT_PRECISION,
    block_b: int = POSED_FUSED_BEST_BLOCK_B,
    interpret: bool = False,
    compute_dtype=None,
) -> jnp.ndarray:
    """Mixed-subject pose-only vertices [B, V, 3] in ONE kernel launch.

    The Pallas twin of ``core.forward_posed_gather`` (verts only): the
    SubjectTable row gather, pose-corrective blend, FK and skinning all
    run per batch tile in VMEM. The table and index are runtime
    arguments — one compiled program per (capacity, batch) shape serves
    every subject mixture, zero per-subject recompiles. Per-row
    numerics are within ~1e-5 (f32) of the XLA gathered program, not
    bit-identical (3-pass MXU policy vs XLA f32 — the parity gate in
    tests/test_pallas_posed.py and bench config14). Inference path
    only: no custom VJP (solvers stay on XLA — the measured fitting
    dead-end, docs/roadmap.md).

    ``compute_dtype`` (PR 14, the serving bf16 tier): ``bfloat16``
    maps the kernel onto its SINGLE-PASS bf16 MXU form — the pose
    blend and skinning dots run one bf16 pass each with f32
    accumulation (``kernel_dot``'s default branch), i.e. the hi/lo
    split and its 2 extra MXU passes are skipped entirely; the one-hot
    gather stays the exact 3-pass reconstruction (data movement, never
    rounded). Outputs stay f32. NOTE the interpret lane cannot see MXU
    rounding (``kernel_dot``'s documented limitation): off-chip this
    tier measures within f32 noise of HIGH; the ~bf16-level error
    (and the raw-speed win) appear on a real TPU only — exactly why
    the serving bf16 tier is sentinel-guarded against its
    PrecisionPolicy envelope rather than assumed.
    """
    if compute_dtype is not None:
        if jnp.dtype(compute_dtype) != jnp.bfloat16:
            raise ValueError(
                f"compute_dtype must be bfloat16 (the serving bf16 "
                f"tier) or None, got {compute_dtype}")
        # Single-pass bf16 MXU with f32 accumulation — the DEFAULT
        # precision branch of kernel_dot; HIGH's 3-pass decomposition
        # is precisely what the bf16 tier trades away.
        precision = None
    f32 = jnp.float32
    v = table.n_verts
    j = table.n_joints
    b = pose.shape[0]
    if b == 0:
        return jnp.zeros((0, v, 3), f32)
    c = table.capacity
    if not posed_fused_capacity_ok(c):
        raise ValueError(
            f"table capacity {c} exceeds the fused kernel's VMEM "
            f"residency budget (POSED_FUSED_MAX_CAPACITY="
            f"{POSED_FUSED_MAX_CAPACITY}); use core.forward_posed_gather "
            "(the XLA program) at this scale — the serving engine's "
            "capacity gate does exactly that")
    if b > 8192:
        raise ValueError(
            f"batch {b} exceeds the 8192-rows-per-launch measured "
            "dead-end (docs/roadmap.md); chunk upstream")
    perm, levels = level_layout(tuple(table.parents))
    tvs, (tjx, tjy, tjz), pose_basis2, wt2 = posed_gather_operands(table)

    pose_p = pose.reshape(b, j, 3).astype(f32)[:, jnp.asarray(perm), :]
    idx = jnp.asarray(subject_idx, jnp.int32).reshape(b, 1)

    block_b = max(1, min(block_b, b))
    bp = _cdiv(b, block_b) * block_b

    def padb(xarr):
        return jnp.pad(xarr, [(0, bp - b)] + [(0, 0)] * (xarr.ndim - 1))

    # Pad rows gather row 0 (always baked first) and are sliced off.
    idx = padb(idx)
    slabs = [padb(pose_p[:, :, cc]) for cc in range(3)]      # 3 x [Bp, J]

    vp = tvs.shape[1] // 3
    pp = pose_basis2.shape[0]
    grid = (bp // block_b,)
    const_tvs = pl.BlockSpec((c, 3 * vp), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)
    const_tj = pl.BlockSpec((c, j), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    const_pb = pl.BlockSpec((pp, 3 * vp), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    const_wt = pl.BlockSpec((j, vp), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    spec_idx = pl.BlockSpec((block_b, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    spec_bj = pl.BlockSpec((block_b, j), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_bv = pl.BlockSpec((block_b, vp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)

    # The packed table is pre-split to its bf16 (hi, lo) pair at the
    # XLA level in BOTH precision modes: the gather consumes the pair
    # directly (see _gather_dot) and the fold-proof split must not run
    # per grid step. MUST be split_hi_lo_xla (ops/common.py: the
    # convert-based split compiles to lo == 0 under XLA:TPU).
    tvs_hi, tvs_lo = split_hi_lo_xla(tvs)

    canon = (jax.lax.Precision(precision)
             if precision is not None else precision)
    split = canon == jax.lax.Precision.HIGH
    if split:
        pb_hi, pb_lo = split_hi_lo_xla(pose_basis2)
        wt_hi, wt_lo = split_hi_lo_xla(wt2)
        operands = (tvs_hi, tvs_lo, tjx, tjy, tjz,
                    pb_hi, pb_lo, wt_hi, wt_lo, idx, *slabs)
        in_specs = [const_tvs, const_tvs, const_tj, const_tj, const_tj,
                    const_pb, const_pb, const_wt, const_wt, spec_idx,
                    *([spec_bj] * 3)]
    else:
        operands = (tvs_hi, tvs_lo, tjx, tjy, tjz,
                    pose_basis2, wt2, idx, *slabs)
        in_specs = [const_tvs, const_tvs, const_tj, const_tj, const_tj,
                    const_pb, const_wt, spec_idx,
                    *([spec_bj] * 3)]
    outs = pl.pallas_call(
        functools.partial(_posed_gather_kernel, vp, levels,
                          precision, split),
        grid=grid,
        in_specs=in_specs,
        out_specs=[spec_bv] * 3,
        out_shape=[jax.ShapeDtypeStruct((bp, vp), jnp.float32)] * 3,
        interpret=interpret,
    )(*operands)
    return jnp.stack(outs, axis=-1)[:b, :v, :]
