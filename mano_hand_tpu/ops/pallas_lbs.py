"""Pallas TPU kernel: fused linear blend skinning.

One kernel computes, per (batch-tile, vertex-tile):

    M      = R_flat @ W^T        [9, TV]  (MXU, contraction over J=16)
    T_blend= T^T    @ W^T        [3, TV]
    out[a] = sum_c M[3a+c] * vp[c] + T_blend[a]          (VPU)

so the blended per-vertex rotations never round-trip through HBM — the XLA
einsum path (ops/lbs.py) materializes the [B, V, 9] blend tensor (~229 MB at
B=8192), this kernel keeps it in VMEM tiles.

Layout is lane-friendly: vertices ride the 128-wide lane dimension, the tiny
3/9/16-sized axes sit on sublanes. Inputs are transposed at the JAX level
(XLA fuses the transposes into the surrounding pads/copies).

``skin_batched`` is the raw forward kernel; ``skin_batched_ad`` wraps it in
a custom VJP so the Pallas path composes with jax.grad. The backward pass
reuses the SAME kernel for the vertex cotangent (LBS is linear in v_posed
with blended matrix M, so dL/dvp = M^T g — i.e. the forward kernel with
transposed rotations and zero translations), and small einsums for the
per-joint cotangents. Numerics: f32 accumulate via preferred_element_type
(matches Precision.HIGHEST on the einsum path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _skin_kernel(wt_ref, rt_ref, tt_ref, vpt_ref, out_ref):
    """Blocks: wt [J, TV], rt [TB, 9, J], tt [TB, 3, J], vpt [TB, 3, TV],
    out [TB, 3, TV]."""
    tb = rt_ref.shape[0]
    j = wt_ref.shape[0]
    wt = wt_ref[:]                                        # [J, TV]
    m = jnp.dot(
        rt_ref[:].reshape(tb * 9, j), wt,
        preferred_element_type=jnp.float32,
    ).reshape(tb, 9, -1)                                  # [TB, 9, TV]
    t_blend = jnp.dot(
        tt_ref[:].reshape(tb * 3, j), wt,
        preferred_element_type=jnp.float32,
    ).reshape(tb, 3, -1)                                  # [TB, 3, TV]
    vp = vpt_ref[:]                                       # [TB, 3, TV]
    for a in range(3):
        acc = t_blend[:, a, :]
        for c in range(3):
            acc = acc + m[:, 3 * a + c, :] * vp[:, c, :]
        out_ref[:, a, :] = acc


@functools.partial(
    jax.jit, static_argnames=("block_b", "block_v", "interpret")
)
def skin_batched(
    weights: jnp.ndarray,    # [V, J] LBS weights
    world_rot: jnp.ndarray,  # [B, J, 3, 3] skinning rotations
    skin_t: jnp.ndarray,     # [B, J, 3] skinning translations
    v_posed: jnp.ndarray,    # [B, V, 3] blendshaped rest-pose verts
    block_b: int = 32,
    block_v: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Batched fused LBS: [B, V, 3] skinned vertices.

    Semantics identical to vmap(ops.lbs.skin) over the batch axis.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    b, v, j = v_posed.shape[0], weights.shape[0], weights.shape[1]
    f32 = jnp.float32
    bp, vp_ = _cdiv(b, block_b) * block_b, _cdiv(v, block_v) * block_v

    wt = jnp.pad(weights.astype(f32).T, [(0, 0), (0, vp_ - v)])     # [J, Vp]
    rt = jnp.pad(
        world_rot.astype(f32).reshape(b, j, 9).transpose(0, 2, 1),
        [(0, bp - b), (0, 0), (0, 0)],
    )                                                               # [Bp,9,J]
    tt = jnp.pad(
        skin_t.astype(f32).transpose(0, 2, 1), [(0, bp - b), (0, 0), (0, 0)]
    )                                                               # [Bp,3,J]
    vpt = jnp.pad(
        v_posed.astype(f32).transpose(0, 2, 1),
        [(0, bp - b), (0, 0), (0, vp_ - v)],
    )                                                               # [Bp,3,Vp]

    grid = (bp // block_b, vp_ // block_v)
    out = pl.pallas_call(
        _skin_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((j, block_v), lambda i, k: (0, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 9, j), lambda i, k: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 3, j), lambda i, k: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_b, 3, block_v), lambda i, k: (i, 0, k),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_b, 3, block_v), lambda i, k: (i, 0, k),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bp, 3, vp_), f32),
        interpret=interpret,
    )(wt, rt, tt, vpt)
    return out[:b].transpose(0, 2, 1)[:, :v]


# ---------------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def skin_batched_ad(
    weights, world_rot, skin_t, v_posed,
    block_b: int = 32, block_v: int = 128, interpret: bool = False,
):
    """Differentiable fused LBS: Pallas forward, composed VJP backward."""
    return skin_batched(
        weights, world_rot, skin_t, v_posed,
        block_b=block_b, block_v=block_v, interpret=interpret,
    )


def _skin_fwd(weights, world_rot, skin_t, v_posed,
              block_b, block_v, interpret):
    out = skin_batched(
        weights, world_rot, skin_t, v_posed,
        block_b=block_b, block_v=block_v, interpret=interpret,
    )
    return out, (weights, world_rot, skin_t, v_posed)


def _skin_bwd(block_b, block_v, interpret, residuals, g):
    weights, world_rot, skin_t, v_posed = residuals
    g = g.astype(jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    # dL/dvp[b,v,c] = sum_j w[v,j] sum_a R[b,j,a,c] g[b,v,a]: the forward
    # kernel applied to g with R transposed and t = 0.
    grad_vp = skin_batched(
        weights, world_rot.transpose(0, 1, 3, 2),
        jnp.zeros_like(skin_t), g,
        block_b=block_b, block_v=block_v, interpret=interpret,
    )
    # The largest backward intermediate is outer [B, V, 3, 3] (9BV floats,
    # shared by grad_rot and grad_w) — the same bound as the einsum path's
    # autodiff, with no [B, V, J, *] tensor anywhere. Fitting-scale batches
    # are the intended consumers of this gradient.
    outer = g[..., :, None] * v_posed[..., None, :]        # [B, V, 3, 3]
    grad_rot = jnp.einsum("vj,bvac->bjac", weights, outer, precision=hi)
    grad_t = jnp.einsum("vj,bva->bja", weights, g, precision=hi)
    # dL/dw[v,j] = sum_{b,a,c} outer[b,v,a,c] R[b,j,a,c]
    #           + sum_{b,a} g[b,v,a] t[b,j,a]
    grad_w = (
        jnp.einsum("bvac,bjac->vj", outer, world_rot, precision=hi)
        + jnp.einsum("bva,bja->vj", g, skin_t, precision=hi)
    )
    return (
        grad_w.astype(weights.dtype),
        grad_rot.astype(world_rot.dtype),
        grad_t.astype(skin_t.dtype),
        grad_vp.astype(v_posed.dtype),
    )


skin_batched_ad.defvjp(_skin_fwd, _skin_bwd)
