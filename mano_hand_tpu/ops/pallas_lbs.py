"""Pallas TPU kernel: fused linear blend skinning.

One kernel computes, per (batch-tile, vertex-tile), for a in 0..2:

    M_ac   = r_ac @ W^T          [TB, TV]  (MXU, contraction over J=16)
    out_a  = t_a @ W^T + sum_c M_ac * v_c  (VPU FMAs)

so the blended per-vertex rotations never round-trip through HBM — the XLA
einsum path (ops/lbs.py) materializes the [B, V, 9] blend tensor (~229 MB at
B=8192), this kernel keeps it in VMEM tiles.

Layout is lane-friendly: vertices ride the 128-wide lane dimension, the tiny
3/9/16-sized axes either sit on sublanes or are split into separate 2-D
operands at the JAX level (nine rotation-component slabs, three translation
slabs, three coordinate planes) so every ref the kernel touches is plain
2-D — the shapes Mosaic lowers most reliably, with no in-kernel reshapes.
XLA fuses the slab slicing into the surrounding pads/copies.

``skin_batched`` is the raw forward kernel; ``skin_batched_ad`` wraps it in
a custom VJP so the Pallas path composes with jax.grad. The backward pass
reuses the SAME kernel for the vertex cotangent (LBS is linear in v_posed
with blended matrix M, so dL/dvp = M^T g — i.e. the forward kernel with
transposed rotations and zero translations), and small einsums for the
per-joint cotangents. Numerics: f32 accumulate via preferred_element_type
(matches Precision.HIGHEST on the einsum path).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mano_hand_tpu.ops.common import (
    DEFAULT_PRECISION, cdiv as _cdiv, kernel_dot,
)


def _skin_kernel(precision, wt_ref, *refs):
    """All-2-D blocks (the shapes Mosaic lowers most reliably — no in-kernel
    reshapes or >2-D relayouts): wt [J, TV]; nine rotation-component slabs
    r_ac [TB, J]; three translation slabs t_a [TB, J]; three rest-coordinate
    planes v_c [TB, TV]; three output planes o_a [TB, TV].

        M_ac    = r_ac @ W^T   [TB, TV]   (MXU, contraction over J)
        o_a     = t_a @ W^T + sum_c M_ac * v_c          (VPU FMAs)
    """
    r = refs[0:9]
    t = refs[9:12]
    v = refs[12:15]
    o = refs[15:18]
    wt = wt_ref[:]                                        # [J, TV]
    for a in range(3):
        acc = kernel_dot(t[a][:], wt, precision)
        for c in range(3):
            m_ac = kernel_dot(r[3 * a + c][:], wt, precision)
            acc = acc + m_ac * v[c][:]
        o[a][:] = acc


@functools.partial(
    jax.jit,
    static_argnames=("block_b", "block_v", "interpret", "precision"),
)
def skin_batched(
    weights: jnp.ndarray,    # [V, J] LBS weights
    world_rot: jnp.ndarray,  # [B, J, 3, 3] skinning rotations
    skin_t: jnp.ndarray,     # [B, J, 3] skinning translations
    v_posed: jnp.ndarray,    # [B, V, 3] blendshaped rest-pose verts
    block_b: int = 32,
    block_v: int = 128,
    interpret: bool = False,
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Batched fused LBS: [B, V, 3] skinned vertices.

    Semantics identical to vmap(ops.lbs.skin) over the batch axis, INCLUDING
    the contraction precision (see ops.common.kernel_dot — a bare in-kernel
    dot would silently run single-pass bf16 and fail the 1e-4 gate).
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    b, v, j = v_posed.shape[0], weights.shape[0], weights.shape[1]
    f32 = jnp.float32
    bp, vp_ = _cdiv(b, block_b) * block_b, _cdiv(v, block_v) * block_v

    def padb(x):  # pad the batch axis of a [B, ...] array
        return jnp.pad(x, [(0, bp - b)] + [(0, 0)] * (x.ndim - 1))

    wt = jnp.pad(weights.astype(f32).T, [(0, 0), (0, vp_ - v)])     # [J, Vp]
    rot = padb(world_rot.astype(f32))                               # [Bp,J,3,3]
    st = padb(skin_t.astype(f32))                                   # [Bp,J,3]
    r_slabs = [rot[:, :, a, c] for a in range(3) for c in range(3)]  # 9x[Bp,J]
    t_slabs = [st[:, :, a] for a in range(3)]                        # 3x[Bp,J]
    vp_pad = jnp.pad(
        v_posed.astype(f32), [(0, bp - b), (0, vp_ - v), (0, 0)]
    )
    v_slabs = [vp_pad[:, :, c] for c in range(3)]                   # 3x[Bp,Vp]

    grid = (bp // block_b, vp_ // block_v)
    spec_bj = pl.BlockSpec((block_b, j), lambda i, k: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_bv = pl.BlockSpec((block_b, block_v), lambda i, k: (i, k),
                           memory_space=pltpu.VMEM)
    outs = pl.pallas_call(
        functools.partial(_skin_kernel, precision),
        grid=grid,
        in_specs=[
            pl.BlockSpec((j, block_v), lambda i, k: (0, k),
                         memory_space=pltpu.VMEM),
            *([spec_bj] * 12),
            *([spec_bv] * 3),
        ],
        out_specs=[spec_bv] * 3,
        out_shape=[jax.ShapeDtypeStruct((bp, vp_), f32)] * 3,
        interpret=interpret,
    )(wt, *r_slabs, *t_slabs, *v_slabs)
    return jnp.stack(outs, axis=-1)[:b, :v, :]


# ---------------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def skin_batched_ad(
    weights, world_rot, skin_t, v_posed,
    block_b: int = 32, block_v: int = 128, interpret: bool = False,
    precision=DEFAULT_PRECISION,
):
    """Differentiable fused LBS: Pallas forward, composed VJP backward."""
    return skin_batched(
        weights, world_rot, skin_t, v_posed,
        block_b=block_b, block_v=block_v, interpret=interpret,
        precision=precision,
    )


def _skin_fwd(weights, world_rot, skin_t, v_posed,
              block_b, block_v, interpret, precision):
    out = skin_batched(
        weights, world_rot, skin_t, v_posed,
        block_b=block_b, block_v=block_v, interpret=interpret,
        precision=precision,
    )
    return out, (weights, world_rot, skin_t, v_posed)


def _skin_bwd(block_b, block_v, interpret, precision, residuals, g):
    weights, world_rot, skin_t, v_posed = residuals
    g = g.astype(jnp.float32)
    hi = jax.lax.Precision.HIGHEST
    # dL/dvp[b,v,c] = sum_j w[v,j] sum_a R[b,j,a,c] g[b,v,a]: the forward
    # kernel applied to g with R transposed and t = 0.
    grad_vp = skin_batched(
        weights, world_rot.transpose(0, 1, 3, 2),
        jnp.zeros_like(skin_t), g,
        block_b=block_b, block_v=block_v, interpret=interpret,
        precision=precision,
    )
    # The largest backward intermediate is outer [B, V, 3, 3] (9BV floats,
    # shared by grad_rot and grad_w) — the same bound as the einsum path's
    # autodiff, with no [B, V, J, *] tensor anywhere. Fitting-scale batches
    # are the intended consumers of this gradient.
    outer = g[..., :, None] * v_posed[..., None, :]        # [B, V, 3, 3]
    grad_rot = jnp.einsum("vj,bvac->bjac", weights, outer, precision=hi)
    grad_t = jnp.einsum("vj,bva->bja", weights, g, precision=hi)
    # dL/dw[v,j] = sum_{b,a,c} outer[b,v,a,c] R[b,j,a,c]
    #           + sum_{b,a} g[b,v,a] t[b,j,a]
    grad_w = (
        jnp.einsum("bvac,bjac->vj", outer, world_rot, precision=hi)
        + jnp.einsum("bva,bja->vj", g, skin_t, precision=hi)
    )
    return (
        grad_w.astype(weights.dtype),
        grad_rot.astype(world_rot.dtype),
        grad_t.astype(skin_t.dtype),
        grad_vp.astype(v_posed.dtype),
    )


skin_batched_ad.defvjp(_skin_fwd, _skin_bwd)
