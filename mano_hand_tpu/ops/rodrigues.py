"""Autodiff-safe Rodrigues rotations (axis-angle -> SO(3)).

The reference clamps theta to float64 eps before dividing
(/root/reference/mano_np.py:130-133), which is value-safe but leaves
``d‖r‖/dr`` NaN at r = 0 under autodiff — fatal for pose fitting that
initializes at the zero pose. We instead use the unnormalized form

    R = I + a(theta) * K + b(theta) * K @ K,   K = skew(r)

with a = sin(theta)/theta and b = (1 - cos(theta))/theta^2 computed through
Taylor guards, so R and all its derivatives are finite and smooth at
theta = 0. For theta > sqrt(eps) this is algebraically identical to the
reference formula cos*I + (1-cos)*rr^T + sin*K(r_hat).
"""

from __future__ import annotations

import jax.numpy as jnp

# Below this theta^2, the Taylor series is more accurate than the closed
# form in f32 *and* keeps gradients finite.
_SMALL = 1e-8


def skew(r: jnp.ndarray) -> jnp.ndarray:
    """[..., 3] -> [..., 3, 3] cross-product (skew-symmetric) matrices."""
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    zero = jnp.zeros_like(x)
    return jnp.stack(
        [zero, -z, y, z, zero, -x, -y, x, zero], axis=-1
    ).reshape(*r.shape[:-1], 3, 3)


def rotation_matrix(axis_angle: jnp.ndarray) -> jnp.ndarray:
    """Axis-angle [..., 3] -> rotation matrices [..., 3, 3].

    Fully differentiable everywhere, including the zero vector.
    """
    theta2 = jnp.sum(axis_angle * axis_angle, axis=-1)[..., None, None]
    small = theta2 < _SMALL
    # Guard the sqrt so its gradient never sees 0.
    theta = jnp.sqrt(jnp.where(small, 1.0, theta2))
    a = jnp.where(small, 1.0 - theta2 / 6.0 + theta2 * theta2 / 120.0,
                  jnp.sin(theta) / theta)
    # Denominator uses the guarded theta so the unselected branch stays
    # finite — the double-where rule: NaN in a dead branch still poisons
    # gradients through jnp.where.
    b = jnp.where(small, 0.5 - theta2 / 24.0 + theta2 * theta2 / 720.0,
                  (1.0 - jnp.cos(theta)) / (theta * theta))
    K = skew(axis_angle)
    # K @ K == r r^T - |r|^2 I exactly; the outer-product form stays on the
    # VPU in full precision (a 3x3 matmul would ride the MXU's bf16 default
    # on TPU and cost ~1e-2 absolute error in the rotation entries).
    outer = axis_angle[..., :, None] * axis_angle[..., None, :]
    eye = jnp.broadcast_to(jnp.eye(3, dtype=axis_angle.dtype), K.shape)
    return (1.0 - b * theta2) * eye + a * K + b * outer


def matrix_from_6d(x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """6D rotation representation [..., 6] -> rotation matrices [..., 3, 3].

    The continuous representation of Zhou et al., "On the Continuity of
    Rotation Representations in Neural Networks" (CVPR 2019): the first two
    columns of a rotation matrix, re-orthonormalized by Gram-Schmidt, the
    third their cross product. Continuous and surjective onto SO(3) — the
    standard parameterization for gradient-based rotation estimation (no
    axis-angle 2*pi wrap, no quaternion double cover).

    CONVENTION: the 6 numbers are the first two COLUMNS of R (the paper's
    formulation). pytorch3d's ``rotation_6d_to_matrix`` uses the first two
    ROWS instead — a pytorch3d-trained regressor's 6D output decodes here
    to R^T (the inverse rotation). Port such outputs with
    ``matrix_to_6d(pytorch3d_matrix)`` or transpose before re-encoding.
    """
    a1, a2 = x[..., 0:3], x[..., 3:6]
    n1 = jnp.sqrt(jnp.sum(a1 * a1, axis=-1, keepdims=True) + eps)
    b1 = a1 / n1
    a2p = a2 - jnp.sum(b1 * a2, axis=-1, keepdims=True) * b1
    n2 = jnp.sqrt(jnp.sum(a2p * a2p, axis=-1, keepdims=True) + eps)
    b2 = a2p / n2
    b3 = jnp.cross(b1, b2)
    return jnp.stack([b1, b2, b3], axis=-1)  # columns


def matrix_to_6d(rot: jnp.ndarray) -> jnp.ndarray:
    """Rotation matrices [..., 3, 3] -> 6D representation [..., 6].

    Inverse of ``matrix_from_6d`` on SO(3): the first two COLUMNS,
    flattened. ``matrix_from_6d(matrix_to_6d(R)) == R`` for orthonormal R.
    (Column convention — differs from pytorch3d's row convention; see
    ``matrix_from_6d``.)
    """
    return jnp.concatenate([rot[..., :, 0], rot[..., :, 1]], axis=-1)


def axis_angle_from_matrix(rot: jnp.ndarray) -> jnp.ndarray:
    """SO(3) log map: rotation matrices [..., 3, 3] -> axis-angle [..., 3].

    Inverse of ``rotation_matrix`` up to the usual angle wrap: output angle
    lies in [0, pi]. Three guarded regimes (all jnp.where-safe for tracing):

      * small angle  — vec/2 with a Taylor correction (vec = 2 sin(t) axis),
      * generic      — theta * vec / (2 sin(theta)),
      * near pi      — sin(theta) -> 0 kills vec, so the axis is recovered
        from the symmetric part: (R + I)/2 == axis axis^T at theta == pi;
        magnitudes from the diagonal, signs from the row of the largest
        diagonal entry (whose own sign is fixed positive — the axis at pi
        is only defined up to global sign anyway).

    Intended for decoding results (e.g. 6D-space fits back to the
    reference's axis-angle convention); like every log map it is not
    differentiable AT theta == pi (the rotation itself is — the chart is).
    """
    vec = jnp.stack(
        [
            rot[..., 2, 1] - rot[..., 1, 2],
            rot[..., 0, 2] - rot[..., 2, 0],
            rot[..., 1, 0] - rot[..., 0, 1],
        ],
        axis=-1,
    )                                            # 2 sin(theta) * axis
    trace = rot[..., 0, 0] + rot[..., 1, 1] + rot[..., 2, 2]
    cos_t = jnp.clip((trace - 1.0) * 0.5, -1.0, 1.0)[..., None]
    theta = jnp.arccos(cos_t)
    sin_t = jnp.sqrt(jnp.clip(1.0 - cos_t * cos_t, 0.0, 1.0))

    small = theta < 1e-3
    near_pi = theta > jnp.pi - 1e-3
    generic = ~(small | near_pi)
    # Guarded denominator: dead branches must stay finite (double-where).
    safe_sin = jnp.where(generic, sin_t, 1.0)
    aa_generic = vec * (theta / (2.0 * safe_sin))
    t2 = theta * theta
    aa_small = vec * 0.5 * (1.0 + t2 / 6.0 + 7.0 * t2 * t2 / 360.0)

    # Near pi: (R + I)/2 ~= axis axis^T. Take magnitudes from the diagonal;
    # align signs with the row of the largest diagonal entry.
    sym = 0.5 * (rot + jnp.swapaxes(rot, -1, -2))
    m = 0.5 * (sym + jnp.eye(3, dtype=rot.dtype))
    diag = jnp.clip(
        jnp.stack([m[..., 0, 0], m[..., 1, 1], m[..., 2, 2]], axis=-1),
        0.0, 1.0,
    )
    k = jnp.argmax(diag, axis=-1)
    row = jnp.take_along_axis(
        m, k[..., None, None] ,
        axis=-2,
    )[..., 0, :]                                  # [..., 3] = a_k * axis
    axis_pi = row / jnp.sqrt(
        jnp.clip(
            jnp.take_along_axis(diag, k[..., None], axis=-1), 1e-12, 1.0
        )
    )
    norm = jnp.sqrt(
        jnp.clip(jnp.sum(axis_pi * axis_pi, axis=-1, keepdims=True),
                 1e-12, None)
    )
    # For theta strictly below pi, vec = 2 sin(theta) axis still carries the
    # true sign — align with it so the chart is continuous up to pi (the
    # largest-diagonal convention alone would flip the axis for rotations
    # whose dominant axis component is negative). Only AT pi (vec == 0)
    # does the global-sign ambiguity remain, and there any sign is correct.
    align = jnp.sum(axis_pi * vec, axis=-1, keepdims=True)
    sign = jnp.where(jnp.abs(align) > 1e-12, jnp.sign(align), 1.0)
    aa_pi = axis_pi * sign / norm * theta

    return jnp.where(small, aa_small, jnp.where(near_pi, aa_pi, aa_generic))


def matrix_from_quaternion(q: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """Quaternions [..., 4] (scalar-first: w, x, y, z) -> [..., 3, 3].

    Inputs are normalized first (regressor outputs and interpolated mocap
    quats are rarely exactly unit), so any nonzero 4-vector maps onto
    SO(3); q and -q give the same rotation (double cover). Matches the
    convention of ``anim``'s slerp helpers.
    """
    q = q / jnp.sqrt(jnp.sum(q * q, axis=-1, keepdims=True) + eps)
    w, x, y, z = q[..., 0], q[..., 1], q[..., 2], q[..., 3]
    return jnp.stack(
        [
            1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y),
            2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x),
            2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y),
        ],
        axis=-1,
    ).reshape(*q.shape[:-1], 3, 3)
