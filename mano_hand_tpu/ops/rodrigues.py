"""Autodiff-safe Rodrigues rotations (axis-angle -> SO(3)).

The reference clamps theta to float64 eps before dividing
(/root/reference/mano_np.py:130-133), which is value-safe but leaves
``d‖r‖/dr`` NaN at r = 0 under autodiff — fatal for pose fitting that
initializes at the zero pose. We instead use the unnormalized form

    R = I + a(theta) * K + b(theta) * K @ K,   K = skew(r)

with a = sin(theta)/theta and b = (1 - cos(theta))/theta^2 computed through
Taylor guards, so R and all its derivatives are finite and smooth at
theta = 0. For theta > sqrt(eps) this is algebraically identical to the
reference formula cos*I + (1-cos)*rr^T + sin*K(r_hat).
"""

from __future__ import annotations

import jax.numpy as jnp

# Below this theta^2, the Taylor series is more accurate than the closed
# form in f32 *and* keeps gradients finite.
_SMALL = 1e-8


def skew(r: jnp.ndarray) -> jnp.ndarray:
    """[..., 3] -> [..., 3, 3] cross-product (skew-symmetric) matrices."""
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    zero = jnp.zeros_like(x)
    return jnp.stack(
        [zero, -z, y, z, zero, -x, -y, x, zero], axis=-1
    ).reshape(*r.shape[:-1], 3, 3)


def rotation_matrix(axis_angle: jnp.ndarray) -> jnp.ndarray:
    """Axis-angle [..., 3] -> rotation matrices [..., 3, 3].

    Fully differentiable everywhere, including the zero vector.
    """
    theta2 = jnp.sum(axis_angle * axis_angle, axis=-1)[..., None, None]
    small = theta2 < _SMALL
    # Guard the sqrt so its gradient never sees 0.
    theta = jnp.sqrt(jnp.where(small, 1.0, theta2))
    a = jnp.where(small, 1.0 - theta2 / 6.0 + theta2 * theta2 / 120.0,
                  jnp.sin(theta) / theta)
    # Denominator uses the guarded theta so the unselected branch stays
    # finite — the double-where rule: NaN in a dead branch still poisons
    # gradients through jnp.where.
    b = jnp.where(small, 0.5 - theta2 / 24.0 + theta2 * theta2 / 720.0,
                  (1.0 - jnp.cos(theta)) / (theta * theta))
    K = skew(axis_angle)
    # K @ K == r r^T - |r|^2 I exactly; the outer-product form stays on the
    # VPU in full precision (a 3x3 matmul would ride the MXU's bf16 default
    # on TPU and cost ~1e-2 absolute error in the rotation entries).
    outer = axis_angle[..., :, None] * axis_angle[..., None, :]
    eye = jnp.broadcast_to(jnp.eye(3, dtype=axis_angle.dtype), K.shape)
    return (1.0 - b * theta2) * eye + a * K + b * outer


def matrix_from_6d(x: jnp.ndarray, eps: float = 1e-12) -> jnp.ndarray:
    """6D rotation representation [..., 6] -> rotation matrices [..., 3, 3].

    The continuous representation of Zhou et al., "On the Continuity of
    Rotation Representations in Neural Networks" (CVPR 2019): the first two
    columns of a rotation matrix, re-orthonormalized by Gram-Schmidt, the
    third their cross product. Continuous and surjective onto SO(3) — the
    standard parameterization for gradient-based rotation estimation (no
    axis-angle 2*pi wrap, no quaternion double cover).
    """
    a1, a2 = x[..., 0:3], x[..., 3:6]
    n1 = jnp.sqrt(jnp.sum(a1 * a1, axis=-1, keepdims=True) + eps)
    b1 = a1 / n1
    a2p = a2 - jnp.sum(b1 * a2, axis=-1, keepdims=True) * b1
    n2 = jnp.sqrt(jnp.sum(a2p * a2p, axis=-1, keepdims=True) + eps)
    b2 = a2p / n2
    b3 = jnp.cross(b1, b2)
    return jnp.stack([b1, b2, b3], axis=-1)  # columns


def matrix_to_6d(rot: jnp.ndarray) -> jnp.ndarray:
    """Rotation matrices [..., 3, 3] -> 6D representation [..., 6].

    Inverse of ``matrix_from_6d`` on SO(3): the first two COLUMNS,
    flattened. ``matrix_from_6d(matrix_to_6d(R)) == R`` for orthonormal R.
    """
    return jnp.concatenate([rot[..., :, 0], rot[..., :, 1]], axis=-1)
