"""Pallas TPU kernel: fully-fused MANO vertex forward (blendshapes + LBS).

The split pipeline (``models/core.py:forward_batched_pallas``) runs the
vertex blendshape matmul in XLA, writes ``v_posed [B, V, 3]`` to HBM, and
re-reads it inside the skinning kernel — ~19 KB of HBM round-trip per eval
that exists only because the two stages live in different programs. This
kernel fuses them: one Pallas program computes

    v_posed = coeff_aug @ basis_aug          [TB, 3*VP]   (MXU)
    M_ac    = r_ac @ W^T                     [TB, VP]     (MXU)
    out_a   = t_a @ W^T + sum_c M_ac * v_c                (VPU FMAs)

per batch tile, so blended vertices never leave VMEM between blending and
skinning. Design points:

* **Coordinate-major vertex layout.** The flat vertex axis is laid out as
  three V-planes (``c * VP + v``, VP = V padded to the 128 lane width)
  instead of interleaved ``v * 3 + c``; each coordinate plane is then an
  aligned lane-slice of the matmul output — no strided access, no in-kernel
  reshapes (the layouts Mosaic lowers most reliably).
* **Template via augmentation.** The rest template is appended as one extra
  basis row driven by a constant-1 coefficient column, so "template + blend
  offsets" is a single MXU contraction with no broadcast-add operand.
* **Basis resident in VMEM.** The grid iterates over batch tiles only; the
  ``[K+1, 3*VP]`` basis and ``[J, VP]`` weight blocks have constant index
  maps, so Pallas fetches them once per launch (~1.7 MB) and every batch
  tile reuses them from VMEM.

Per-eval HBM traffic drops to coeff + (R, t) slabs + output verts
(~12 KB) vs ~30 KB for the split path; FLOPs are unchanged (the blend
matmul pays ~15% lane padding at V=778 -> 896).

Reference semantics being fused: blendshapes /root/reference/mano_np.py:81-91
and skinning /root/reference/mano_np.py:112-115, with the [B, V, 4, 4]
transform materialization of the latter eliminated (see ops/pallas_lbs.py).

``forward_verts_fused`` is the raw forward; ``forward_verts_fused_ad``
carries a custom VJP (backward reuses the skinning kernel for the vertex
cotangent and one MXU matmul for the coefficient cotangent).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu import ops
from mano_hand_tpu.ops import pallas_lbs
from mano_hand_tpu.ops.common import (
    DEFAULT_PRECISION, LANE, SUBLANE, cdiv as _cdiv,
    dot3 as _dot3, kernel_dot, split_hi_lo as _split_hi_lo,
    split_hi_lo_xla,
)


def vertex_operands(params: ManoParams):
    """Kernel-side derived tensors: ``(basis_aug [Kp, 3*VP], wt [J, VP])``.

    Kp = S + P + 1 rounded up to the sublane height; the extra row is the
    rest template (augmentation trick), extra padding rows are zero.
    """
    f32 = jnp.float32
    v, _, s = params.shape_basis.shape
    p = params.pose_basis.shape[-1]
    k = s + p + 1
    kp = _cdiv(k, SUBLANE) * SUBLANE
    vp = _cdiv(v, LANE) * LANE
    # Rows of the augmented basis, coordinate-major: [K, 3, V].
    # (jnp coercion first: leaves can arrive as plain host arrays, e.g.
    # inside custom_vjp backward passes.)
    shape_basis = jnp.asarray(params.shape_basis, f32)
    pose_basis = jnp.asarray(params.pose_basis, f32)
    v_template = jnp.asarray(params.v_template, f32)
    basis = jnp.concatenate(
        [
            shape_basis.transpose(2, 1, 0),                      # [S, 3, V]
            pose_basis.transpose(2, 1, 0),                       # [P, 3, V]
            v_template.T[None],                                  # [1, 3, V]
        ],
        axis=0,
    )
    basis_aug = jnp.pad(
        basis, [(0, kp - k), (0, 0), (0, vp - v)]
    ).reshape(kp, 3 * vp)
    wt = jnp.pad(
        jnp.asarray(params.lbs_weights, f32).T, [(0, 0), (0, vp - v)]
    )                                                            # [J, VP]
    return basis_aug, wt


def joint_operands(params: ManoParams, precision=DEFAULT_PRECISION):
    """Pre-stage derived tensors: ``(joint_template [J, 3],
    joint_shape_basis [J, 3, S])`` — joint regression precomposed with the
    shape basis exactly as in ``core.fused_blend_bases``."""
    f32 = jnp.float32
    j_regressor = jnp.asarray(params.j_regressor, f32)
    joint_template = jnp.einsum(
        "jv,vc->jc", j_regressor,
        jnp.asarray(params.v_template, f32), precision=precision,
    )
    joint_shape_basis = jnp.einsum(
        "jv,vcs->jcs", j_regressor,
        jnp.asarray(params.shape_basis, f32), precision=precision,
    )
    return joint_template, joint_shape_basis


def fused_operands(params: ManoParams, precision=DEFAULT_PRECISION):
    """All per-asset derived tensors for the fused path (batch-invariant):
    ``(basis_aug, wt, joint_template, joint_shape_basis)`` in float32."""
    return (*vertex_operands(params), *joint_operands(params, precision))


def _fused_kernel(vp: int, precision, basis_ref, wt_ref, coeff_ref, *refs):
    """One batch tile: blend + skin without leaving VMEM.

    Blocks: basis [Kp, 3*VP] and wt [J, VP] (constant index maps — resident
    across the whole launch); coeff [TB, Kp]; nine rotation-component slabs
    r_ac [TB, J]; three translation slabs t_a [TB, J]; three output
    coordinate planes o_a [TB, VP]. Contractions go through
    ops.common.kernel_dot so the model's precision policy holds inside the
    kernel too (a bare dot is single-pass bf16 under Mosaic).
    """
    r = refs[0:9]
    t = refs[9:12]
    o = refs[12:15]
    vp_flat = kernel_dot(coeff_ref[:], basis_ref[:], precision)  # [TB, 3*VP]
    wt = wt_ref[:]                                               # [J, VP]
    for a in range(3):
        acc = kernel_dot(t[a][:], wt, precision)
        for c in range(3):
            m_ac = kernel_dot(r[3 * a + c][:], wt, precision)
            acc = acc + m_ac * vp_flat[:, c * vp:(c + 1) * vp]
        o[a][:] = acc


def _fused_kernel_split(vp: int, basis_hi_ref, basis_lo_ref,
                        wt_hi_ref, wt_lo_ref, coeff_ref, *refs):
    """HIGH-precision variant with the big operands pre-split to bf16.

    Splitting the [Kp, 3*VP] basis inside the kernel would redo ~400K VPU
    cast/subtract ops on every grid step; pre-splitting at the JAX level
    moves that work out of the loop entirely (it fuses into the one-time
    operand prep) and halves the resident bytes per copy. Only the tiny
    per-tile operands (coeff [TB, Kp], r/t slabs [TB, J]) split in-kernel.
    Numerics are identical to kernel_dot's HIGH path: same a_hi*b_hi +
    a_hi*b_lo + a_lo*b_hi decomposition, f32 accumulate.
    """
    r = refs[0:9]
    t = refs[9:12]
    o = refs[12:15]
    c_hi, c_lo = _split_hi_lo(coeff_ref[:])
    vp_flat = _dot3(c_hi, c_lo, basis_hi_ref[:], basis_lo_ref[:])
    w_hi, w_lo = wt_hi_ref[:], wt_lo_ref[:]
    for a in range(3):
        t_hi, t_lo = _split_hi_lo(t[a][:])
        acc = _dot3(t_hi, t_lo, w_hi, w_lo)
        for c in range(3):
            r_hi, r_lo = _split_hi_lo(r[3 * a + c][:])
            m_ac = _dot3(r_hi, r_lo, w_hi, w_lo)
            acc = acc + m_ac * vp_flat[:, c * vp:(c + 1) * vp]
        o[a][:] = acc


def blend_skin_fused(
    basis_aug: jnp.ndarray,  # [Kp, 3*VP] from fused_operands
    wt: jnp.ndarray,         # [J, VP] transposed padded LBS weights
    coeff: jnp.ndarray,      # [B, K] blend coefficients (no template column)
    skin_rot: jnp.ndarray,   # [B, J, 3, 3] skinning rotations
    skin_t: jnp.ndarray,     # [B, J, 3] skinning translations
    n_verts: int,
    block_b: int = 128,
    interpret: bool = False,
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Blend + skin in one kernel launch: [B, n_verts, 3] vertices."""
    f32 = jnp.float32
    b = coeff.shape[0]
    j = wt.shape[0]
    kp, lanes = basis_aug.shape
    vp = lanes // 3
    block_b = max(1, min(block_b, b))
    bp = _cdiv(b, block_b) * block_b

    def padb(x):
        return jnp.pad(x, [(0, bp - b)] + [(0, 0)] * (x.ndim - 1))

    k = coeff.shape[1]
    # Constant-1 template column, then zero-pad the coefficient axis to Kp.
    coeff_aug = jnp.pad(
        jnp.concatenate(
            [coeff.astype(f32), jnp.ones((b, 1), f32)], axis=1
        ),
        [(0, bp - b), (0, kp - (k + 1))],
    )                                                   # [Bp, Kp]
    rot = padb(skin_rot.astype(f32))
    st = padb(skin_t.astype(f32))
    r_slabs = [rot[:, :, a, c] for a in range(3) for c in range(3)]
    t_slabs = [st[:, :, a] for a in range(3)]

    grid = (bp // block_b,)
    const_basis = pl.BlockSpec((kp, 3 * vp), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    const_wt = pl.BlockSpec((j, vp), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    spec_bk = pl.BlockSpec((block_b, kp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_bj = pl.BlockSpec((block_b, j), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_bv = pl.BlockSpec((block_b, vp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)

    canon = (jax.lax.Precision(precision)
             if precision is not None else precision)
    if canon == jax.lax.Precision.HIGH:
        # Pre-split the resident operands to bf16 hi/lo pairs at the JAX
        # level (one-time prep, hoisted out of callers' loops) so the grid
        # steps run pure bf16 MXU passes — see _fused_kernel_split. MUST be
        # the fold-proof XLA-level split: the convert-based one compiles to
        # lo == 0 under XLA:TPU (see ops.common).
        basis_hi, basis_lo = split_hi_lo_xla(basis_aug)
        wt_hi, wt_lo = split_hi_lo_xla(wt)
        outs = pl.pallas_call(
            functools.partial(_fused_kernel_split, vp),
            grid=grid,
            in_specs=[const_basis, const_basis, const_wt, const_wt,
                      spec_bk, *([spec_bj] * 12)],
            out_specs=[spec_bv] * 3,
            out_shape=[jax.ShapeDtypeStruct((bp, vp), f32)] * 3,
            interpret=interpret,
        )(basis_hi, basis_lo, wt_hi, wt_lo, coeff_aug,
          *r_slabs, *t_slabs)
    else:
        outs = pl.pallas_call(
            functools.partial(_fused_kernel, vp, precision),
            grid=grid,
            in_specs=[const_basis, const_wt, spec_bk,
                      *([spec_bj] * 12)],
            out_specs=[spec_bv] * 3,
            out_shape=[jax.ShapeDtypeStruct((bp, vp), f32)] * 3,
            interpret=interpret,
        )(basis_aug, wt, coeff_aug, *r_slabs, *t_slabs)
    return jnp.stack(outs, axis=-1)[:b, :n_verts, :]


def _pre_stage(params, operands, pose, shape, precision):
    """Rodrigues + joint regression + FK (the tiny non-vertex math, XLA)."""
    _, _, joint_template, joint_shape_basis = operands

    def one(p, s):
        rot_mats = ops.rotation_matrix(p)
        joints = joint_template + jnp.einsum(
            "jcs,s->jc", joint_shape_basis, s, precision=precision
        )
        world_rot, world_t = ops.forward_kinematics(
            params.parents, rot_mats, joints, precision
        )
        skin_rot, skin_t = ops.skinning_transforms(
            world_rot, world_t, joints, precision
        )
        eye = jnp.eye(3, dtype=rot_mats.dtype)
        coeff = jnp.concatenate([s, (rot_mats[1:] - eye).reshape(-1)])
        return coeff, skin_rot, skin_t

    return jax.vmap(one)(pose, shape)


def forward_verts_fused(
    params: ManoParams,
    pose: jnp.ndarray,   # [B, J, 3] axis-angle (row 0 global)
    shape: jnp.ndarray,  # [B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = 128,
    interpret: bool = False,
) -> jnp.ndarray:
    """Batched vertices [B, V, 3] via the fully-fused kernel.

    Semantics match ``core.forward_batched(...).verts`` (fused path); only
    vertices are produced — the joint outputs stay on the XLA paths.
    """
    f32 = jnp.float32
    n_verts = params.v_template.shape[0]
    if pose.shape[0] == 0:
        return jnp.zeros((0, n_verts, 3), f32)
    pose = pose.reshape(pose.shape[0], -1, 3).astype(f32)
    shape = shape.astype(f32)
    operands = fused_operands(params, precision)
    coeff, skin_rot, skin_t = _pre_stage(
        params, operands, pose, shape, precision
    )
    return blend_skin_fused(
        operands[0], operands[1], coeff, skin_rot, skin_t,
        n_verts, block_b=block_b, interpret=interpret, precision=precision,
    )


# ------------------------------------------------- FULL fusion (pre-stage in)
# The kernel above still receives its rotation/translation slabs from an
# XLA pre-stage (Rodrigues + joint regression + FK), worth ~166us of the
# ~770us per 8192-batch pass on v5e plus the r/t slab HBM round-trips
# (docs/roadmap.md #1). The variant below moves the ENTIRE forward into
# one kernel: inputs are just (pose, shape); Rodrigues, shaped-joint
# regression, level-parallel FK, inverse-bind, blendshapes and skinning
# all happen per batch tile without leaving VMEM.
#
# Layout key: joints ride the LANES in breadth-first level order
# [root | level1 | level2 | level3] (fingers in a fixed order), so each
# FK level composes against its parents as an ALIGNED elementwise
# multiply of contiguous lane slices — no gathers, no scatters, just
# slice + concat. The per-(a,b) rotation components live in nine separate
# [TB, J] slabs (VPU-friendly); the blend coefficient vector is
# concatenated in-register in (ab-major, level-ordered-joint) layout,
# with the basis rows permuted to match at operand-prep time.
# Reference semantics fused: /root/reference/mano_np.py:79-115 complete.


@functools.lru_cache(maxsize=None)
def level_layout(parents: tuple):
    """Static layout for lane-ordered FK: ``(perm, segments)``.

    ``perm`` lists original joint indices in [root, level1, level2, ...]
    order; ``segments`` holds ``(start, size, parent_start, parent_size)``
    lane ranges into the permuted order (``parent_size == 1`` broadcasts
    a shared parent; ``== size`` pairs one-to-one with a consecutive
    parent run). Parent positions are ABSOLUTE lanes into the
    accumulated permuted order, so ANY topologically ordered tree lays
    out: each BFS level is greedily split into shared-parent or
    consecutive-parent segments (SMPL-H's two hands hanging off the two
    mid-tree wrists become separate per-wrist segments). MANO-family
    trees emit exactly one whole-level segment per level — the layout,
    and therefore the compiled kernel, is unchanged for them.
    """
    from mano_hand_tpu.ops import fk

    parents = tuple(parents)
    levels_orig = fk.tree_levels(parents)
    perm = [0]
    pos = {0: 0}
    segments = []
    for lv in levels_orig:
        order = sorted(lv, key=lambda j: (pos[parents[j]], j))
        ppos = [pos[parents[j]] for j in order]
        i = 0
        while i < len(order):
            start = len(perm)
            k = i + 1
            if k < len(order) and ppos[k] == ppos[i]:
                while k < len(order) and ppos[k] == ppos[i]:
                    k += 1
                pinfo = (ppos[i], 1)  # shared parent, broadcasts
            else:
                while k < len(order) and ppos[k] == ppos[k - 1] + 1:
                    k += 1
                pinfo = (ppos[i], 1 if k - i == 1 else k - i)
            for j_ in order[i:k]:
                pos[j_] = len(perm)
                perm.append(j_)
            segments.append((start, k - i, *pinfo))
            i = k
    return tuple(perm), tuple(segments)


def fused_full_operands(params: ManoParams, precision=DEFAULT_PRECISION):
    """Batch-invariant operands for the fully-fused kernel.

    Returns ``(basis2 [Kp2, 3*VP], wt2 [J, VP], jb [3][Sp, J])`` where all
    joint axes are in ``level_layout`` order and the basis rows follow the
    in-kernel coefficient layout ``[shape(S) | template | zero-pad to Sp |
    pose rows (ab-major, permuted joints) | pad]``. ``jb[a]`` maps the
    augmented shape vector [beta | 1 | 0...] to joint coordinate ``a``
    (template row included — the same augmentation trick as the vertex
    basis).
    """
    f32 = jnp.float32
    perm, _ = level_layout(tuple(params.parents))
    perm = list(perm)
    v, _, s = params.shape_basis.shape
    j = params.j_regressor.shape[0]
    p = params.pose_basis.shape[-1]
    sp = _cdiv(s + 1, SUBLANE) * SUBLANE
    k2 = sp + p
    kp2 = _cdiv(k2, SUBLANE) * SUBLANE
    vp = _cdiv(v, LANE) * LANE

    shape_basis = jnp.asarray(params.shape_basis, f32)   # [V, 3, S]
    pose_basis = jnp.asarray(params.pose_basis, f32)     # [V, 3, P]
    v_template = jnp.asarray(params.v_template, f32)     # [V, 3]

    # Rows [K2, 3, V] in coefficient order.
    rows = [shape_basis.transpose(2, 1, 0)]              # [S, 3, V]
    rows.append(v_template.T[None])                      # template at S
    if sp - (s + 1):
        rows.append(jnp.zeros((sp - (s + 1), 3, v), f32))
    # Pose rows: ab-major, joints in perm order (root excluded). Original
    # column for joint jj, entry (a, b) is (jj-1)*9 + 3a + b (the
    # reference's joint-major row-major ravel, mano_np.py:87-91).
    pb = pose_basis.transpose(2, 1, 0)                   # [P, 3, V]
    order = [
        (perm[pos] - 1) * 9 + 3 * a + b
        for a in range(3) for b in range(3)
        for pos in range(1, j)
    ]
    rows.append(pb[jnp.asarray(order, jnp.int32)])
    basis = jnp.concatenate(rows, axis=0)                # [K2, 3, V]
    basis2 = jnp.pad(
        basis, [(0, kp2 - k2), (0, 0), (0, vp - v)]
    ).reshape(kp2, 3 * vp)

    wt2 = jnp.pad(
        jnp.asarray(params.lbs_weights, f32).T[jnp.asarray(perm)],
        [(0, 0), (0, vp - v)],
    )                                                    # [J, VP]

    joint_template, joint_shape_basis = joint_operands(params, precision)
    jt = joint_template[jnp.asarray(perm)]               # [J, 3]
    jsb = joint_shape_basis[jnp.asarray(perm)]           # [J, 3, S]
    jb = []
    for a in range(3):
        rows_a = jnp.concatenate(
            [jsb[:, a, :].T, jt[None, :, a],
             jnp.zeros((sp - (s + 1), j), f32)], axis=0
        )                                                # [Sp, J]
        jb.append(rows_a)
    return basis2, wt2, tuple(jb)


def _rodrigues_slabs(x, y, z):
    """Per-joint rotation components from axis-angle slabs [TB, J].

    Same guarded math as ops.rodrigues.rotation_matrix (value-identical;
    the hybrid VJP never differentiates through the kernel, so only value
    continuity matters here): R = (1 - b t2) I + a K + b rr^T.
    """
    t2 = x * x + y * y + z * z
    small = t2 < 1e-8
    theta = jnp.sqrt(jnp.where(small, 1.0, t2))
    a = jnp.where(small, 1.0 - t2 / 6.0 + t2 * t2 / 120.0,
                  jnp.sin(theta) / theta)
    b = jnp.where(small, 0.5 - t2 / 24.0 + t2 * t2 / 720.0,
                  (1.0 - jnp.cos(theta)) / (theta * theta))
    diag = 1.0 - b * t2
    return (
        diag + b * x * x, b * x * y - a * z, b * x * z + a * y,
        b * x * y + a * z, diag + b * y * y, b * y * z - a * x,
        b * x * z - a * y, b * y * z + a * x, diag + b * z * z,
    )


def _slice_parts(parts, bounds, lo, hi):
    """[lo, hi) lane range out of an accumulated parts list.

    A range inside one part is a plain slice (the only case MANO-family
    trees hit — their parent runs never span segments, so the compiled
    program is identical to the pre-generalization layout); a spanning
    range concatenates just the covering pieces.
    """
    segs = []
    for arr, b in zip(parts, bounds):
        e = b + arr.shape[1]
        if e <= lo or b >= hi:
            continue
        segs.append(arr[:, max(lo - b, 0):min(hi, e) - b])
    return segs[0] if len(segs) == 1 else jnp.concatenate(segs, axis=1)


def _fk_slabs(r_local, jx, jy, jz, levels):
    """Level-parallel FK on lane slabs; returns (world_rot 9-tuple,
    skin_t 3-tuple), each [TB, J] in permuted joint order.

    Each segment's compose is elementwise on contiguous, parent-aligned
    lane slices (see level_layout; parent positions are absolute lanes
    into the accumulated order) — concat accumulates the result, no
    scatters. Equivalent to ops.fk.forward_kinematics +
    skinning_transforms (mano_np.py:96-110 semantics).
    """
    jslab = (jx, jy, jz)
    parts_r = [[r[:, 0:1]] for r in r_local]   # 9 lists of lane chunks
    parts_t = [[jx[:, 0:1]], [jy[:, 0:1]], [jz[:, 0:1]]]
    bounds = [0]  # start lane of each accumulated part
    for (st, sz, pst, psz) in levels:
        # Parent slab: the (pst, psz) ABSOLUTE lane range out of the
        # accumulated parts — width sz (one-to-one) or 1 (shared parent,
        # broadcasts). Rest-joint coords slice from the full [TB, J]
        # slabs directly.
        pr = [_slice_parts(p, bounds, pst, pst + psz) for p in parts_r]
        pt = [_slice_parts(p, bounds, pst, pst + psz) for p in parts_t]
        pj = [jslab[c][:, pst:pst + psz] for c in range(3)]
        rl = [r[:, st:st + sz] for r in r_local]
        jl = [jslab[c][:, st:st + sz] for c in range(3)]
        loc = [jl[c] - pj[c] for c in range(3)]
        new_r = [
            pr[3 * a + 0] * rl[0 + b]
            + pr[3 * a + 1] * rl[3 + b]
            + pr[3 * a + 2] * rl[6 + b]
            for a in range(3) for b in range(3)
        ]
        new_t = [
            pr[3 * a + 0] * loc[0]
            + pr[3 * a + 1] * loc[1]
            + pr[3 * a + 2] * loc[2]
            + pt[a]
            for a in range(3)
        ]
        for i in range(9):
            parts_r[i].append(new_r[i])
        for a in range(3):
            parts_t[a].append(new_t[a])
        bounds.append(st)
    world_r = tuple(jnp.concatenate(ps, axis=1) for ps in parts_r)
    world_t = [jnp.concatenate(ps, axis=1) for ps in parts_t]
    # Inverse bind: skin_t = world_t - world_rot @ j_rest (fk.py:82-97).
    skin_t = tuple(
        world_t[a]
        - (world_r[3 * a + 0] * jx + world_r[3 * a + 1] * jy
           + world_r[3 * a + 2] * jz)
        for a in range(3)
    )
    return world_r, skin_t


def _fused_full_kernel(vp, levels, precision, split, stack_skin, *refs):
    """One batch tile of the COMPLETE forward: pose/shape slabs in,
    vertex coordinate planes out. ``split`` selects the pre-split-bf16
    HIGH path for the resident operands (see _fused_kernel_split)."""
    n_in = 11 if split else 9
    ins = [r[:] for r in refs[:n_in]]
    outs = _fused_full_compute(vp, levels, precision, split, stack_skin,
                               *ins)
    for o, r in zip(outs, refs[n_in:n_in + 3]):
        r[:] = o


def _fused_full_kernel_hands(vp, levels, precision, split, stack_skin,
                             *refs):
    """Two-hand variant: identical math per (hand, batch-tile) grid cell;
    every block carries a leading size-1 hand axis (the hand-major grid
    keeps each hand's resident operands in VMEM across its whole batch
    range — one refetch per hand, not per tile)."""
    n_in = 11 if split else 9
    ins = [r[0] for r in refs[:n_in]]
    outs = _fused_full_compute(vp, levels, precision, split, stack_skin,
                               *ins)
    for o, r in zip(outs, refs[n_in:n_in + 3]):
        r[0] = o


def _fused_full_compute(vp, levels, precision, split, stack_skin, *ins):
    """The full forward on VALUES (blocks already read): returns the
    three output coordinate planes. Shared by the one-hand and two-hand
    kernels. ``stack_skin`` batches each output coordinate's four K=16
    skin dots into one [4*TB, J] dot (same FLOPs; fewer MXU pipeline
    fills) — a measured-on-chip choice, see bench config3d."""
    if split:
        (basis_hi, basis_lo, wt_hi, wt_lo, jbx, jby, jbz,
         shape_aug, x, y, z) = ins
    else:
        (basis_op, wt_op, jbx, jby, jbz, shape_aug, x, y, z) = ins

    r_local = _rodrigues_slabs(x, y, z)

    # Shaped joints: [TB, Sp] x [Sp, J] per coordinate (tiny MXU dots).
    jx = kernel_dot(shape_aug, jbx, precision)
    jy = kernel_dot(shape_aug, jby, precision)
    jz = kernel_dot(shape_aug, jbz, precision)

    world_r, skin_t = _fk_slabs(r_local, jx, jy, jz, levels)

    # Blend coefficients in-register: [shape_aug | (R_local - I) deltas
    # ab-major over non-root joints | pad] matching fused_full_operands'
    # basis row order.
    deltas = [
        r_local[3 * a + b][:, 1:] - (1.0 if a == b else 0.0)
        for a in range(3) for b in range(3)
    ]
    coeff = jnp.concatenate([shape_aug, *deltas], axis=1)
    kp2 = (basis_hi if split else basis_op).shape[0]
    pad = kp2 - coeff.shape[1]
    if pad:
        coeff = jnp.concatenate(
            [coeff, jnp.zeros((coeff.shape[0], pad), coeff.dtype)], axis=1
        )

    tb = x.shape[0]

    # Skin-dot pass structure (all variants share one RHS, wt):
    #   False  — 12 separate [TB, J] dots per tile (the original form);
    #   True   — each output coordinate's four dots stacked into one
    #            [4*TB, J] dot (3 dots per tile);
    #   "full" — all twelve stacked into one [12*TB, J] dot.
    # Identical FLOPs and per-row math in every case; stacking amortizes
    # the MXU pipeline fill the skinny K=16 pays per pass (36 / 9 / 3
    # passes per tile under the 3-pass HIGH policy). Rows slice back out
    # of the product for the combine. VMEM note: "full" materializes a
    # [12*TB, VP] f32 product (~5.5 MB at TB=128) — the bench's
    # fault-isolated measurement decides whether it fits and pays.
    def skin_dot(lhs):
        if split:
            l_hi, l_lo = _split_hi_lo(lhs)
            return _dot3(l_hi, l_lo, wt_hi, wt_lo)
        return kernel_dot(lhs, wt_op, precision)

    def combine(acc, m_planes):
        for c in range(3):
            acc = acc + m_planes[c] * vp_flat[:, c * vp:(c + 1) * vp]
        return acc

    if split:
        c_hi, c_lo = _split_hi_lo(coeff)
        vp_flat = _dot3(c_hi, c_lo, basis_hi, basis_lo)
    else:
        vp_flat = kernel_dot(coeff, basis_op, precision)

    outs = []
    if stack_skin == "full":
        big = skin_dot(jnp.concatenate([*skin_t, *world_r], axis=0))
        for a in range(3):
            outs.append(combine(
                big[a * tb:(a + 1) * tb],
                [big[(3 + 3 * a + c) * tb:(4 + 3 * a + c) * tb]
                 for c in range(3)]))
    elif stack_skin:
        for a in range(3):
            big = skin_dot(jnp.concatenate(
                [skin_t[a], world_r[3 * a + 0],
                 world_r[3 * a + 1], world_r[3 * a + 2]], axis=0))
            outs.append(combine(
                big[0:tb],
                [big[(1 + c) * tb:(2 + c) * tb] for c in range(3)]))
    else:
        for a in range(3):
            acc = skin_dot(skin_t[a])
            outs.append(combine(
                acc, [skin_dot(world_r[3 * a + c]) for c in range(3)]))
    return tuple(outs)


def forward_verts_fused_full(
    params: ManoParams,
    pose: jnp.ndarray,   # [B, J, 3] axis-angle (row 0 global)
    shape: jnp.ndarray,  # [B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = 128,
    interpret: bool = False,
    stack_skin=False,  # False | True (4-way) | "full" (12-way)
) -> jnp.ndarray:
    """Batched vertices [B, V, 3] with the WHOLE forward in one kernel.

    Per-eval HBM input traffic is pose (48 f32 = 192 B) + shape
    (10 f32 = 40 B); the r/t slabs and blend coefficients of the split
    pipeline never exist in HBM. Any topologically ordered kinematic
    tree lays out (``level_layout`` splits levels into parent-aligned
    segments; MANO-family trees compile identically to the whole-level
    layout, SMPL-H's per-wrist hand chains become extra segments).

    LOCKSTEP: the launch scaffolding below (operand prep, padding,
    BlockSpecs, HIGH-path split) is deliberately mirrored line for line
    in ``forward_verts_fused_full_hands`` — apply any change here to
    that function too (they differ only by the leading hand axis).
    """
    f32 = jnp.float32
    v = params.v_template.shape[0]
    j = params.j_regressor.shape[0]
    s = params.shape_basis.shape[-1]
    if pose.shape[0] == 0:
        return jnp.zeros((0, v, 3), f32)
    perm, levels = level_layout(tuple(params.parents))
    basis2, wt2, jb = fused_full_operands(params, precision)

    b = pose.shape[0]
    pose_p = pose.reshape(b, j, 3).astype(f32)[:, jnp.asarray(perm), :]
    sp = jb[0].shape[0]
    shape_aug = jnp.concatenate(
        [shape.astype(f32), jnp.ones((b, 1), f32),
         jnp.zeros((b, sp - s - 1), f32)], axis=1
    )                                                    # [B, Sp]

    block_b = max(1, min(block_b, b))
    bp = _cdiv(b, block_b) * block_b

    def padb(xarr):
        return jnp.pad(xarr, [(0, bp - b)] + [(0, 0)] * (xarr.ndim - 1))

    shape_aug = padb(shape_aug)
    slabs = [padb(pose_p[:, :, c]) for c in range(3)]    # 3 x [Bp, J]

    kp2, lanes = basis2.shape
    vp = lanes // 3
    grid = (bp // block_b,)
    const_basis = pl.BlockSpec((kp2, 3 * vp), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
    const_wt = pl.BlockSpec((j, vp), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    const_jb = pl.BlockSpec((sp, j), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    spec_bs = pl.BlockSpec((block_b, sp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_bj = pl.BlockSpec((block_b, j), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
    spec_bv = pl.BlockSpec((block_b, vp), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)

    canon = (jax.lax.Precision(precision)
             if precision is not None else precision)
    split = canon == jax.lax.Precision.HIGH
    if split:
        basis_hi, basis_lo = split_hi_lo_xla(basis2)
        wt_hi, wt_lo = split_hi_lo_xla(wt2)
        operands = (basis_hi, basis_lo, wt_hi, wt_lo, *jb,
                    shape_aug, *slabs)
        in_specs = [const_basis, const_basis, const_wt, const_wt,
                    const_jb, const_jb, const_jb, spec_bs,
                    *([spec_bj] * 3)]
    else:
        operands = (basis2, wt2, *jb, shape_aug, *slabs)
        in_specs = [const_basis, const_wt,
                    const_jb, const_jb, const_jb, spec_bs,
                    *([spec_bj] * 3)]
    outs = pl.pallas_call(
        functools.partial(_fused_full_kernel, vp, levels,
                          precision, split, stack_skin),
        grid=grid,
        in_specs=in_specs,
        out_specs=[spec_bv] * 3,
        out_shape=[jax.ShapeDtypeStruct((bp, vp), f32)] * 3,
        interpret=interpret,
    )(*operands)
    return jnp.stack(outs, axis=-1)[:b, :v, :]


def forward_verts_fused_full_hands(
    params2,             # stacked ManoParams: [2, ...] array leaves (L, R)
    pose: jnp.ndarray,   # [2, B, J, 3] axis-angle, hand-major
    shape: jnp.ndarray,  # [2, B, S]
    precision=DEFAULT_PRECISION,
    block_b: int = 128,
    interpret: bool = False,
    stack_skin=False,  # False | True (4-way) | "full" (12-way)
) -> jnp.ndarray:
    """BOTH hands' complete forward in ONE kernel launch: [2, B, V, 3].

    The canonical two-hand workloads (BASELINE config 3's interleaved
    L+R batch, config 5's two-hand clips) otherwise pay two sequenced
    launches per pass; here the grid is (hand, batch-tile) — hand-major,
    so each hand's resident operands (basis/weights/joint maps) are
    fetched into VMEM once and reused across its whole batch range, and
    the second hand's tiles follow without leaving the kernel. Same
    math, layout, and precision policy as ``forward_verts_fused_full``
    (the kernels share ``_fused_full_compute``); both hands must share
    one kinematic tree (they do: stack_params requires it).

    LOCKSTEP: the host-side launch scaffolding (operand prep, padding,
    BlockSpecs, HIGH-path split) deliberately mirrors
    ``forward_verts_fused_full`` line for line rather than sharing a
    builder — the one-hand path is the measured headline kernel and
    stays untouched; any change to either launch sequence must be
    applied to BOTH (they differ only by the leading hand axis).
    """
    f32 = jnp.float32
    v = params2.v_template.shape[-2]
    j = params2.j_regressor.shape[-2]
    s = params2.shape_basis.shape[-1]
    if pose.ndim == 3 and pose.shape[-1] == 3 * j:
        pose = pose.reshape(pose.shape[0], pose.shape[1], j, 3)
    if pose.shape[0] != 2 or pose.ndim != 4:
        raise ValueError(
            f"pose must be [2, B, {j}, 3] (or flat [2, B, {3 * j}]), "
            f"got {pose.shape}")
    b = pose.shape[1]
    if b == 0:
        return jnp.zeros((2, 0, v, 3), f32)
    perm, levels = level_layout(tuple(params2.parents))
    basis2, wt2, jb = jax.vmap(
        lambda p: fused_full_operands(p, precision)
    )(params2)                       # [2, Kp2, 3VP], [2, J, VP], 3x[2, Sp, J]

    pose_p = pose.reshape(2, b, j, 3).astype(f32)[:, :, jnp.asarray(perm), :]
    sp = jb[0].shape[-2]
    shape_aug = jnp.concatenate(
        [shape.astype(f32), jnp.ones((2, b, 1), f32),
         jnp.zeros((2, b, sp - s - 1), f32)], axis=-1
    )                                                    # [2, B, Sp]

    block_b = max(1, min(block_b, b))
    bp = _cdiv(b, block_b) * block_b

    def padb(xarr):
        return jnp.pad(
            xarr, [(0, 0), (0, bp - b)] + [(0, 0)] * (xarr.ndim - 2))

    shape_aug = padb(shape_aug)
    slabs = [padb(pose_p[:, :, :, c]) for c in range(3)]  # 3 x [2, Bp, J]

    kp2, lanes = basis2.shape[-2:]
    vp = lanes // 3
    grid = (2, bp // block_b)        # hand-major: operands refetch once/hand
    const_basis = pl.BlockSpec((1, kp2, 3 * vp), lambda h, i: (h, 0, 0),
                               memory_space=pltpu.VMEM)
    const_wt = pl.BlockSpec((1, j, vp), lambda h, i: (h, 0, 0),
                            memory_space=pltpu.VMEM)
    const_jb = pl.BlockSpec((1, sp, j), lambda h, i: (h, 0, 0),
                            memory_space=pltpu.VMEM)
    spec_bs = pl.BlockSpec((1, block_b, sp), lambda h, i: (h, i, 0),
                           memory_space=pltpu.VMEM)
    spec_bj = pl.BlockSpec((1, block_b, j), lambda h, i: (h, i, 0),
                           memory_space=pltpu.VMEM)
    spec_bv = pl.BlockSpec((1, block_b, vp), lambda h, i: (h, i, 0),
                           memory_space=pltpu.VMEM)

    canon = (jax.lax.Precision(precision)
             if precision is not None else precision)
    split = canon == jax.lax.Precision.HIGH
    if split:
        basis_hi, basis_lo = split_hi_lo_xla(basis2)
        wt_hi, wt_lo = split_hi_lo_xla(wt2)
        operands = (basis_hi, basis_lo, wt_hi, wt_lo, *jb,
                    shape_aug, *slabs)
        in_specs = [const_basis, const_basis, const_wt, const_wt,
                    const_jb, const_jb, const_jb, spec_bs,
                    *([spec_bj] * 3)]
    else:
        operands = (basis2, wt2, *jb, shape_aug, *slabs)
        in_specs = [const_basis, const_wt,
                    const_jb, const_jb, const_jb, spec_bs,
                    *([spec_bj] * 3)]
    outs = pl.pallas_call(
        functools.partial(_fused_full_kernel_hands, vp, levels,
                          precision, split, stack_skin),
        grid=grid,
        in_specs=in_specs,
        out_specs=[spec_bv] * 3,
        out_shape=[jax.ShapeDtypeStruct((2, bp, vp), f32)] * 3,
        interpret=interpret,
    )(*operands)
    return jnp.stack(outs, axis=-1)[:, :b, :v, :]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def forward_verts_fused_full_ad(
    params, pose, shape,
    precision=DEFAULT_PRECISION, block_b: int = 128, interpret: bool = False,
    stack_skin=False,  # False | True (4-way) | "full" (12-way)
):
    """Differentiable fully-fused forward — same hybrid VJP as
    ``forward_verts_fused_ad`` (the backward recomputes the tiny
    pre-stage in XLA regardless of how the forward was fused, so the
    cotangent math is shared verbatim; ``stack_skin`` only reorders the
    forward's MXU passes)."""
    return forward_verts_fused_full(
        params, pose, shape, precision, block_b, interpret, stack_skin
    )


def _fwd_full(params, pose, shape, precision, block_b, interpret,
              stack_skin):
    out = forward_verts_fused_full(
        params, pose, shape, precision, block_b, interpret, stack_skin
    )
    return out, (params, pose, shape)


# (defvjp wiring for the full variant is at the bottom of the file, after
# the shared _bwd is defined.)


# ---------------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def forward_verts_fused_ad(
    params, pose, shape,
    precision=DEFAULT_PRECISION, block_b: int = 128, interpret: bool = False,
):
    """Differentiable fused forward: Pallas forward, hybrid backward.

    The backward pass reuses the skinning kernel for the dominant vertex
    cotangent (LBS is linear in the blended vertices: dL/dvp = M^T g), one
    MXU matmul for the blend-coefficient cotangent, and JAX autodiff of the
    tiny pre-stage (Rodrigues/FK) to carry those into (pose, shape) —
    no [B, V, J, *] tensor anywhere.
    """
    return forward_verts_fused(
        params, pose, shape, precision, block_b, interpret
    )


def _fwd(params, pose, shape, precision, block_b, interpret):
    out = forward_verts_fused(
        params, pose, shape, precision, block_b, interpret
    )
    return out, (params, pose, shape)


def _bwd(precision, block_b, interpret, residuals, g):
    params, pose, shape = residuals
    f32 = jnp.float32
    g = g.astype(f32)
    hi = jax.lax.Precision.HIGHEST
    pose32 = pose.reshape(pose.shape[0], -1, 3).astype(f32)
    shape32 = shape.astype(f32)
    # Only the vertex-side tensors are needed here; pre_p derives its own
    # joint operands under the vjp (so their cotangents flow to params).
    basis_aug, _ = vertex_operands(params)

    # Re-run the cheap pre-stage under VJP so its cotangents flow to
    # (params, pose, shape); the expensive vertex stages never re-run in
    # XLA. Differentiating through fused_operands here carries the
    # joint-regression path's cotangent into j_regressor/shape_basis/
    # v_template.
    def pre_p(prm, p, s):
        return _pre_stage(prm, fused_operands(prm, precision), p, s,
                          precision)

    (coeff, skin_rot, skin_t), pre_vjp = jax.vjp(
        pre_p, params, pose32, shape32,
    )

    # Vertex cotangent dL/dv_posed via the skinning kernel with transposed
    # rotations and zero translations (see ops/pallas_lbs.py:_skin_bwd).
    grad_vp = pallas_lbs.skin_batched(
        params.lbs_weights.astype(f32),
        skin_rot.transpose(0, 1, 3, 2),
        jnp.zeros_like(skin_t),
        g,
        block_b=min(block_b, 32), block_v=LANE, interpret=interpret,
        precision=precision,
    )                                                    # [B, V, 3]
    # Blend matmul cotangent: vp_flat = coeff_aug @ basis_aug, so
    # dL/dcoeff = dL/dvp_flat @ basis_aug^T (template column dropped).
    b = g.shape[0]
    v = g.shape[1]
    vp = basis_aug.shape[1] // 3
    gvp_cm = jnp.pad(
        grad_vp.transpose(0, 2, 1), [(0, 0), (0, 0), (0, vp - v)]
    ).reshape(b, 3 * vp)                                 # [B, 3*VP] c-major
    grad_coeff_aug = jnp.einsum(
        "bl,kl->bk", gvp_cm, basis_aug, precision=hi
    )
    k = coeff.shape[1]
    grad_coeff = grad_coeff_aug[:, :k]

    # Recompute v_posed (one matmul) for the rotation/translation cotangents.
    coeff_aug = jnp.concatenate([coeff, jnp.ones((b, 1), f32)], axis=1)
    kp = basis_aug.shape[0]
    coeff_aug = jnp.pad(coeff_aug, [(0, 0), (0, kp - (k + 1))])
    v_posed = (
        jnp.einsum("bk,kl->bl", coeff_aug, basis_aug, precision=hi)
        .reshape(b, 3, vp)[:, :, :v].transpose(0, 2, 1)  # [B, V, 3]
    )
    outer = g[..., :, None] * v_posed[..., None, :]      # [B, V, 3, 3]
    w = jnp.asarray(params.lbs_weights, f32)
    grad_rot = jnp.einsum("vj,bvac->bjac", w, outer, precision=hi)
    grad_t = jnp.einsum("vj,bva->bja", w, g, precision=hi)

    grad_params_pre, grad_pose, grad_shape = pre_vjp(
        (grad_coeff, grad_rot, grad_t)
    )

    # Direct vertex-path parameter cotangents (the pre-stage vjp covers
    # only the joint/FK dependence):
    #   lbs_weights — same formula as pallas_lbs._skin_bwd;
    #   basis_aug   — vp_flat = coeff_aug @ basis_aug, so
    #                 dL/dbasis = coeff_aug^T @ dL/dvp_flat, unpacked back
    #                 through the coordinate-major packing of
    #                 fused_operands into (shape_basis, pose_basis,
    #                 v_template) cotangents.
    grad_w = (
        jnp.einsum("bvac,bjac->vj", outer, skin_rot, precision=hi)
        + jnp.einsum("bva,bja->vj", g, skin_t, precision=hi)
    )
    grad_basis = jnp.einsum(
        "bk,bl->kl", coeff_aug, gvp_cm, precision=hi
    ).reshape(kp, 3, vp)[:, :, :v]                       # [Kp, 3, V]
    s_dim = params.shape_basis.shape[-1]
    p_dim = params.pose_basis.shape[-1]
    import dataclasses

    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    grad_params_vert = dataclasses.replace(
        zeros,
        lbs_weights=grad_w.astype(zeros.lbs_weights.dtype),
        shape_basis=grad_basis[:s_dim].transpose(2, 1, 0)
        .astype(zeros.shape_basis.dtype),
        pose_basis=grad_basis[s_dim:s_dim + p_dim].transpose(2, 1, 0)
        .astype(zeros.pose_basis.dtype),
        v_template=grad_basis[s_dim + p_dim].T
        .astype(zeros.v_template.dtype),
    )
    def _combine(a, b):
        # Integer leaves (faces) carry float0 cotangents from the vjp —
        # pass those through untouched (the required tangent type).
        if getattr(b, "dtype", None) == jax.dtypes.float0:
            return b
        return a + b.astype(a.dtype)

    grad_params = jax.tree_util.tree_map(
        _combine, grad_params_vert, grad_params_pre,
    )
    return (
        grad_params,
        grad_pose.reshape(pose.shape).astype(pose.dtype),
        grad_shape.astype(shape.dtype),
    )


def _bwd_full(precision, block_b, interpret, stack_skin, residuals, g):
    # stack_skin only reorders forward MXU passes; the hybrid backward
    # is identical.
    return _bwd(precision, block_b, interpret, residuals, g)


forward_verts_fused_ad.defvjp(_fwd, _bwd)
forward_verts_fused_full_ad.defvjp(_fwd_full, _bwd_full)
