"""Vertex and face normals as pure, vmappable JAX ops.

The reference has no normals code of its own — shading normals are computed
inside its external OpenGL viewer (vctoolkit TriMeshViewer, used at
/root/reference/data_explore.py:17-18). The TPU framework needs them
natively for the rasterizer (mano_hand_tpu.viz) and for normal-based
fitting objectives, so they are first-class ops here: one gather, one
cross product, one segment-sum scatter — all fusable under jit and exact
under vmap/grad.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mano_hand_tpu.ops.common import EPS


def face_normals(
    verts: jnp.ndarray,   # [V, 3]
    faces: jnp.ndarray,   # [F, 3] int
    normalize: bool = True,
) -> jnp.ndarray:
    """Per-face normals [F, 3] (right-hand winding, CCW = outward).

    Un-normalized, the magnitude is twice the triangle area — which is
    exactly the area weighting wanted for vertex accumulation.
    """
    fv = verts[faces]  # [F, 3(corner), 3(xyz)]
    n = jnp.cross(fv[:, 1] - fv[:, 0], fv[:, 2] - fv[:, 0])
    if normalize:
        n = n / jnp.maximum(
            jnp.linalg.norm(n, axis=-1, keepdims=True), EPS
        )
    return n


def vertex_normals(
    verts: jnp.ndarray,   # [V, 3]
    faces: jnp.ndarray,   # [F, 3] int
) -> jnp.ndarray:
    """Area-weighted vertex normals [V, 3], unit length.

    Area weighting falls out of accumulating the *un-normalized* face
    normals (|n| = 2A): large triangles dominate their corners' normals,
    the standard choice for watertight skinned meshes. The scatter is a
    ``segment_sum`` over the flattened corner list — one XLA scatter-add,
    batchable with vmap over the verts axis. Vertices referenced by no
    face get a zero normal (the eps guard keeps that finite).
    """
    n_verts = verts.shape[-2]
    fn = face_normals(verts, faces, normalize=False)       # [F, 3]
    corners = jnp.repeat(fn, 3, axis=0)                    # [F*3, 3]
    acc = jax.ops.segment_sum(
        corners, faces.reshape(-1), num_segments=n_verts
    )                                                      # [V, 3]
    return acc / jnp.maximum(
        jnp.linalg.norm(acc, axis=-1, keepdims=True), EPS
    )


def batched_vertex_normals(verts: jnp.ndarray, faces: jnp.ndarray):
    """vertex_normals vmapped over a leading batch axis of verts."""
    return jax.vmap(vertex_normals, in_axes=(0, None))(verts, faces)
