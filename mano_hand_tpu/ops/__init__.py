from mano_hand_tpu.ops.rodrigues import (
    axis_angle_from_matrix,
    matrix_from_6d,
    matrix_from_quaternion,
    matrix_to_6d,
    rotation_matrix,
    skew,
)
from mano_hand_tpu.ops.fk import forward_kinematics, skinning_transforms, tree_levels
from mano_hand_tpu.ops.blend import pose_blend, regress_joints, shape_blend
from mano_hand_tpu.ops.lbs import skin
from mano_hand_tpu.ops.normals import (
    batched_vertex_normals,
    face_normals,
    vertex_normals,
)

__all__ = [
    "face_normals",
    "vertex_normals",
    "batched_vertex_normals",
    "rotation_matrix",
    "skew",
    "axis_angle_from_matrix",
    "matrix_from_6d",
    "matrix_from_quaternion",
    "matrix_to_6d",
    "forward_kinematics",
    "skinning_transforms",
    "tree_levels",
    "shape_blend",
    "pose_blend",
    "regress_joints",
    "skin",
]
