"""Fused linear blend skinning.

The reference materializes a per-vertex [778, 4, 4] transform tensor
(/root/reference/mano_np.py:112-115); batched naively that is
[B, 778, 4, 4] — ~4.4 GB at B=65536 — and is pure HBM traffic. We blend
(rotation, translation) pairs instead and contract straight to vertices:

    verts[v] = (sum_j w[v,j] R_j) @ v_posed[v] + sum_j w[v,j] t_j

which XLA fuses into two MXU contractions ([V,J]x[J,9] and [V,J]x[J,3]) plus
an elementwise combine, never touching 4x4 homogeneous padding.
"""

from __future__ import annotations

import jax.numpy as jnp

from mano_hand_tpu.ops.common import DEFAULT_PRECISION


def skin(
    weights: jnp.ndarray,    # [V, J] LBS weights
    world_rot: jnp.ndarray,  # [J, 3, 3] skinning rotations
    skin_t: jnp.ndarray,     # [J, 3] skinning translations (inverse-bound)
    v_posed: jnp.ndarray,    # [V, 3] blendshaped rest-pose verts
    precision=DEFAULT_PRECISION,
    compute_dtype=None,
) -> jnp.ndarray:
    """Pose the mesh: [V, 3] skinned vertices.

    ``compute_dtype`` (PR 14): the two weight contractions — the
    MXU-bound work of this op — take operands cast to this dtype (bf16
    on the serving bf16 tier) and accumulate into f32
    (``preferred_element_type``); ``precision`` is ignored on THOSE
    two dots (the enum describes f32-operand MXU decompositions, and
    their operands are already bf16) but still governs the final
    per-vertex 3x3 apply, whose operands are the f32 accumulations —
    left at default it would itself lower to single-pass bf16 on TPU,
    adding unbudgeted rounding outside the stated policy (review
    finding).
    """
    rot_flat = world_rot.reshape(world_rot.shape[0], 9)        # [J, 9]
    if compute_dtype is not None:
        w = weights.astype(compute_dtype)
        blend_rot = jnp.einsum(
            "vj,jr->vr", w, rot_flat.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        ).reshape(-1, 3, 3)                                    # [V, 3, 3]
        blend_t = jnp.einsum(
            "vj,jc->vc", w, skin_t.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
        return (
            jnp.einsum("vab,vb->va", blend_rot,
                       v_posed.astype(jnp.float32),
                       precision=precision)
            + blend_t
        )
    blend_rot = jnp.einsum(
        "vj,jr->vr", weights, rot_flat, precision=precision
    ).reshape(-1, 3, 3)                                        # [V, 3, 3]
    blend_t = jnp.einsum("vj,jc->vc", weights, skin_t, precision=precision)
    return (
        jnp.einsum("vab,vb->va", blend_rot, v_posed, precision=precision)
        + blend_t
    )
