"""Fused linear blend skinning.

The reference materializes a per-vertex [778, 4, 4] transform tensor
(/root/reference/mano_np.py:112-115); batched naively that is
[B, 778, 4, 4] — ~4.4 GB at B=65536 — and is pure HBM traffic. We blend
(rotation, translation) pairs instead and contract straight to vertices:

    verts[v] = (sum_j w[v,j] R_j) @ v_posed[v] + sum_j w[v,j] t_j

which XLA fuses into two MXU contractions ([V,J]x[J,9] and [V,J]x[J,3]) plus
an elementwise combine, never touching 4x4 homogeneous padding.
"""

from __future__ import annotations

import jax.numpy as jnp

from mano_hand_tpu.ops.common import DEFAULT_PRECISION


def skin(
    weights: jnp.ndarray,    # [V, J] LBS weights
    world_rot: jnp.ndarray,  # [J, 3, 3] skinning rotations
    skin_t: jnp.ndarray,     # [J, 3] skinning translations (inverse-bound)
    v_posed: jnp.ndarray,    # [V, 3] blendshaped rest-pose verts
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Pose the mesh: [V, 3] skinned vertices."""
    rot_flat = world_rot.reshape(world_rot.shape[0], 9)        # [J, 9]
    blend_rot = jnp.einsum(
        "vj,jr->vr", weights, rot_flat, precision=precision
    ).reshape(-1, 3, 3)                                        # [V, 3, 3]
    blend_t = jnp.einsum("vj,jc->vc", weights, skin_t, precision=precision)
    return (
        jnp.einsum("vab,vb->va", blend_rot, v_posed, precision=precision)
        + blend_t
    )
