"""Blendshape application and joint regression.

These are the MXU-bound contractions of the forward pass
(/root/reference/mano_np.py:81-91). All einsums take an explicit
``precision`` so callers can force float32 accumulation on TPU (bf16-default
matmuls would blow the <1e-4 vertex-error budget; SURVEY.md §7).
"""

from __future__ import annotations

import jax.numpy as jnp

from mano_hand_tpu.ops.common import DEFAULT_PRECISION


def shape_blend(
    v_template: jnp.ndarray,   # [V, 3]
    shape_basis: jnp.ndarray,  # [V, 3, S]
    beta: jnp.ndarray,         # [S]
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Template + shape blendshape offsets (mano_np.py:81)."""
    return v_template + jnp.einsum(
        "vcs,s->vc", shape_basis, beta, precision=precision
    )


def pose_blend(
    v_shaped: jnp.ndarray,    # [V, 3]
    pose_basis: jnp.ndarray,  # [V, 3, P]
    rot_mats: jnp.ndarray,    # [J, 3, 3] incl. root
    precision=DEFAULT_PRECISION,
    compute_dtype=None,
) -> jnp.ndarray:
    """Pose-corrective offsets driven by (R - I) of the articulated joints;
    the root/global rotation is excluded (mano_np.py:87-91).

    ``compute_dtype`` (PR 14): the contraction's OPERANDS are cast to
    this dtype (bf16 on the serving bf16 tier) with accumulation pinned
    to f32 via ``preferred_element_type`` — the reduced-precision form
    the PrecisionPolicy states, auditable in the jaxpr (bf16-in/f32-out
    dots). The residual add stays in ``v_shaped``'s dtype. ``precision``
    is ignored on this branch: XLA precision enums describe f32-operand
    MXU decompositions, and the operands here are already bf16.
    """
    eye = jnp.eye(3, dtype=rot_mats.dtype)
    pose_feat = (rot_mats[1:] - eye).reshape(-1)
    if compute_dtype is not None:
        return v_shaped + jnp.einsum(
            "vcp,p->vc",
            pose_basis.astype(compute_dtype),
            pose_feat.astype(compute_dtype),
            preferred_element_type=jnp.float32,
        )
    return v_shaped + jnp.einsum(
        "vcp,p->vc", pose_basis, pose_feat, precision=precision
    )


def regress_joints(
    j_regressor: jnp.ndarray,  # [J, V]
    v_shaped: jnp.ndarray,     # [V, 3]
    precision=DEFAULT_PRECISION,
) -> jnp.ndarray:
    """Joint locations as convex combinations of vertices (mano_np.py:83)."""
    return jnp.einsum("jv,vc->jc", j_regressor, v_shaped, precision=precision)
