"""Forward kinematics over the static MANO kinematic tree.

The reference walks the 15 articulated joints sequentially with 4x4
homogeneous matrices (/root/reference/mano_np.py:96-110). On TPU we instead:

  * carry (rotation, translation) pairs — no 4x4 padding, fewer FLOPs,
    no wasted lanes on constant rows;
  * compose **level-parallel**: the MANO tree has depth 4 (wrist -> MCP ->
    PIP -> DIP across 5 fingers), so all joints at a depth compose against
    their parents in one batched [5,3,3] matmul — 3 batched steps instead
    of 15 sequential ones, shrinking the XLA dependency chain;
  * levels and gather indices are static Python, derived from the
    ``parents`` tuple at trace time, so jit sees fixed shapes.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax.numpy as jnp
import numpy as np

from mano_hand_tpu.ops.common import DEFAULT_PRECISION


@functools.lru_cache(maxsize=None)
def tree_levels(parents: Tuple[int, ...]) -> Tuple[Tuple[int, ...], ...]:
    """Group joint indices by depth (root excluded). Static, cached."""
    depth = [0] * len(parents)
    for i, p in enumerate(parents):
        if p >= 0:
            depth[i] = depth[p] + 1
    levels = []
    for d in range(1, max(depth) + 1):
        levels.append(tuple(i for i, dd in enumerate(depth) if dd == d))
    return tuple(levels)


def forward_kinematics(
    parents: Tuple[int, ...],
    rot_local: jnp.ndarray,   # [J, 3, 3] per-joint local rotations
    joints: jnp.ndarray,      # [J, 3] rest-pose joint positions
    precision=DEFAULT_PRECISION,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Compose the kinematic chain; returns (world_rot [J,3,3], world_t [J,3]).

    world_t are the posed joint positions; world_rot the accumulated
    orientations — together the reference's G matrices
    (/root/reference/mano_np.py:96-104) without the homogeneous row.

    ``precision`` is accepted for signature symmetry with the other ops but
    unused: the 3x3 composes below are broadcast-multiply-sums (full f32
    mul+add, equivalent to Precision.HIGHEST), not dot_generals.
    """
    del precision
    parents_arr = np.asarray(parents)
    world_rot = jnp.zeros_like(rot_local).at[0].set(rot_local[0])
    world_t = jnp.zeros_like(joints).at[0].set(joints[0])
    for level in tree_levels(parents):
        idx = np.asarray(level)
        par = parents_arr[idx]
        parent_rot = world_rot[par]                       # [k, 3, 3]
        local_t = joints[idx] - joints[par]               # [k, 3]
        # 3x3 composes as broadcast-multiply-sum, NOT einsum/dot_general:
        # at this size the MXU buys nothing, f32 mul+add matches
        # Precision.HIGHEST, and a dot_general here (3 batch dims once
        # callers nest vmap over hand and batch axes) trips an XLA
        # simplifier bug that mangles batch-dim order and fails the hlo
        # verifier (f32[5,2,4,3,3] vs f32[4,5,2,3,3]).
        world_rot = world_rot.at[idx].set(
            (parent_rot[..., :, :, None]
             * rot_local[idx][..., None, :, :]).sum(axis=-2)
        )
        world_t = world_t.at[idx].set(
            (parent_rot * local_t[..., None, :]).sum(axis=-1)
            + world_t[par]
        )
    return world_rot, world_t


def skinning_transforms(
    world_rot: jnp.ndarray,  # [J, 3, 3]
    world_t: jnp.ndarray,    # [J, 3]
    joints: jnp.ndarray,     # [J, 3] rest-pose joints
    precision=DEFAULT_PRECISION,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Inverse-bind: map rest-pose space to posed space per joint.

    Equivalent to the reference's G - pack(G @ [J;0]) step
    (/root/reference/mano_np.py:106-110): rotation unchanged, translation
    becomes world_t - world_rot @ J_rest.
    """
    skin_t = world_t - jnp.einsum(
        "jab,jb->ja", world_rot, joints, precision=precision
    )
    return world_rot, skin_t
