"""Shared compute policy for all ops.

One definition of the default contraction precision: every matmul/einsum in
the model must pin explicit precision — default-precision f32 contractions
run as single-pass bf16 on TPU (and on this stack even on CPU), costing
~5e-4 absolute vertex error against the <1e-4 budget.

HIGH (3-pass bf16 on the MXU) is the default: measured on a v5e chip it is
1.56x the throughput of HIGHEST (6-pass) at 3.8e-6 max vertex error vs the
float64 oracle — 26x inside the 1e-4 gate (docs/benchmarking.md, round-2
table). On CPU, HIGH and HIGHEST are identical f32 math, so oracle-parity
tests are precision-invariant. Pass ``precision=jax.lax.Precision.HIGHEST``
explicitly where the last two decimal digits matter more than speed.
"""

import functools

import jax
import jax.numpy as jnp

DEFAULT_PRECISION = jax.lax.Precision.HIGH

# TPU register tiling (f32): kernels pad their lane axis to LANE and their
# sublane/contraction axes to SUBLANE multiples.
LANE = 128
SUBLANE = 8


def cdiv(a: int, b: int) -> int:
    """Ceiling division (grid/pad arithmetic in the Pallas kernels)."""
    return -(-a // b)


def split_hi_lo(x):
    """f32 -> (hi, lo) bf16 pair with x ~= hi + lo — INSIDE-KERNEL version.

    The operand split of the HIGH-precision 3-pass decomposition. This
    convert-based form is correct under Mosaic (measured 1.45e-6 vertex
    error on-chip) but MUST NOT run at the XLA level: XLA:TPU folds the
    bf16->f32 convert pair to identity, so ``x - f32(bf16(x))`` compiles
    to literally zero (measured) and the decomposition silently collapses
    to single-pass bf16. Use ``split_hi_lo_xla`` outside kernels."""
    hi = x.astype(jnp.bfloat16)
    lo = (x - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def split_hi_lo_xla(x):
    """f32 -> (hi, lo) bf16 pair with x ~= hi + lo — XLA-LEVEL version.

    Fold-proof form of ``split_hi_lo`` for code compiled by XLA (operand
    pre-splitting outside Pallas kernels): the high half is extracted by
    masking the low 16 mantissa bits (truncation — every such value is
    exactly representable in bf16), so there is no convert round-trip for
    the simplifier to elide and the residual subtraction stays exact f32
    (Sterbenz). The truncated hi makes lo at most 2x the round-to-nearest
    split's — immaterial, since lo is fully carried by the decomposition.
    """
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    hi_f32 = jax.lax.bitcast_convert_type(
        bits & jnp.uint32(0xFFFF0000), jnp.float32
    )
    hi = hi_f32.astype(jnp.bfloat16)       # exact: value is on the bf16 grid
    lo = (x - hi_f32).astype(jnp.bfloat16)
    return hi, lo


def dot3(a_hi, a_lo, b_hi, b_lo):
    """HIGH-precision product of pre-split operands: 3 bf16 MXU passes
    (a_hi·b_hi + a_hi·b_lo + a_lo·b_hi), f32 accumulation."""
    d = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    return d(a_hi, b_hi) + d(a_hi, b_lo) + d(a_lo, b_hi)


def kernel_dot(a, b, precision=DEFAULT_PRECISION):
    """Precision-faithful matmul for INSIDE Pallas kernels.

    Mosaic ignores the surrounding jit's precision config and lowers a bare
    ``jnp.dot`` to single-pass bf16 on the MXU (~2.4e-3 relative error —
    measured on v5e; fails the 1e-4 vertex gate that interpret-mode tests
    can't see). It honors ``Precision.HIGHEST`` (6-pass, 2e-7) but rejects
    ``HIGH``, so HIGH is implemented here as the standard 3-pass bf16
    decomposition a ≈ a_hi + a_lo: a_hi·b_hi + a_hi·b_lo + a_lo·b_hi
    (5e-6 relative error measured on-chip — same policy XLA applies for
    HIGH outside kernels). Accumulation is always f32.
    """
    # Canonicalize: JAX accepts strings ('high', 'highest') and None for
    # precision everywhere else; an un-canonicalized string would fall
    # through BOTH enum comparisons below and silently run single-pass
    # bf16 — the exact failure this helper exists to prevent.
    if precision is not None:
        precision = jax.lax.Precision(precision)
    if precision == jax.lax.Precision.HIGHEST:
        return jnp.dot(a, b, precision=jax.lax.Precision.HIGHEST,
                       preferred_element_type=jnp.float32)
    if precision == jax.lax.Precision.HIGH:
        return dot3(*split_hi_lo(a), *split_hi_lo(b))
    return jnp.dot(a, b, preferred_element_type=jnp.float32)

# Division guard for normalizations (normals, axis vectors). Safe for both
# f32 and f64 inputs: comfortably above denormals, far below any real
# geometric magnitude in meters.
EPS = 1e-12
