"""Shared compute policy for all ops.

One definition of the default contraction precision: every matmul/einsum in
the model must pin explicit precision — default-precision f32 contractions
run as single-pass bf16 on TPU (and on this stack even on CPU), costing
~5e-4 absolute vertex error against the <1e-4 budget.

HIGH (3-pass bf16 on the MXU) is the default: measured on a v5e chip it is
1.56x the throughput of HIGHEST (6-pass) at 3.8e-6 max vertex error vs the
float64 oracle — 26x inside the 1e-4 gate (docs/benchmarking.md, round-2
table). On CPU, HIGH and HIGHEST are identical f32 math, so oracle-parity
tests are precision-invariant. Pass ``precision=jax.lax.Precision.HIGHEST``
explicitly where the last two decimal digits matter more than speed.
"""

import jax

DEFAULT_PRECISION = jax.lax.Precision.HIGH

# Division guard for normalizations (normals, axis vectors). Safe for both
# f32 and f64 inputs: comfortably above denormals, far below any real
# geometric magnitude in meters.
EPS = 1e-12
