"""Shared compute policy for all ops.

One definition of the default contraction precision: every matmul/einsum in
the model must pin explicit precision — default-precision f32 contractions
run as bf16 passes on TPU (and on this stack even on CPU), costing ~1e-2
absolute error against the <1e-4 vertex budget.
"""

import jax

DEFAULT_PRECISION = jax.lax.Precision.HIGHEST

# Division guard for normalizations (normals, axis vectors). Safe for both
# f32 and f64 inputs: comfortably above denormals, far below any real
# geometric magnitude in meters.
EPS = 1e-12
