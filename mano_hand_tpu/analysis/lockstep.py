"""Lockstep-drift detector for the mirrored fused-launch scaffolding.

``ops/pallas_forward.py:forward_verts_fused_full`` and its two-hand
mirror ``forward_verts_fused_full_hands`` deliberately duplicate the
host-side launch scaffolding (operand prep, padding, BlockSpecs,
HIGH-path split) line for line instead of sharing a builder — the
one-hand path is the measured headline kernel and stays untouched
(both docstrings carry the LOCKSTEP note). The constraint was
previously enforced by reviewers remembering it.

This detector fingerprints each function's normalized AST (docstring
stripped, positions excluded — comments and formatting never matter)
and compares both against the committed baseline:

* exactly ONE fingerprint changed -> FAIL: the mirror drifted;
* BOTH changed -> a lockstep edit; passes, with a reminder to
  recommit the baseline (``mano analyze --update-baseline``);
* neither changed -> clean.
"""

from __future__ import annotations

import ast
import hashlib
from pathlib import Path
from typing import Dict, List, Optional, Sequence

from .common import Finding

OPS_PATH = (Path(__file__).resolve().parents[1] / "ops"
            / "pallas_forward.py")

#: The mirrored pair under the LOCKSTEP constraint.
LOCKSTEP_PAIR = ("forward_verts_fused_full",
                 "forward_verts_fused_full_hands")


def _strip_docstring(fn: ast.FunctionDef) -> ast.FunctionDef:
    # Mutating is safe: the tree is parsed fresh per fingerprint call.
    if (fn.body and isinstance(fn.body[0], ast.Expr)
            and isinstance(fn.body[0].value, ast.Constant)
            and isinstance(fn.body[0].value.value, str)):
        fn.body = fn.body[1:] or [ast.Pass()]
    return fn


def fingerprint_function(path: Path, func_name: str) -> str:
    """sha256 of the function's normalized AST (no docstring, no
    source positions) — stable under comments/reformatting, changed by
    any code edit."""
    tree = ast.parse(Path(path).read_text(), filename=str(path))
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            dump = ast.dump(_strip_docstring(node),
                            include_attributes=False)
            return hashlib.sha256(dump.encode()).hexdigest()
    raise ValueError(f"{path} has no function {func_name!r}")


def _lineno(path: Path, func_name: str) -> int:
    tree = ast.parse(Path(path).read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            return node.lineno
    return 0


def check_lockstep(
    baseline: Dict[str, str],
    path: Path = OPS_PATH,
    pair: Sequence[str] = LOCKSTEP_PAIR,
) -> List[Finding]:
    """Compare the pair's fingerprints against the committed baseline.

    Returns failures only for one-sided drift; a lockstep edit of both
    passes (recommit the baseline to re-arm detection).
    """
    path = Path(path)
    rel = path.name if path.is_absolute() else str(path)
    current = {name: fingerprint_function(path, name) for name in pair}
    missing = [n for n in pair if n not in baseline]
    if missing:
        return [Finding(
            "lockstep-drift", rel, _lineno(path, missing[0]),
            f"no committed lockstep baseline for {missing} — run "
            "`mano analyze --update-baseline` and commit "
            "analysis/baseline.json")]
    changed = [n for n in pair if current[n] != baseline[n]]
    if len(changed) == 1:
        drifted = changed[0]
        (untouched,) = [n for n in pair if n != drifted]
        return [Finding(
            "lockstep-drift", rel, _lineno(path, drifted),
            f"{drifted} changed but its LOCKSTEP mirror {untouched} "
            "did not (see both docstrings: the launch scaffolding is "
            "mirrored line for line) — apply the change to BOTH, then "
            "`mano analyze --update-baseline`")]
    return []


def lockstep_stale(baseline: Dict[str, str],
                   path: Path = OPS_PATH,
                   pair: Sequence[str] = LOCKSTEP_PAIR) -> Optional[str]:
    """Non-failing advisory: both fingerprints moved in lockstep, so
    the committed baseline should be regenerated."""
    current = {name: fingerprint_function(Path(path), name)
               for name in pair}
    changed = [n for n in pair
               if baseline.get(n) is not None and current[n] != baseline[n]]
    if len(changed) == len(pair):
        return ("lockstep pair edited in lockstep (OK) — recommit the "
                "baseline with `mano analyze --update-baseline`")
    return None


def current_fingerprints(path: Path = OPS_PATH,
                         pair: Sequence[str] = LOCKSTEP_PAIR
                         ) -> Dict[str, str]:
    return {name: fingerprint_function(Path(path), name) for name in pair}
