"""Jaxpr program auditor: abstract-eval every reachable program family.

Two silent precision collapses were only caught by probing compiled
paths in the same compilation context as the timed path
(docs/roadmap.md process notes). This auditor moves the cheap half of
that probe to review time: every program family the serving engine (or
bench) can dispatch is traced ON CPU — no chip, no compile — and the
resulting jaxpr is audited for

* **float64 leaks** — an f64 aval anywhere (inputs, outputs, any
  equation) doubles bandwidth on the serving hot path and silently
  changes numerics vs the committed f32 contract;
* **host callbacks** — a ``pure_callback``/``io_callback``/debug print
  that sneaks into a jitted program syncs the device per batch (and
  hangs with the tunnel down mid-dispatch);
* **donation** — each family's documented ``donate_argnums`` actually
  reach the lowering (pose/shape donated on the full path, pose only on
  the gathered path — the table must NOT be donated, other in-flight
  snapshots read it; the CPU failover tier donates nothing);
* **primitive counts** — the flattened per-program primitive histogram
  must match ``analysis/baseline.json``, so silent compile-graph bloat
  (an accidental extra transpose sweep, a dropped fusion) shows up in
  review instead of on the chip. Intentional changes:
  ``mano analyze --update-baseline``.

Program families (ISSUE 7, extended by PR 10, PR 12, and PR 14): full
forward, posed (pose-only fast path), gathered (PR-4 coalescing),
fused one-/two-hand single-launch kernels, the FUSED gathered
pose-only serving kernel (PR 10), the bf16-TIER gathered families
(PR 14 — XLA and fused forms, with a dtype-policy assertion: bf16
contraction operands must accumulate into f32 and the program's
outputs stay f32; f64/complex remain banned everywhere), the
CPU-failover tier, and the stream-session per-frame solve (PR 12 —
the frozen-shape LM tracker step every ``open_stream`` session
shares).
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .common import Finding

#: Fixed trace shapes: primitive counts are only comparable at fixed
#: shapes, and small ones keep the audit in the seconds range.
_BUCKET = 8
_CAPACITY = 4

_CALLBACK_MARKERS = ("callback", "outside_call", "infeed", "outfeed")


class ProgramSpec(NamedTuple):
    name: str               # e.g. "gathered"
    family: str             # one of the five audited families
    fn: Callable            # positional-args callable to trace
    args: Tuple             # concrete CPU example arguments
    donate_argnums: Tuple[int, ...]   # as built for device serving
    expect_donated: Tuple[int, ...]   # flat arg indices that MUST donate
    lowerable: bool = True  # False: Pallas TPU program — jaxpr only
    bf16: bool = False      # True: a PR-14 bf16-tier family — the
    #   dtype-policy assertion applies (every bf16-operand dot must
    #   accumulate f32; the program's outputs stay f32)


def build_program_specs() -> List[ProgramSpec]:
    """The audited programs, built exactly the way serving builds them
    (params/table as runtime ARGUMENTS — the bit-identity policy)."""
    import jax

    from mano_hand_tpu.assets import synthetic_pair, synthetic_params
    from mano_hand_tpu.models import core
    from mano_hand_tpu.ops import pallas_forward, pallas_posed

    params = synthetic_params(seed=0).astype(np.float32)
    left, right = synthetic_pair(seed=0)
    params2 = core.stack_params(left.astype(np.float32),
                                right.astype(np.float32))
    j, s = params.n_joints, params.n_shape
    pose = np.zeros((_BUCKET, j, 3), np.float32)
    shape = np.zeros((_BUCKET, s), np.float32)
    shaped = jax.device_put(core.specialize(params, np.zeros(s, np.float32)))
    table = core.subject_table(params, _CAPACITY)
    idx = np.zeros((_BUCKET,), np.int32)
    pose2 = np.zeros((2, _BUCKET, j, 3), np.float32)
    shape2 = np.zeros((2, _BUCKET, s), np.float32)

    return [
        # serving/engine.py:build_bucket_executable — pose+shape donated
        # on device backends.
        ProgramSpec(
            "full", "full",
            lambda q, p, sh: core.forward_batched(q, p, sh).verts,
            (params, pose, shape), donate_argnums=(1, 2),
            expect_donated=(1, 2)),
        # models/core.py:jit_forward_posed_batched — the PR-2 pose-only
        # fast path over one baked subject.
        ProgramSpec(
            "posed", "posed",
            lambda sh, p: core.forward_posed_batched(sh, p).verts,
            (shaped, pose), donate_argnums=(), expect_donated=()),
        # serving/engine.py:build_posed_gather_executable — pose donated,
        # table NOT (in-flight snapshots read it).
        ProgramSpec(
            "gathered", "gathered",
            lambda tab, ix, p: core.forward_posed_gather(tab, ix, p).verts,
            (table, idx, pose), donate_argnums=(2,),
            expect_donated=(2,)),
        # ops/pallas_forward.py one-/two-hand single-launch kernels.
        # Jaxpr-audited only: lowering a TPU pallas_call needs the chip
        # (the interpret lane covers execution; `make bench-interpret`).
        ProgramSpec(
            "fused_one", "fused",
            lambda q, p, sh: pallas_forward.forward_verts_fused_full(
                q, p, sh),
            (params, pose, shape), donate_argnums=(),
            expect_donated=(), lowerable=False),
        ProgramSpec(
            "fused_two", "fused",
            lambda q2, p2, sh2: pallas_forward.forward_verts_fused_full_hands(
                q2, p2, sh2),
            (params2, pose2, shape2), donate_argnums=(),
            expect_donated=(), lowerable=False),
        # serving/engine.py:build_posed_gather_fused_executable — the
        # PR-10 fused gathered serving kernel (ops/pallas_posed.py).
        # Jaxpr-audited only, like its fused siblings (TPU pallas
        # lowering needs the chip; the interpret lane covers
        # execution — `make posed-kernel-smoke` / bench config14). The
        # live builder donates the pose buffer exactly like the XLA
        # gathered family; donation flags need a lowering, so that
        # contract is pinned by the XLA twin above.
        ProgramSpec(
            "gathered_fused", "fused",
            lambda tab, ix, p: pallas_posed.forward_posed_gather_fused(
                tab, ix, p),
            (table, idx, pose), donate_argnums=(),
            expect_donated=(), lowerable=False),
        # serving/engine.py:build_posed_gather_bf16_executable — the
        # PR-14 bf16-TIER gathered family (XLA form): bf16 contraction
        # operands with f32 accumulation on the pose-stage matmuls,
        # f32 everywhere else. Donation contract identical to the XLA
        # gathered twin (pose only; the table is read by in-flight
        # snapshots). bf16=True arms the dtype-policy assertion.
        ProgramSpec(
            "gathered_bf16", "gathered",
            lambda tab, ix, p: core.forward_posed_gather(
                tab, ix, p, compute_dtype=jax.numpy.bfloat16).verts,
            (table, idx, pose), donate_argnums=(2,),
            expect_donated=(2,), bf16=True),
        # serving/engine.py:build_posed_gather_bf16_executable(fused=
        # True) — the fused kernel's single-pass bf16 MXU form. Jaxpr-
        # audited only, like its fused siblings; the MXU pass count is
        # a Mosaic lowering property invisible off-chip, so the
        # auditable contract here is the f64/complex ban, the callback
        # ban, and the committed primitive counts (the pass-count
        # delta vs gathered_fused shows up there).
        ProgramSpec(
            "gathered_fused_bf16", "fused",
            lambda tab, ix, p: pallas_posed.forward_posed_gather_fused(
                tab, ix, p, compute_dtype=jax.numpy.bfloat16),
            (table, idx, pose), donate_argnums=(),
            expect_donated=(), lowerable=False, bf16=True),
        # serving/engine.py:build_cpu_fallback_executable — never
        # donated (CPU donation is unimplemented; the clean tier).
        ProgramSpec(
            "cpu_fallback", "cpu_fallback",
            lambda q, p, sh: core.forward_batched(q, p, sh).verts,
            (params, pose, shape), donate_argnums=(),
            expect_donated=()),
        # serving/streams.py per-frame solve (PR 12): the frozen-shape
        # LM tracker step — 48-col GN, joints data term — exactly as
        # fitting/tracking.py:make_tracker builds it for a stream
        # session (init pose + frozen betas as runtime arguments, so
        # every session shares this one program). n_steps is tiny: the
        # scan length changes execution, not the audited graph shape.
        ProgramSpec(
            "stream_fit", "stream_fit",
            lambda q, tgt, p0, fs: _lm().fit_lm(
                q, tgt, n_steps=2, data_term="joints",
                init={"pose": p0}, frozen_shape=fs).pose,
            (params, np.zeros((j, 3), np.float32),
             np.zeros((j, 3), np.float32),
             np.zeros((s,), np.float32)),
            donate_argnums=(), expect_donated=()),
    ]


def _lm():
    from mano_hand_tpu.fitting import lm as lm_mod

    return lm_mod


def _walk_jaxpr(jaxpr) -> Tuple[Dict[str, int], List, List[str], List]:
    """Flattened (primitive histogram, all avals, callback prims,
    dot-equation dtypes) of a jaxpr including every nested sub-jaxpr
    (pjit bodies, scans, conds, pallas kernels). ``dots`` records each
    ``dot_general``'s (input dtypes, output dtypes) — the raw material
    of the PR-14 dtype-policy assertion (bf16 operands must accumulate
    into f32, visible as bf16-in/f32-out dots)."""
    from jax.extend import core as jex_core  # jaxpr types

    counts: Dict[str, int] = {}
    avals: List = []
    callbacks: List[str] = []
    dots: List = []

    def visit(jx) -> None:
        closed = getattr(jx, "jaxpr", None)
        inner = closed if closed is not None and hasattr(
            closed, "eqns") else jx
        for v in (*inner.invars, *inner.outvars, *inner.constvars):
            aval = getattr(v, "aval", None)
            if aval is not None:
                avals.append(aval)
        for eqn in inner.eqns:
            name = eqn.primitive.name
            counts[name] = counts.get(name, 0) + 1
            if any(m in name for m in _CALLBACK_MARKERS):
                callbacks.append(name)
            if name == "dot_general":
                dots.append((
                    tuple(str(getattr(v.aval, "dtype", ""))
                          for v in eqn.invars
                          if getattr(v, "aval", None) is not None),
                    tuple(str(getattr(v.aval, "dtype", ""))
                          for v in eqn.outvars
                          if getattr(v, "aval", None) is not None),
                ))
            for v in (*eqn.invars, *eqn.outvars):
                aval = getattr(v, "aval", None)
                if aval is not None:
                    avals.append(aval)
            for p in eqn.params.values():
                for sub in (p if isinstance(p, (tuple, list)) else (p,)):
                    if isinstance(sub, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
                        visit(sub)

    visit(jaxpr)
    return counts, avals, callbacks, dots


def _donated_flags(fn: Callable, args: Tuple,
                   donate_argnums: Tuple[int, ...]) -> List[bool]:
    """Flat per-leaf donation flags as recorded by the lowering."""
    import warnings

    import jax

    with warnings.catch_warnings():
        # The audit lowers on CPU, where XLA declines donation with a
        # warning; args_info still records the REQUEST, which is what
        # the rule checks (the device build donates for real).
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        lowered = jax.jit(fn, donate_argnums=donate_argnums).lower(*args)
    info = jax.tree_util.tree_leaves(lowered.args_info)
    return [bool(a.donated) for a in info]


def _leaf_arg_index(args: Tuple) -> List[int]:
    """Map each flat leaf to the positional argument it came from."""
    import jax

    owners: List[int] = []
    for i, a in enumerate(args):
        owners.extend([i] * len(jax.tree_util.tree_leaves(a)))
    return owners


def audit_programs(
    baseline: Optional[dict],
    specs: Optional[Sequence[ProgramSpec]] = None,
) -> Tuple[List[Finding], dict]:
    """Audit all program families.

    Returns (findings, measured) where ``measured`` is the would-be
    baseline ``{"programs": {name: {"primitives": {...}}}}`` for
    ``--update-baseline``.
    """
    import jax

    findings: List[Finding] = []
    measured: dict = {"programs": {}}
    here = "analysis/jaxpr_audit.py"
    if specs is None:
        specs = build_program_specs()

    for spec in specs:
        jaxpr = jax.make_jaxpr(spec.fn)(*spec.args)
        counts, avals, callbacks, dots = _walk_jaxpr(jaxpr)
        measured["programs"][spec.name] = {
            "primitives": dict(sorted(counts.items()))}

        f64 = sorted({str(getattr(a, "dtype", ""))
                      for a in avals
                      if str(getattr(a, "dtype", "")) in
                      ("float64", "complex128")})
        if f64:
            findings.append(Finding(
                "jaxpr-f64-leak", here, 0,
                f"program {spec.name!r} carries {'/'.join(f64)} values "
                "— the serving contract is f32 end to end (two silent "
                "precision collapses, docs/roadmap.md process notes)"))
        if callbacks:
            findings.append(Finding(
                "jaxpr-host-callback", here, 0,
                f"program {spec.name!r} embeds host callback(s) "
                f"{sorted(set(callbacks))} — a per-batch host sync on "
                "the dispatch path (and a hang when the tunnel drops "
                "mid-call)"))

        if spec.bf16:
            # The PR-14 dtype-policy assertion: a bf16-tier family's
            # reduced-precision contractions must ACCUMULATE into f32
            # (serving/precision.py states accumulate="f32"; a
            # bf16-in/bf16-out dot is the single-pass-accumulation
            # silent-collapse class the sentinel exists to catch), and
            # the program must hand f32 vertices back to the engine.
            bad_dots = [
                (ins, outs) for ins, outs in dots
                if any(d == "bfloat16" for d in ins)
                and any(d == "bfloat16" for d in outs)
            ]
            if bad_dots:
                findings.append(Finding(
                    "jaxpr-dtype-policy", here, 0,
                    f"program {spec.name!r}: {len(bad_dots)} "
                    f"bf16-operand dot(s) accumulate in bf16 "
                    f"({bad_dots[:3]}) — the committed policy is bf16 "
                    "compute with f32 accumulation "
                    "(preferred_element_type; serving/precision.py)"))
            if spec.lowerable and not any(
                    any(d == "bfloat16" for d in ins)
                    and all(o == "float32" for o in outs)
                    for ins, outs in dots):
                # The XLA bf16 family must actually CONTAIN the
                # bf16-in/f32-out dots it claims (a refactor that
                # silently drops the casts would leave an "f32 program
                # labelled bf16" — and a phantom speed lever). The
                # fused family's passes live inside Mosaic, invisible
                # here — hence lowerable-gated.
                findings.append(Finding(
                    "jaxpr-dtype-policy", here, 0,
                    f"program {spec.name!r} is flagged bf16 but "
                    "carries no bf16-operand/f32-output dot_general — "
                    "the compute_dtype parameterization is not "
                    "reaching the contractions"))
            out_dtypes = sorted({
                str(getattr(v.aval, "dtype", ""))
                for v in jaxpr.jaxpr.outvars
                if getattr(v, "aval", None) is not None})
            if any(d not in ("float32", "int32") for d in out_dtypes):
                findings.append(Finding(
                    "jaxpr-dtype-policy", here, 0,
                    f"program {spec.name!r} outputs {out_dtypes} — the "
                    "serving engine delivers f32 vertices on every "
                    "tier (callers never see bf16 arrays)"))

        if spec.lowerable:
            flags = _donated_flags(spec.fn, spec.args, spec.donate_argnums)
            owners = _leaf_arg_index(spec.args)
            donated_args = {o for o, fl in zip(owners, flags) if fl}
            want = set(spec.expect_donated)
            if donated_args != want:
                findings.append(Finding(
                    "jaxpr-donation", here, 0,
                    f"program {spec.name!r}: donated args {sorted(donated_args)} "
                    f"!= designed {sorted(want)} (full path donates "
                    "pose+shape, gathered donates pose only — the table "
                    "is read by in-flight snapshots — and the CPU "
                    "failover tier donates nothing)"))

        base = ((baseline or {}).get("programs", {})
                .get(spec.name, {}).get("primitives"))
        if base is None:
            findings.append(Finding(
                "jaxpr-baseline", here, 0,
                f"program {spec.name!r} has no committed primitive-count "
                "baseline — run `mano analyze --update-baseline` and "
                "commit analysis/baseline.json"))
        elif base != measured["programs"][spec.name]["primitives"]:
            now = measured["programs"][spec.name]["primitives"]
            delta = {k: (base.get(k, 0), now.get(k, 0))
                     for k in sorted(set(base) | set(now))
                     if base.get(k, 0) != now.get(k, 0)}
            findings.append(Finding(
                "jaxpr-primitive-drift", here, 0,
                f"program {spec.name!r} primitive counts drifted from "
                f"baseline: {delta} (was -> is). Intentional? "
                "`mano analyze --update-baseline` and justify the graph "
                "change in the PR; unintentional bloat lands on the "
                "chip as compile time + HBM traffic"))
    return findings, measured
