"""Project-invariant static analysis (`mano analyze`, PR 7).

Six PRs of serving machinery accumulated hard-won invariants that lived
only as comments and incident lore. This package turns them into
machine-checked rules, runnable on CPU in seconds — every future kernel
or scheduling change is vetted here before it ever reaches the scarce
chip, the same way ``make bench-interpret`` keeps plumbing bugs off it.

Four checkers:

* :mod:`.policy` — an AST linter encoding the repo's written rules
  (CLAUDE.md / docs/roadmap.md process notes) as lints: bare
  ``jax.devices()`` outside a killable subprocess, ``JAX_PLATFORMS``
  env mutation, unbounded retry loops around device calls (the r3
  incident), wall-clock ``time.time()`` in deadline/TTL arithmetic,
  device work lexically inside an ``_exe_lock`` hold.
* :mod:`.locks` — extracts the ``with self.<lock>`` nesting graph of
  ``serving/engine.py`` (plus intra-class call edges) and fails on any
  cycle or any edge violating the documented
  ``_install_lock -> _exe_lock`` order.
* :mod:`.jaxpr_audit` — abstract-evals every reachable program family
  on CPU and asserts no float64 leaks, no host callbacks, donation
  as designed, and primitive counts within the committed
  ``baseline.json``.
* :mod:`.lockstep` — fingerprints the launch scaffolding of
  ``forward_verts_fused_full`` and its two-hand mirror and fails when
  one changes without the other (the documented LOCKSTEP constraint).

Audited sites silence a rule with ``# analysis: allow(<rule>)`` on (or
directly above) the flagged line. ``mano analyze --update-baseline``
recommits intentional jaxpr/lockstep baseline changes.
"""

from __future__ import annotations

from .common import (  # noqa: F401
    Finding,
    baseline_path,
    load_baseline,
    save_baseline,
)
from .policy import POLICY_RULES, lint_paths, lint_source  # noqa: F401
from .locks import check_lock_discipline  # noqa: F401
from .lockstep import LOCKSTEP_PAIR, check_lockstep, fingerprint_function  # noqa: F401
