"""Shared infrastructure for the static-analysis checkers.

Findings, the ``# analysis: allow(<rule>)`` pragma, the default scan
scope, and the committed baseline file.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Sequence, Set

#: Repo root (the package lives at <root>/mano_hand_tpu/analysis).
REPO_ROOT = Path(__file__).resolve().parents[2]

#: The committed jaxpr/lockstep baseline. Regenerate with
#: ``mano analyze --update-baseline`` when a primitive-count or
#: lockstep change is intentional (README "Static analysis").
BASELINE = Path(__file__).resolve().parent / "baseline.json"


@dataclass(frozen=True)
class Finding:
    """One rule violation: rule id, location, human message."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.message}"


# The escape hatch for audited sites: a pragma on the flagged line, or
# on the line directly above it (comment-above-statement style), lifts
# the named rule(s) there. Multiple rules: allow(rule-a, rule-b).
_PRAGMA_RE = re.compile(r"#\s*analysis:\s*allow\(([\w\-, ]+)\)")


def pragma_map(source: str) -> Dict[int, Set[str]]:
    """Line number (1-based) -> rules allowed AT that line.

    A pragma on line N covers findings on lines N and N+1, so both the
    trailing-comment and the comment-above idioms work.
    """
    allowed: Dict[int, Set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        for ln in (i, i + 1):
            allowed.setdefault(ln, set()).update(rules)
    return allowed


def apply_pragmas(findings: Sequence[Finding],
                  source: str) -> List[Finding]:
    """Drop findings silenced by an ``analysis: allow`` pragma."""
    allowed = pragma_map(source)
    return [f for f in findings
            if f.rule not in allowed.get(f.line, ())]


def default_policy_paths(root: Path = REPO_ROOT) -> List[Path]:
    """The policy linter's scan scope: the package, ``bench.py``, and
    ``scripts/*.py`` — the code that can reach the device tunnel.
    Tests and examples are out of scope (they run under conftest's
    forced-CPU harness or are documentation).
    """
    paths = sorted((root / "mano_hand_tpu").rglob("*.py"))
    bench = root / "bench.py"
    if bench.exists():
        paths.append(bench)
    paths.extend(sorted((root / "scripts").glob("*.py")))
    return [p for p in paths if "__pycache__" not in p.parts]


def load_baseline(path: Path = BASELINE) -> dict:
    if not Path(path).exists():
        return {}
    with open(path) as f:
        return json.load(f)


def save_baseline(data: dict, path: Path = BASELINE) -> None:
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def baseline_path() -> Path:
    return BASELINE
