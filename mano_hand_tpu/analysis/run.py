"""`mano analyze` driver: run all four checkers, print the verdict.

Report style follows ``scripts/bench_report.py``: one ``[PASS]``/
``[FAIL]`` line per check, findings as ``file:line: [rule] message``,
exit code 0 iff everything passes. Every failure line carries its
escape hatch — the ``# analysis: allow(<rule>)`` pragma for audited
policy/lock sites, ``--update-baseline`` for intentional jaxpr/
lockstep changes.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional

from .common import (
    Finding,
    REPO_ROOT,
    baseline_path,
    default_policy_paths,
    load_baseline,
    save_baseline,
)
from .jaxpr_audit import audit_programs
from .locks import check_lock_discipline
from .lockstep import check_lockstep, current_fingerprints, lockstep_stale
from .policy import lint_paths


def run_analysis(
    root: Path = REPO_ROOT,
    update_baseline: bool = False,
    skip_jaxpr: bool = False,
    as_json: bool = False,
    log=print,
) -> int:
    """Run every checker; returns the process exit code (0 = clean)."""
    baseline = load_baseline()
    sections: List[tuple] = []   # (name, findings, info)

    pol = lint_paths(default_policy_paths(root), root=root)
    sections.append(("policy", pol,
                     f"{len(default_policy_paths(root))} files linted"))

    locks = check_lock_discipline()
    # PR 17: the dispatch pipeline's completion stage lives in
    # serving/engine.py, so the default pass above already covers its
    # _completion_lock Condition (cycle/re-acquire) — and the policy
    # linter's new device-under-completion-lock rule enforces that it
    # stays a LEAF: the worker pops under the lock, releases, then
    # dispatches; nothing (engine locks included) is taken inside it.
    # PR 8: the obs/ tracer and flight recorder hold their own locks on
    # the dispatch path — same cycle/re-acquire rules, no documented
    # order (each class owns exactly one lock; any nesting edge a
    # refactor introduces still gets cycle-checked).
    for p in sorted((root / "mano_hand_tpu" / "obs").glob("*.py")):
        locks += check_lock_discipline(p, order=())
    # PR 12: the stream subsystem's two locks (StreamManager registry,
    # per-session fit serialization) are documented as never nested —
    # the cycle/re-acquire checker keeps that true through refactors.
    locks += check_lock_discipline(
        root / "mano_hand_tpu" / "serving" / "streams.py", order=())
    # PR 13: the lane subsystem's one LaneSet lock (placement +
    # telemetry + replica swaps; device work staged outside, which the
    # device-under-install-lock policy rule guards) — cycle/re-acquire
    # checked like the obs/ classes.
    locks += check_lock_discipline(
        root / "mano_hand_tpu" / "serving" / "lanes.py", order=())
    # PR 15: the network edge — the server's connection/drain state and
    # the stream frame-future's cancel-forwarding lock (streams.py's
    # _FrameFuture is covered by the streams pass above; edge/ holds
    # no engine locks, and the policy linter scans it via the package
    # rglob like every other subsystem). PR 18 grows this glob's scope
    # to the fleet front tier: edge/proxy.py (loop-thread counters +
    # drain coordination) and edge/fleet.py (worker supervision) —
    # tests/test_analysis.py pins both by name, with a seeded
    # drain/route lock-cycle fixture proving the rule fires on
    # proxy-shaped code.
    for p in sorted((root / "mano_hand_tpu" / "edge").glob("*.py")):
        locks += check_lock_discipline(p, order=())
    # PR 16: the subject store's one LEAF lock (warm LRU + promotion
    # registry + cold index; transfers and page IO staged outside, the
    # documented contract in its module docstring) — cycle/re-acquire
    # checked like the obs/ classes, and the policy linter's
    # device-work/wallclock rules scan it via the package rglob.
    locks += check_lock_discipline(
        root / "mano_hand_tpu" / "serving" / "subject_store.py",
        order=())
    # PR 19: the closed-loop controller's one LEAF lock (actuation
    # ledger + snapshot values share ONE hold; engine setters run
    # OUTSIDE it — the actuate-vs-load() cycle the seeded fixture in
    # tests/fixtures/analysis/ deadlocks on) and the traffic
    # generator (no locks by design; pinned here so a refactor that
    # grows one gets cycle-checked from day one). The policy linter's
    # wallclock-deadline rule scans both via the package rglob — the
    # controller's cadence/rate-limit arithmetic is exactly the
    # monotonic-only territory that rule exists for.
    locks += check_lock_discipline(
        root / "mano_hand_tpu" / "serving" / "control.py", order=())
    locks += check_lock_discipline(
        root / "mano_hand_tpu" / "serving" / "traffic.py", order=())
    # PR 20: the self-healing tier. edge/fleet.py rides the edge/ glob
    # above and now holds TWO more graphs — the FleetSupervisor's
    # ledger lock (a LEAF: heals rewire the proxy OUTSIDE it; load()'s
    # one-hold snapshot is the torn-read contract the seeded
    # heal-vs-healthz cycle fixture deadlocks on) and the ProxyPair's
    # process bookkeeping. runtime/chaos.py (campaign schedule lock,
    # fault injection on the monotonic clock) is scanned here by name —
    # chaos code that deadlocks or reads time.time() would corrupt the
    # very drills that certify the healing paths.
    locks += check_lock_discipline(
        root / "mano_hand_tpu" / "runtime" / "chaos.py", order=())
    sections.append(("lock-discipline", locks,
                     "serving/engine.py + serving/streams.py + "
                     "serving/lanes.py + serving/subject_store.py + "
                     "serving/control.py + serving/traffic.py + "
                     "runtime/chaos.py + "
                     "edge/ + obs/ nesting graphs + call edges"))

    step = check_lockstep(baseline.get("lockstep", {}))
    stale_note = lockstep_stale(baseline.get("lockstep", {}))
    sections.append(("lockstep", step,
                     "ops/pallas_forward.py fused one-/two-hand pair"))

    jaxpr_findings: List[Finding] = []
    measured = None
    if not skip_jaxpr:
        jaxpr_findings, measured = audit_programs(baseline)
        sections.append((
            "jaxpr-audit", jaxpr_findings,
            f"{len(measured['programs'])} programs over 6 families "
            "(full/posed/gathered/fused/cpu_fallback/stream_fit) "
            "traced on CPU"))

    if update_baseline:
        new = dict(baseline)
        if measured is not None:
            new["programs"] = measured["programs"]
        new["lockstep"] = current_fingerprints()
        save_baseline(new)
        if not as_json:
            # JSON mode keeps the one-machine-readable-line contract
            # (the bench.py policy); the flag rides in the payload.
            log(f"baseline updated: {baseline_path()}")
        # Baseline-relative findings are void once recommitted; the
        # structural rules (f64, callbacks, donation, policy, locks)
        # still judge this run.
        void = {"jaxpr-baseline", "jaxpr-primitive-drift",
                "lockstep-drift"}
        sections = [(n, [f for f in fs if f.rule not in void], info)
                    for n, fs, info in sections]
        stale_note = None

    all_findings = [f for _, fs, _ in sections for f in fs]
    rc = 1 if all_findings else 0

    if as_json:
        log(json.dumps({
            "ok": rc == 0,
            "findings": [f.__dict__ for f in all_findings],
            "sections": {n: len(fs) for n, fs, _ in sections},
            "baseline_updated": bool(update_baseline),
        }))
        return rc

    for name, findings, info in sections:
        ok = not findings
        log(f"  [{'PASS' if ok else 'FAIL'}] {name}: {info}"
            + ("" if ok else f" — {len(findings)} finding(s)"))
        for f in findings:
            log(f"    {f.format()}")
    if stale_note:
        log(f"  note: {stale_note}")
    if rc:
        log("RESULT: ANALYZE FAILING — audited sites may add "
            "`# analysis: allow(<rule>)` on (or above) the flagged "
            "line; intentional jaxpr/lockstep changes recommit via "
            "`mano analyze --update-baseline` (README 'Static "
            "analysis')")
    else:
        log("RESULT: ANALYZE OK")
    return rc
