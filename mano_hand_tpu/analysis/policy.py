"""AST policy linter: the repo's written rules as machine-checked lints.

Every rule codifies an invariant that already cost an incident or a
debugging session (CLAUDE.md, docs/roadmap.md process notes):

``bare-devices``
    ``jax.devices()`` / ``jax.local_devices()`` with no platform
    argument resolves the DEFAULT backend — on this box one real TPU
    behind a flaky tunnel, where the call HANGS for hours when the
    tunnel is down (r3: ~10 h, r4: 15+ h). Probe in a killable
    subprocess (``bench.py``/``runtime/health.py``) instead.
    ``jax.devices("cpu")`` is exempt: the host backend cannot hang.

``platforms-env``
    Mutating ``os.environ["JAX_PLATFORMS"]`` selects nothing here: a
    site hook re-sets jax_platforms at interpreter startup, overriding
    the env var. Only ``jax.config.update("jax_platforms", ...)`` wins.

``unbounded-retry``
    A ``while True`` loop with a device call and no ``break``/``return``
    is the r3 incident as a lint rule: a leftover builder retry loop
    polled a downed tunnel for hours while the driver bench queued
    behind it. Bound every retry loop by a deadline or an attempt
    count (``scripts/bench_tpu_wait.sh`` is the pattern).

``wallclock-deadline``
    ``time.time()`` in deadline/TTL arithmetic breaks on a clock jump
    (NTP step, suspend/resume): a wait can give up instantly or never.
    Use ``time.monotonic()``; wall clock is only for CROSS-PROCESS
    timestamps (file mtimes — the devicelock claim-age check).

``device-under-exe-lock``
    ``serving/engine.py``'s dispatcher blocks on ``_exe_lock`` for
    every batch; on the tunneled backend a device call inside that lock
    (device_put / jit build / block_until_ready) can stall the entire
    serving path for seconds. Stage device work OUTSIDE the lock (the
    ``_install_subject`` bake-and-swap pattern).

``device-under-install-lock``
    The ``_install_lock`` variant (docs/roadmap.md PR-7 "Open", landed
    with the PR-13 multi-device lanes): installs are serialized per
    engine, and with N lane replicas one install's device work is N
    devices wide — a checkpoint restore, a racing ``specialize()``,
    and every lane broadcast queue behind whatever device calls sit
    inside the hold. The audited EXCEPTION is the engine's documented
    bake-and-swap (``_install_subject`` stages the functional row
    write under ``_install_lock`` precisely so it stays OUT of
    ``_exe_lock``; the dispatcher never takes ``_install_lock``) —
    that one site carries the pragma with its justification. New code
    (serving/lanes.py's replica machinery in particular) keeps device
    work outside EVERY lock.

``device-under-completion-lock``
    The PR-17 completion stage's ``_completion_lock`` (a Condition) is
    the handoff between the dispatcher and the completion worker: the
    dispatcher blocks on it for backpressure at every staged launch,
    and ``drain()``/``stop()`` wait on it for the in-flight horizon.
    It is a LEAF lock by design — nothing is ever taken under it and
    no device work happens inside a hold (the worker pops the item,
    RELEASES the lock, then dispatches/reads back). A device call
    inside the hold would wedge the dispatcher behind a tunneled RPC
    exactly like the ``_exe_lock`` case, except worse: ``stop()``
    waits on the same Condition, so shutdown wedges too.

Audited sites: ``# analysis: allow(<rule>)`` on or directly above the
flagged line.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable, List, Optional

from .common import Finding, apply_pragmas

POLICY_RULES = (
    "bare-devices",
    "platforms-env",
    "unbounded-retry",
    "wallclock-deadline",
    "device-under-exe-lock",
    "device-under-install-lock",
    "device-under-completion-lock",
)

_DEADLINE_NAME_RE = re.compile(
    r"deadline|expir|ttl|timeout|time_left|budget", re.IGNORECASE)

#: Calls that touch the device / build executables; flagged inside an
#: ``_exe_lock`` hold and used as the "device call" marker for the
#: retry-loop rule (any ``jax.*`` call counts there too).
_DEVICE_ATTRS = {"device_put", "block_until_ready", "devices",
                 "local_devices"}


def _attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted-name string of a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_bare_devices(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    if chain not in ("jax.devices", "jax.local_devices"):
        return False
    # An explicit platform argument pins the backend; only the
    # argument-less default-backend form can hang on the tunnel.
    return not call.args and not call.keywords


def _is_device_call(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    if chain is None:
        return False
    if chain.startswith("jax."):
        return True
    leaf = chain.rsplit(".", 1)[-1]
    return leaf in _DEVICE_ATTRS or leaf.startswith("jit_")


def _mentions_deadline_name(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _DEADLINE_NAME_RE.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and \
                _DEADLINE_NAME_RE.search(sub.attr):
            return True
    return False


def _contains_wallclock(node: ast.AST) -> bool:
    return any(isinstance(sub, ast.Call)
               and _attr_chain(sub.func) == "time.time"
               for sub in ast.walk(node))


def _walk_same_frame(node: ast.AST) -> Iterable[ast.AST]:
    """``node`` and its descendants, NOT descending into nested
    def/lambda (their bodies run later, in another frame — neither
    their calls nor their returns belong to the enclosing context)."""
    stack = [node]
    while stack:
        sub = stack.pop()
        yield sub
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue   # the def node itself is same-frame; its body isn't
        stack.extend(ast.iter_child_nodes(sub))


def _iter_body_calls(node: ast.AST) -> Iterable[ast.Call]:
    return (sub for sub in _walk_same_frame(node)
            if isinstance(sub, ast.Call))


class _PolicyVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._exe_lock_depth = 0
        self._install_lock_depth = 0
        self._completion_lock_depth = 0

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(rule, self.path, getattr(node, "lineno", 0), message))

    # -- bare-devices / device-under-exe-lock ------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if _is_bare_devices(node):
            self._emit(
                "bare-devices", node,
                "bare jax.devices() resolves the default backend and "
                "HANGS for hours when the device tunnel is down — probe "
                "in a killable subprocess (bench.py/runtime/health.py), "
                "or pass an explicit platform")
        chain = _attr_chain(node.func) or ""
        if chain == "os.environ.setdefault" and node.args:
            key = node.args[0]
            if isinstance(key, ast.Constant) and key.value == "JAX_PLATFORMS":
                self._emit(
                    "platforms-env", node,
                    "JAX_PLATFORMS env is overridden by a site hook at "
                    "interpreter startup; select platforms via "
                    'jax.config.update("jax_platforms", ...) instead')
        if (self._exe_lock_depth > 0 or self._install_lock_depth > 0
                or self._completion_lock_depth > 0):
            leaf = chain.rsplit(".", 1)[-1]
            if (chain in ("jax.device_put", "jax.jit",
                          "jax.block_until_ready")
                    or leaf in ("device_put", "block_until_ready")
                    or leaf.startswith("jit_")
                    or leaf in ("lower", "compile")):
                if self._exe_lock_depth > 0:
                    self._emit(
                        "device-under-exe-lock", node,
                        f"{chain}() lexically inside an _exe_lock hold: "
                        "the dispatcher blocks on _exe_lock per batch, "
                        "and a device call here can stall serving for "
                        "seconds on the tunneled backend — stage device "
                        "work outside the lock "
                        "(engine.py:_install_subject pattern)")
                if self._install_lock_depth > 0:
                    self._emit(
                        "device-under-install-lock", node,
                        f"{chain}() lexically inside an _install_lock "
                        "hold: installs serialize behind it, and with "
                        "per-device lanes one install's device work is "
                        "N replicas wide — restores, racing "
                        "specialize(), and lane broadcasts all queue "
                        "behind this call. Stage device work outside "
                        "the lock; the engine's documented bake-and-"
                        "swap is the one audited exception "
                        "(see analysis/policy.py)")
                if self._completion_lock_depth > 0:
                    self._emit(
                        "device-under-completion-lock", node,
                        f"{chain}() lexically inside a _completion_lock "
                        "hold: the dispatcher backpressures on this "
                        "Condition every staged launch and stop()/"
                        "drain() wait on it, so a device call here "
                        "wedges serving AND shutdown behind a tunneled "
                        "RPC — the completion lock is a leaf: pop the "
                        "item, release, then dispatch (engine.py "
                        "_CompletionStage._worker pattern)")
        self.generic_visit(node)

    # -- platforms-env (subscript assignment) ------------------------
    def _check_environ_target(self, target: ast.AST) -> None:
        if not isinstance(target, ast.Subscript):
            return
        chain = _attr_chain(target.value)
        if chain not in ("os.environ", "environ"):
            return
        key = target.slice
        if isinstance(key, ast.Constant) and key.value == "JAX_PLATFORMS":
            self._emit(
                "platforms-env", target,
                "JAX_PLATFORMS env is overridden by a site hook at "
                "interpreter startup; select platforms via "
                'jax.config.update("jax_platforms", ...) instead')

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_environ_target(t)
        self._check_wallclock_assign(node.targets, node.value, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_environ_target(node.target)
        self._check_wallclock_assign([node.target], node.value, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_environ_target(node.target)
        if node.value is not None:
            self._check_wallclock_assign([node.target], node.value, node)
        self.generic_visit(node)

    # -- wallclock-deadline ------------------------------------------
    def _check_wallclock_assign(self, targets, value, node) -> None:
        if not _contains_wallclock(value):
            return
        if any(_mentions_deadline_name(t) for t in targets):
            self._emit(
                "wallclock-deadline", node,
                "deadline/TTL computed from wall-clock time.time(): a "
                "clock jump (NTP step, suspend) breaks the wait — use "
                "time.monotonic() for deadline arithmetic (time.time() "
                "is for cross-process timestamps like file mtimes)")

    def visit_Compare(self, node: ast.Compare) -> None:
        sides = [node.left, *node.comparators]
        if (any(_contains_wallclock(s) for s in sides)
                and any(_mentions_deadline_name(s) for s in sides
                        if not _contains_wallclock(s))):
            self._emit(
                "wallclock-deadline", node,
                "wall-clock time.time() compared against a deadline/TTL: "
                "a clock jump breaks the wait — use time.monotonic()")
        self.generic_visit(node)

    # -- unbounded-retry ---------------------------------------------
    def visit_While(self, node: ast.While) -> None:
        test = node.test
        is_true = (isinstance(test, ast.Constant) and bool(test.value))
        if is_true:
            # Same-frame walk: a `return` inside a nested def does NOT
            # exit this loop and must not count as a bound.
            has_exit = any(
                isinstance(sub, (ast.Break, ast.Return))
                for stmt in node.body
                for sub in _walk_same_frame(stmt))
            touches_device = any(_is_device_call(c)
                                 for stmt in node.body
                                 for c in _iter_body_calls(stmt))
            if touches_device and not has_exit:
                self._emit(
                    "unbounded-retry", node,
                    "unbounded `while True` retry loop around a device "
                    "call (the r3 incident: a bare retry loop polled a "
                    "downed tunnel for hours) — bound it by a deadline "
                    "or attempt count (scripts/bench_tpu_wait.sh is the "
                    "pattern)")
        self.generic_visit(node)

    # Nested def/lambda bodies run LATER, outside the lexical lock
    # context — a deferred jax call stored under the lock is the
    # engine's normal caching pattern, not a violation.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved = (self._exe_lock_depth, self._install_lock_depth,
                 self._completion_lock_depth)
        self._exe_lock_depth = self._install_lock_depth = 0
        self._completion_lock_depth = 0
        self.generic_visit(node)
        (self._exe_lock_depth, self._install_lock_depth,
         self._completion_lock_depth) = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = (self._exe_lock_depth, self._install_lock_depth,
                 self._completion_lock_depth)
        self._exe_lock_depth = self._install_lock_depth = 0
        self._completion_lock_depth = 0
        self.generic_visit(node)
        (self._exe_lock_depth, self._install_lock_depth,
         self._completion_lock_depth) = saved

    # -- with self._exe_lock / self._install_lock ----------------------
    def visit_With(self, node: ast.With) -> None:
        chains = [c for item in node.items
                  if (c := _attr_chain(item.context_expr)) is not None]
        holds_exe = any(c.endswith("_exe_lock") for c in chains)
        holds_install = any(c.endswith("_install_lock") for c in chains)
        holds_completion = any(c.endswith("_completion_lock")
                               for c in chains)
        if holds_exe:
            self._exe_lock_depth += 1
        if holds_install:
            self._install_lock_depth += 1
        if holds_completion:
            self._completion_lock_depth += 1
        self.generic_visit(node)
        if holds_exe:
            self._exe_lock_depth -= 1
        if holds_install:
            self._install_lock_depth -= 1
        if holds_completion:
            self._completion_lock_depth -= 1


def lint_source(source: str, path: str = "<source>") -> List[Finding]:
    """Lint one file's source; pragma-silenced findings are dropped."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0,
                        f"file does not parse: {e.msg}")]
    v = _PolicyVisitor(path)
    v.visit(tree)
    return apply_pragmas(v.findings, source)


def lint_paths(paths: Iterable[Path],
               root: Optional[Path] = None) -> List[Finding]:
    findings: List[Finding] = []
    for p in paths:
        p = Path(p)
        rel = str(p.relative_to(root)) if root else str(p)
        findings.extend(lint_source(p.read_text(), rel))
    return findings
