"""Lock-discipline checker for ``serving/engine.py``-style classes.

The engine's documented order is ``_install_lock -> _exe_lock``, never
the reverse (engine.py:_install_subject docstring): the dispatcher
blocks on ``_exe_lock`` for every batch, so anything that could make an
``_exe_lock`` holder wait on an installer inverts the latency design —
and a genuine inversion deadlocks under concurrency.

The checker is purely lexical, which is what makes it a REVIEW-time
gate:

* lock attributes are discovered from ``self.<name> = threading.Lock()``
  (or ``RLock``) assignments in ``__init__``;
* within each method, a ``with self.<lock>:`` nested inside another
  acquires an ordering edge ``outer -> inner``;
* a call ``self.m(...)`` made while a lock is lexically held adds edges
  from every held lock to every lock ``m`` may acquire — transitively
  through the intra-class call graph (a conservative
  over-approximation: a callee that acquires only on paths the caller
  never takes still counts, which is the right bias for a deadlock
  gate);
* violations: any cycle in the edge graph (including a self-edge — a
  re-acquire of a non-reentrant ``threading.Lock`` deadlocks
  immediately), and any edge that runs AGAINST the documented order.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .common import Finding, apply_pragmas

#: The documented order for the serving engine (outer first).
ENGINE_LOCK_ORDER = ("_install_lock", "_exe_lock")

ENGINE_PATH = Path(__file__).resolve().parents[1] / "serving" / "engine.py"


def _attr_of_self(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes assigned a ``threading.Lock()``/``RLock()`` anywhere
    in the class body (``__init__`` in practice)."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        v = node.value
        if not (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr in ("Lock", "RLock")):
            continue
        for t in node.targets:
            attr = _attr_of_self(t)
            if attr:
                locks.add(attr)
    return locks


class _MethodScan(ast.NodeVisitor):
    """Per-method facts: direct nesting edges, lock acquisitions, and
    self-method calls annotated with the locks lexically held."""

    def __init__(self, locks: Set[str], methods: Set[str]):
        self.locks = locks
        self.methods = methods
        self.held: List[str] = []
        self.acquires: Set[str] = set()      # locks acquired in this body
        self.edges: List[Tuple[str, str, int]] = []   # (outer, inner, line)
        self.calls: List[Tuple[Tuple[str, ...], str, int]] = []

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            attr = _attr_of_self(item.context_expr)
            if attr in self.locks:
                self.acquires.add(attr)
                # A re-acquire of a non-reentrant Lock (attr already in
                # held) lands here as the self-edge (attr, attr): a
                # guaranteed self-deadlock, reported as a cycle of one.
                for outer in self.held:
                    self.edges.append((outer, attr, node.lineno))
                self.held.append(attr)
                entered.append(attr)
        self.generic_visit(node)
        for _ in entered:
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        attr = _attr_of_self(node.func)
        if attr in self.methods:
            # Recorded even when no lock is held: lock-free calls still
            # propagate acquisition sets through the call-graph fixpoint
            # (m1 holds A -> m2 (lock-free) -> m3 acquires B).
            self.calls.append((tuple(self.held), attr, node.lineno))
        self.generic_visit(node)

    # Nested defs/lambdas run later, outside the lexical lock context.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved


def _transitive_acquires(scans: Dict[str, "_MethodScan"]
                         ) -> Dict[str, Set[str]]:
    """Locks each method may acquire, directly or via self-calls
    anywhere in its body (fixpoint over the intra-class call graph)."""
    callees: Dict[str, Set[str]] = {
        name: {c for c in _all_self_calls(scan) if c in scans}
        for name, scan in scans.items()}
    acq = {name: set(scan.acquires) for name, scan in scans.items()}
    changed = True
    while changed:
        changed = False
        for name in scans:
            for c in callees[name]:
                add = acq.get(c, set()) - acq[name]
                if add:
                    acq[name] |= add
                    changed = True
    return acq


def _all_self_calls(scan: "_MethodScan") -> Set[str]:
    return {callee for _, callee, _ in scan.calls}


def _find_cycle(edges: Set[Tuple[str, str]]) -> Optional[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    path: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GREY
        path.append(n)
        for m in sorted(graph[n]):
            if color[m] == GREY:
                return path[path.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        path.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def check_lock_discipline(
    path: Path = ENGINE_PATH,
    order: Sequence[str] = ENGINE_LOCK_ORDER,
    class_name: Optional[str] = None,
) -> List[Finding]:
    """Check one file's classes for lock-order violations and cycles."""
    path = Path(path)
    source = path.read_text()
    tree = ast.parse(source, filename=str(path))
    findings: List[Finding] = []
    rel = path.name if path.is_absolute() else str(path)

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        if class_name is not None and cls.name != class_name:
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods = {n.name for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        scans: Dict[str, _MethodScan] = {}
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _MethodScan(locks, methods)
                for stmt in node.body:
                    scan.visit(stmt)
                scans[node.name] = scan

        acq = _transitive_acquires(scans)
        # Edge set: direct lexical nesting + (held locks x callee's
        # transitive acquisitions) for every under-lock self-call.
        edges: Dict[Tuple[str, str], Tuple[int, str]] = {}
        for mname, scan in scans.items():
            for a, b, line in scan.edges:
                edges.setdefault((a, b), (line, f"{cls.name}.{mname}"))
            for held, callee, line in scan.calls:
                for inner in acq.get(callee, set()):
                    for outer in held:
                        edges.setdefault(
                            (outer, inner),
                            (line, f"{cls.name}.{mname} -> "
                                   f"self.{callee}()"))

        rank = {name: i for i, name in enumerate(order)}
        for (a, b), (line, where) in sorted(edges.items(),
                                            key=lambda kv: kv[1][0]):
            if a == b:
                findings.append(Finding(
                    "lock-discipline", rel, line,
                    f"{where}: re-acquisition of non-reentrant "
                    f"self.{a} while already held — guaranteed "
                    "deadlock"))
            elif a in rank and b in rank and rank[a] > rank[b]:
                findings.append(Finding(
                    "lock-discipline", rel, line,
                    f"{where}: acquires self.{b} while holding "
                    f"self.{a}, inverting the documented order "
                    f"{' -> '.join(order)} (engine.py:_install_subject "
                    "docstring) — deadlocks against a compliant "
                    "holder"))
        cyc = _find_cycle({e for e in edges if e[0] != e[1]})
        if cyc:
            line = min(edges[(a, b)][0]
                       for a, b in zip(cyc, cyc[1:]) if (a, b) in edges)
            findings.append(Finding(
                "lock-discipline", rel, line,
                f"{cls.name}: lock-nesting cycle "
                f"{' -> '.join(cyc)} — two threads taking opposite "
                "arcs deadlock"))
    return apply_pragmas(findings, source)
