"""Sharded MANO execution: parameter layouts + two multi-chip forward paths.

Tensor-parallel layout: the vertex dimension (V=778) is the only axis with
real extent, so vertex-indexed arrays shard over the 'model' mesh axis while
joint-level state stays replicated:

    v_template  [V, 3]     -> P('model', None)
    shape_basis [V, 3, S]  -> P('model', None, None)
    pose_basis  [V, 3, P]  -> P('model', None, None)
    lbs_weights [V, J]     -> P('model', None)
    j_regressor [J, V]     -> P(None, 'model')   (contraction dim sharded)
    pca_basis/pca_mean/faces -> replicated

The joint regression J = Jreg . v_shaped contracts over the sharded V axis,
so each device holds a partial sum — one psum over 'model' makes the joints
(and the tiny FK that consumes them) replicated, and skinning proceeds on
local vertex shards with no further communication. Batch shards over 'data'.

Two implementations:
  * ``gspmd_forward`` — jit + NamedSharding constraints; XLA's SPMD
    partitioner inserts the all-reduce automatically.
  * ``shard_map_forward`` — explicit per-shard program with a hand-placed
    ``jax.lax.psum``, for when you want manual control (and as executable
    documentation of the communication pattern).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mano_hand_tpu import ops
from mano_hand_tpu.assets.schema import ManoParams
from mano_hand_tpu.models import core
from mano_hand_tpu.ops.common import DEFAULT_PRECISION
from mano_hand_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def _shard_map(fn, **kw):
    """``jax.shard_map`` across jax versions: older jaxlibs ship it as
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` in place
    of ``check_vma`` — same semantics for these collective-free uses."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
    return sm(fn, **kw)


PARAM_SPECS = {
    "v_template": P(MODEL_AXIS, None),
    "shape_basis": P(MODEL_AXIS, None, None),
    "pose_basis": P(MODEL_AXIS, None, None),
    "j_regressor": P(None, MODEL_AXIS),
    "lbs_weights": P(MODEL_AXIS, None),
    "pca_basis": P(),
    "pca_mean": P(),
    "faces": P(),
}


def pad_verts(params: ManoParams, multiple: int) -> tuple[ManoParams, int]:
    """Zero-pad the vertex dimension to a multiple of the model-axis size.

    Padded rows are inert: zero template/basis rows and zero skinning
    weights contribute nothing to joints and produce zero vertices, which
    callers slice off. Returns (padded params, original V).
    """
    v = params.v_template.shape[0]
    pad = (-v) % multiple
    if pad == 0:
        return params, v

    def pad0(x):
        widths = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        return np.pad(np.asarray(x), widths)

    return dataclasses.replace(
        params,
        v_template=pad0(params.v_template),
        shape_basis=pad0(params.shape_basis),
        pose_basis=pad0(params.pose_basis),
        lbs_weights=pad0(params.lbs_weights),
        j_regressor=np.pad(np.asarray(params.j_regressor), [(0, 0), (0, pad)]),
    ), v


class ShardedParams(NamedTuple):
    """Mesh-placed (possibly vertex-padded) parameters + the true V.

    Padding must never leak: every consumer slices outputs back to
    ``n_verts``, so carrying the true count next to the padded PyTree is the
    only way a default argument can be correct.
    """

    params: ManoParams
    n_verts: int


def shard_params(params: ManoParams, mesh: Mesh) -> ShardedParams:
    """Place parameters on the mesh with the tensor-parallel layout.

    Pads V to the model-axis size if needed; the returned ShardedParams
    remembers the true V so forward/fit builders slice outputs correctly.
    """
    padded, n_verts = pad_verts(params, mesh.shape[MODEL_AXIS])
    placed = dataclasses.replace(
        padded,
        **{
            name: jax.device_put(
                getattr(padded, name), NamedSharding(mesh, spec)
            )
            for name, spec in PARAM_SPECS.items()
        },
    )
    return ShardedParams(placed, n_verts)


def _unwrap(params) -> tuple[ManoParams, int]:
    if isinstance(params, ShardedParams):
        return params.params, params.n_verts
    return params, params.v_template.shape[0]


def gspmd_forward(params, mesh: Mesh, n_verts: int | None = None):
    """Build a jitted batched forward with GSPMD-partitioned layout.

    ``params`` is a ShardedParams (from shard_params) or a plain ManoParams.
    Returns fn(pose [B,16,3], shape [B,S]) -> verts [B, n_verts, 3], with
    batch sharded over 'data', vertices over 'model', and the joint
    all-reduce inserted by XLA.
    """
    params, true_v = _unwrap(params)
    n_verts = n_verts or true_v
    # The true V may not divide the model axis (778 = 2 x 389); when padding
    # was applied, the sliced output can't stay vertex-sharded — leave its
    # vertex dim unconstrained and let XLA place the gather.
    out_spec = (
        P(DATA_AXIS, MODEL_AXIS)
        if n_verts % mesh.shape[MODEL_AXIS] == 0
        else P(DATA_AXIS)
    )

    @functools.partial(
        jax.jit,
        in_shardings=(
            None,  # params: keep their committed (vertex-sharded) placement
            NamedSharding(mesh, P(DATA_AXIS)),
            NamedSharding(mesh, P(DATA_AXIS)),
        ),
        out_shardings=NamedSharding(mesh, out_spec),
    )
    def fwd(prm, pose, shape):
        verts = core.forward_batched(prm, pose, shape).verts
        return verts[:, :n_verts]

    # Bind params outside the trace: passing them as a jit argument (instead
    # of capturing device arrays as constants) keeps dispatch fast on the
    # axon TPU tunnel.
    return lambda pose, shape: fwd(params, pose, shape)


def shard_map_forward(params, mesh: Mesh, n_verts: int | None = None):
    """Explicit-collective forward: per-shard program + one psum.

    The only communication in the whole forward pass is the [J, 3] joint
    all-reduce over the 'model' axis (a few hundred bytes), after which FK
    runs replicated and skinning is embarrassingly vertex-parallel.
    """
    params, true_v = _unwrap(params)
    n_verts = n_verts or true_v
    precision = DEFAULT_PRECISION

    param_specs = ManoParams(
        **PARAM_SPECS, parents=params.parents, side=params.side
    )

    def per_shard(local_params: ManoParams, pose, shape):
        # pose/shape: local batch shard [b, ...]; vertex arrays: local shard.
        def one(p, s):
            v_shaped = ops.shape_blend(
                local_params.v_template, local_params.shape_basis, s, precision
            )
            partial_joints = ops.regress_joints(
                local_params.j_regressor, v_shaped, precision
            )
            joints = jax.lax.psum(partial_joints, MODEL_AXIS)
            rot_mats = ops.rotation_matrix(p)
            v_posed = ops.pose_blend(
                v_shaped, local_params.pose_basis, rot_mats, precision
            )
            world_rot, world_t = ops.forward_kinematics(
                local_params.parents, rot_mats, joints, precision
            )
            skin_rot, skin_t = ops.skinning_transforms(
                world_rot, world_t, joints, precision
            )
            return ops.skin(
                local_params.lbs_weights, skin_rot, skin_t, v_posed, precision
            )

        return jax.vmap(one)(pose, shape)

    shard_fn = _shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(param_specs, P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS, MODEL_AXIS),
    )

    @jax.jit
    def fwd(prm, pose, shape):
        return shard_fn(prm, pose, shape)[:, :n_verts]

    return lambda pose, shape: fwd(params, pose, shape)


def pallas_forward_dp(
    params: ManoParams,
    mesh: Mesh,
    block_b: int | None = None,
    interpret: bool = False,
    full: bool = False,
):
    """Data-parallel fused-kernel forward: each device runs the fully-fused
    Pallas kernel (ops/pallas_forward.py) on its local batch shard.

    Params are replicated (they are ~1.4 MB — far below the point where the
    'model'-axis vertex sharding of ``shard_map_forward`` pays for itself on
    the kernel path) and the per-shard program contains no collectives, so
    scaling is embarrassingly parallel: the batch shards over BOTH mesh
    axes (a model>1 axis would otherwise just replicate work), giving full
    n-device parallelism on the single-chip headline path. The total
    device count must divide the global batch.

    ``interpret=True`` runs the kernel in the Pallas interpreter — how the
    virtual CPU meshes in CI exercise this composition. ``full=True``
    selects the FULL-fusion kernel (Rodrigues + FK in-kernel,
    ops/pallas_forward.py:forward_verts_fused_full) per shard.
    """
    from mano_hand_tpu.models import core as _core
    from mano_hand_tpu.ops import pallas_forward

    params, true_v = _unwrap(params)
    if block_b is None:
        bb = (_core.FUSED_FULL_BEST_BLOCK_B if full
              else _core.FUSED_BEST_BLOCK_B)
    else:
        bb = block_b
    kernel = (pallas_forward.forward_verts_fused_full if full
              else pallas_forward.forward_verts_fused)

    def per_shard(prm, pose, shape):
        # Slice back to the asset's true vertex count: padded ShardedParams
        # must never leak padding rows into outputs (module invariant).
        return kernel(
            prm, pose, shape, block_b=bb, interpret=interpret
        )[:, :true_v]

    batch_spec = P((DATA_AXIS, MODEL_AXIS))
    shard_fn = _shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec),
        out_specs=batch_spec,
        # pallas_call's out_shape carries no varying-mesh-axes annotation,
        # so shard_map's vma check rejects it; the manual out_specs above
        # are the full truth for this collective-free program.
        check_vma=False,
    )

    @jax.jit
    def fwd(prm, pose, shape):
        return shard_fn(prm, pose, shape)

    return lambda pose, shape: fwd(params, pose, shape)
