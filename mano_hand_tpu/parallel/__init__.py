from mano_hand_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    batch_sharding,
    make_mesh,
    replicated,
)
from mano_hand_tpu.parallel.sharding import (
    PARAM_SPECS,
    ShardedParams,
    gspmd_forward,
    pad_verts,
    pallas_forward_dp,
    shard_map_forward,
    shard_params,
)
from mano_hand_tpu.parallel.fit import FitState, init_state, make_fit_step
from mano_hand_tpu.parallel import multihost

__all__ = [
    "multihost",
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "PARAM_SPECS",
    "ShardedParams",
    "shard_params",
    "pad_verts",
    "gspmd_forward",
    "pallas_forward_dp",
    "shard_map_forward",
    "FitState",
    "init_state",
    "make_fit_step",
]
