"""Device-mesh construction for multi-chip execution.

The reference is single-process CPU with no parallel machinery at all
(SURVEY.md §2.2); here batch ("data") and vertex ("model") axes map onto a
2-D ``jax.sharding.Mesh`` so collectives ride ICI. On one chip the mesh is
trivial and everything compiles to the single-device program.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    data: int = -1,
    model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ('data', 'model') mesh.

    ``data=-1`` absorbs all remaining devices. ICI-friendly layout comes
    from mesh_utils when the sizes allow; otherwise a plain reshape.
    """
    # Reached only after bring-up proved the backend answers: callers
    # (bench.py --mesh-scaling, tests' virtual mesh) run behind the
    # killable-subprocess probe; a mesh build is never the first
    # backend touch.  # analysis: allow(bare-devices)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data == -1:
        if n % model:
            raise ValueError(f"{n} devices not divisible by model={model}")
        data = n // model
    if data * model != n:
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {n}"
        )
    try:
        dev_array = mesh_utils.create_device_mesh((data, model), devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(data, model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def lane_devices(
    n: Optional[int] = None,
    platform: Optional[str] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> list:
    """Devices to pin per-device serving dispatch LANES to (PR 13,
    serving/lanes.py).

    ``n=None`` returns every addressable device (one lane per chip —
    the fleet default). An explicit ``n`` returns exactly ``n`` device
    handles, OVERSUBSCRIBING round-robin when fewer physical/virtual
    devices exist: lane correctness (placement, ladder failover,
    telemetry) is device-count-independent, so a 4-lane engine on a
    1-device box still exercises the whole dispatch story — only true
    parallel placement needs distinct devices (the CPU drill forces
    them via ``--xla_force_host_platform_device_count``, the same
    virtual-mesh trick the test suite runs on).
    """
    if devices is None:
        if platform:
            devices = jax.devices(platform)
        else:
            # Reached only through ServingEngine lane construction,
            # which is lazy by design (first warmup/dispatch, never the
            # constructor) — the engine's callers have already proven
            # the backend answers (tests/bench run behind the killable
            # probe), so this is never the first backend touch.
            devices = jax.devices()  # analysis: allow(bare-devices)
    devices = list(devices)
    if not devices:
        raise RuntimeError("no devices to build serving lanes on")
    if n is None:
        return devices
    if n < 1:
        raise ValueError(f"lane count must be >= 1, got {n}")
    return [devices[i % len(devices)] for i in range(int(n))]


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis batch sharding over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
