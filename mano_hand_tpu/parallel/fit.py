"""Distributed fitting: a pjit-sharded optimization step over the 2-D mesh.

Data parallelism: each device's 'data' slice carries an independent batch of
fitting problems (per-sample parameters, per-sample Adam state — no gradient
all-reduce is *required*). Tensor parallelism: the model parameters stay in
the vertex-sharded layout of ``sharding.PARAM_SPECS``, so each forward's
joint regression all-reduces over 'model'. This is the "full training step"
program the multi-chip dry-run compiles and executes.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mano_hand_tpu.fitting import objectives
from mano_hand_tpu.models import core
from mano_hand_tpu.parallel.mesh import DATA_AXIS


class FitState(NamedTuple):
    pose: jnp.ndarray       # [B, 16, 3]
    shape: jnp.ndarray      # [B, S]
    opt_state: optax.OptState


def init_state(
    params, batch: int, optimizer: optax.GradientTransformation
) -> FitState:
    from mano_hand_tpu.parallel.sharding import _unwrap

    params, _ = _unwrap(params)
    dtype = params.v_template.dtype
    pose = jnp.zeros((batch, params.n_joints, 3), dtype)
    shape = jnp.zeros((batch, params.n_shape), dtype)
    return FitState(pose, shape, optimizer.init({"pose": pose, "shape": shape}))


def make_fit_step(
    params,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    n_verts: int | None = None,
):
    """Build the jitted sharded step: (state, targets) -> (state, loss).

    ``targets`` is [B, V, 3] sharded over 'data'; ``params`` is a
    ShardedParams from ``sharding.shard_params`` (vertex-sharded over
    'model', carrying the true V) or a plain ManoParams.
    """
    from mano_hand_tpu.parallel.sharding import _unwrap

    params, true_v = _unwrap(params)
    n_verts = n_verts or true_v
    data = NamedSharding(mesh, P(DATA_AXIS))

    def loss_fn(prm, fit_params, targets):
        out = core.forward_batched(
            prm, fit_params["pose"], fit_params["shape"]
        )
        return objectives.vertex_l2(out.verts[:, :n_verts], targets)

    @functools.partial(
        jax.jit,
        in_shardings=(None, None, data),
        out_shardings=(None, None),
        donate_argnums=(1,),
    )
    def step(prm, state: FitState, targets):
        fit_params = {"pose": state.pose, "shape": state.shape}
        loss, grads = jax.value_and_grad(loss_fn, argnums=1)(
            prm, fit_params, targets
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, fit_params)
        fit_params = optax.apply_updates(fit_params, updates)
        return FitState(fit_params["pose"], fit_params["shape"], opt_state), loss

    # Params ride as a jit argument, not a captured constant (axon dispatch).
    wrapper = lambda state, targets: step(params, state, targets)  # noqa: E731
    # AOT introspection hooks (bench.py's mesh scaling table lowers the
    # step to count collectives without running it).
    wrapper.jitted = step
    wrapper.bound_params = params
    return wrapper
