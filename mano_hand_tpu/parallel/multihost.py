"""Multi-host (multi-process) execution helpers.

The reference has no distributed machinery at all (SURVEY.md §2.2); the
TPU-native equivalent of a NCCL/MPI backend is JAX's built-in runtime:
``jax.distributed`` bootstraps the process group over DCN, meshes span all
hosts' devices, and XLA inserts the collectives (the forward's only one is
the joint-regression psum, which rides ICI within a slice).

Everything here degrades to a no-op single-process setup in CI — the same
code path runs on one host with a virtual device count and on a v5e pod
slice, which is what makes it testable without a cluster.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mano_hand_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bootstrap the JAX process group; True if multi-process.

    On TPU pods all arguments come from the environment and may be omitted
    (`jax.distributed.initialize()`); pass them explicitly for CPU/GPU
    clusters. Safe to call in single-process runs: does nothing when no
    coordinator is configured and none is discoverable.
    """
    already = getattr(initialize, "_done", False)
    if already:
        return jax.process_count() > 1
    # Do NOT touch jax.process_count()/jax.devices() before deciding:
    # querying them initializes the backend, after which distributed init
    # is impossible ("must be called before any JAX computation").
    if coordinator_address is None and num_processes is None:
        try:
            # Pod environments self-describe (TPU metadata, SLURM, etc.);
            # jax raises when no cluster environment is discoverable.
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            initialize._done = True  # single host (CI, laptop)
            return False
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    initialize._done = True
    return jax.process_count() > 1


def global_mesh(
    data: Optional[int] = None,
    model: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A ('data', 'model') mesh over every device of every process.

    Defaults to all-data-parallel over the global device count. The
    'model' (tensor-parallel) axis should stay within a host/ICI domain on
    real pods — keep ``model`` a divisor of the per-host device count so
    the vertex-sharded all-reduce never crosses DCN.
    """
    # Reached only after initialize()/bring-up proved the backend
    # answers (see the jax.process_count() note above): global-mesh
    # construction is never the first backend touch, so the killable-
    # subprocess probe rule is satisfied upstream.
    # analysis: allow(bare-devices)
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % model:
            raise ValueError(f"model={model} must divide device count {n}")
        data = n // model
    if data * model != n:
        raise ValueError(f"mesh {data}x{model} != {n} devices")
    arr = np.asarray(devices).reshape(data, model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def process_local_slice(global_batch: int, mesh: Mesh) -> slice:
    """The [start, stop) rows of a global batch this process should load.

    Row-major over the 'data' axis: each process feeds its own addressable
    shard — the host-side analogue of a distributed sampler.
    """
    n_proc = jax.process_count()
    n_data = mesh.shape[DATA_AXIS]
    if global_batch % n_data:
        raise ValueError(
            f"global batch {global_batch} not divisible by the mesh's "
            f"data axis ({n_data})"
        )
    if global_batch % n_proc:
        raise ValueError(
            f"global batch {global_batch} not divisible by "
            f"{n_proc} processes"
        )
    per = global_batch // n_proc
    pid = jax.process_index()
    return slice(pid * per, (pid + 1) * per)


def global_batch_array(local_rows: np.ndarray, mesh: Mesh) -> jax.Array:
    """Assemble a data-sharded global array from per-process local rows.

    ``local_rows`` is this process's slice (see ``process_local_slice``);
    the result is a global jax.Array sharded over 'data', usable directly
    by the sharded forward/fit programs. Single-process: equivalent to
    ``jax.device_put`` with the batch sharding.
    """
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    global_shape = (
        local_rows.shape[0] * jax.process_count(),
        *local_rows.shape[1:],
    )
    return jax.make_array_from_process_local_data(
        sharding, np.ascontiguousarray(local_rows), global_shape
    )
